"""Sharded checkpoint + resharding-on-load tests (≙ the reference's
hybrid_parallel_pp_save_load.py and auto_parallel_autoconvert.py doctrine)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.checkpoint import load_sharded, save_sharded

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


class TestReshardingRoundtrip:
    def test_dp4_mp2_to_dp2_mp4(self, tmp_path):
        """The headline capability: save under one layout, load under
        another, values identical."""
        m1 = _mesh((4, 2), ("dp", "mp"))
        m2 = _mesh((2, 4), ("dp", "mp"))
        rng = np.random.RandomState(0)
        state = {
            "w": jax.device_put(
                rng.randn(16, 8).astype(np.float32),
                NamedSharding(m1, P(None, "mp"))),
            "emb": jax.device_put(
                rng.randn(32, 8).astype(np.float32),
                NamedSharding(m1, P("mp", None))),
            "opt": {"m": jax.device_put(
                rng.randn(16, 8).astype(np.float32),
                NamedSharding(m1, P("dp", "mp")))},
            "step": jnp.asarray(7, jnp.int32),
        }
        path = str(tmp_path / "ckpt")
        save_sharded(state, path)

        template = {
            "w": jax.ShapeDtypeStruct(
                (16, 8), np.float32,
                sharding=NamedSharding(m2, P(None, "mp"))),
            "emb": jax.ShapeDtypeStruct(
                (32, 8), np.float32,
                sharding=NamedSharding(m2, P("mp", None))),
            "opt": {"m": jax.ShapeDtypeStruct(
                (16, 8), np.float32,
                sharding=NamedSharding(m2, P("dp", "mp")))},
            "step": jax.ShapeDtypeStruct((), np.int32),
        }
        back = load_sharded(path, template)
        for k in ("w", "emb"):
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(state[k]))
        np.testing.assert_array_equal(np.asarray(back["opt"]["m"]),
                                      np.asarray(state["opt"]["m"]))
        assert int(back["step"]) == 7
        assert back["w"].sharding.mesh.devices.shape == (2, 4)
        assert back["w"].sharding.spec == P(None, "mp")

    def test_bfloat16_roundtrip(self, tmp_path):
        m1 = _mesh((8,), ("mp",))
        x = jax.device_put(
            jnp.asarray(np.random.RandomState(1).randn(8, 16), jnp.bfloat16),
            NamedSharding(m1, P("mp", None)))
        path = str(tmp_path / "bf16")
        save_sharded({"x": x}, path)
        back = load_sharded(path)
        np.testing.assert_array_equal(
            np.asarray(back["x"].astype(jnp.float32)),
            np.asarray(x.astype(jnp.float32)))

    def test_templateless_load_returns_numpy_tree(self, tmp_path):
        state = {"a": jnp.arange(6.0).reshape(2, 3), "n": {"b": jnp.ones(4)}}
        path = str(tmp_path / "plain")
        save_sharded(state, path)
        back = load_sharded(path)
        np.testing.assert_array_equal(back["a"], np.arange(6.0).reshape(2, 3))
        np.testing.assert_array_equal(back["n"]["b"], np.ones(4))

    def test_async_save(self, tmp_path):
        state = {"x": jnp.ones((64, 64))}
        path = str(tmp_path / "async")
        handle = save_sharded(state, path, use_async=True)
        handle.wait()
        assert handle.done()
        back = load_sharded(path)
        np.testing.assert_array_equal(back["x"], np.ones((64, 64)))


class TestTrainResume:
    def test_gpt_resumes_identical_loss(self, tmp_path):
        """Save mid-training under dp4×mp2, resume under dp2×mp4: losses on
        the continuation match the uninterrupted run exactly."""
        from paddle_tpu.framework import random as fw_random
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        def build():
            pt.seed(17)
            cfg = GPTConfig(hidden_size=64, num_layers=2, num_heads=4,
                            max_position_embeddings=128, vocab_size=512,
                            hidden_dropout=0.0, attention_dropout=0.0)
            m = GPTForCausalLM(cfg)
            m.train()
            return m

        def init_fleet(dp, mp):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp}
            fleet.init(is_collective=True, strategy=strategy)

        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, 512, (8, 32)).astype(np.int32)

        def make_step(model, opt):
            def step(p, s, ids, key):
                def loss_fn(q):
                    with fw_random.key_scope(key):
                        loss, _ = model.apply(q, ids, labels=ids)
                    return loss
                loss, grads = jax.value_and_grad(loss_fn)(p)
                p2, s2 = opt.apply_gradients(grads, p, s)
                return loss, p2, s2
            return jax.jit(step)

        # uninterrupted reference: 4 steps on dp4 x mp2
        model = build()
        init_fleet(4, 2)
        fleet.distributed_model(model)
        opt = pt.optimizer.AdamW(learning_rate=1e-3)
        params = model.state_dict()
        state = opt.init(params)
        step = make_step(model, opt)
        ids = dist.shard_batch(ids_np)
        key = jax.random.key(0)
        ref_losses = []
        for i in range(4):
            loss, params, state = step(params, state, ids,
                                       jax.random.fold_in(key, i))
            ref_losses.append(float(loss))
        # checkpoint was taken after step 2 in the resumed variant — rebuild
        dist.set_hybrid_communicate_group(None)

        model = build()
        init_fleet(4, 2)
        fleet.distributed_model(model)
        params = model.state_dict()
        state = opt.init(params)
        step = make_step(model, opt)
        ids = dist.shard_batch(ids_np)
        for i in range(2):
            loss, params, state = step(params, state, ids,
                                       jax.random.fold_in(key, i))
        path = str(tmp_path / "resume")
        save_sharded({"params": params, "opt": state}, path)
        dist.set_hybrid_communicate_group(None)

        # resume under the TRANSPOSED layout
        model = build()
        init_fleet(2, 4)
        fleet.distributed_model(model)
        params_t = model.state_dict()
        state_t = opt.init(params_t)
        template = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding),
            {"params": params_t, "opt": state_t})
        restored = load_sharded(path, template)
        params, state = restored["params"], restored["opt"]
        step = make_step(model, opt)
        ids = dist.shard_batch(ids_np)
        for i in range(2, 4):
            loss, params, state = step(params, state, ids,
                                       jax.random.fold_in(key, i))
            np.testing.assert_allclose(float(loss), ref_losses[i],
                                       rtol=2e-6, err_msg=f"step {i}")
