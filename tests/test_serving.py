"""Serving subsystem (ISSUE 6): paged-KV cache invariants, scheduler
policy under a tight block budget, ragged-vs-dense numerics, the compile
contract, the slow-consumer fault drill, and the legacy facade routing."""
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.inference import (BlockAllocator, Config, PagedKVCache,
                                  ServingEngine, create_predictor)
from paddle_tpu.inference.paged_attention import (paged_attention_pallas,
                                                  paged_attention_reference)
from paddle_tpu.inference.scheduler import (ContinuousBatchingScheduler,
                                            SequenceState, prefill_bucket)
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.compilation import CompileTracker
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.testing import faults

pytestmark = pytest.mark.serving


def tiny_model(max_pos=32):
    pt.seed(7)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=2,
                    num_heads=2, ffn_hidden_size=64,
                    max_position_embeddings=max_pos, hidden_dropout=0.0,
                    attention_dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def dense_continuation(model, prompt, max_new, eos=None):
    out = model.generate(jnp.asarray([prompt], jnp.int32),
                         max_new_tokens=max_new, temperature=0.0,
                         eos_token_id=eos)
    return np.asarray(out)[0, len(prompt):].tolist()


def assert_no_block_aliasing(cache: PagedKVCache):
    seen = {}
    for sid in cache.live_seqs():
        for b in cache.table(sid):
            assert b not in seen, \
                f"block {b} aliased by {sid} and {seen[b]}"
            seen[b] = sid


# ---------------------------------------------------------------------------
# KV block allocator
# ---------------------------------------------------------------------------
class TestBlockAllocator:
    def test_alloc_free_reuse(self):
        a = BlockAllocator(4, block_size=8)
        g1 = a.alloc(3)
        assert sorted(g1) == [0, 1, 2] and a.num_free == 1
        assert a.alloc(2) is None          # all-or-nothing
        assert a.num_free == 1             # the failed alloc took nothing
        a.free(g1[:2])
        g2 = a.alloc(3)
        assert g2 is not None and a.num_free == 0
        assert set(g2).isdisjoint({g1[2]})
        assert a.occupancy() == 1.0

    def test_double_free_rejected(self):
        a = BlockAllocator(2, block_size=4)
        g = a.alloc(1)
        a.free(g)
        with pytest.raises(Exception):
            a.free(g)

    def test_blocks_for_tokens(self):
        a = BlockAllocator(8, block_size=4)
        assert [a.blocks_for_tokens(n) for n in (0, 1, 4, 5, 8)] \
            == [0, 1, 1, 2, 2]

    def test_defrag_compacts_and_renumbers(self):
        a = BlockAllocator(8, block_size=4)
        t1 = a.alloc(2)
        t2 = a.alloc(2)
        t3 = a.alloc(2)
        a.free(t1)
        a.free(t3)
        tables = {"s2": list(t2)}
        perm = a.defrag(tables)
        assert perm is not None
        # live blocks now occupy the lowest ids and tables were rewritten
        assert sorted(tables["s2"]) == [0, 1]
        assert a.num_used == 2
        # perm maps new -> old for the page permutation
        assert [perm[n] for n in tables["s2"]] == t2 or \
            sorted(perm[:2].tolist()) == sorted(t2)
        # fresh allocs continue from the compacted prefix
        assert sorted(a.alloc(6)) == [2, 3, 4, 5, 6, 7]


# ---------------------------------------------------------------------------
# PagedKVCache
# ---------------------------------------------------------------------------
class TestPagedKVCache:
    def make(self, blocks=6, bs=4):
        return PagedKVCache(num_layers=1, num_heads=2, head_dim=4,
                            num_blocks=blocks, block_size=bs)

    def test_capacity_growth_and_slots(self):
        c = self.make()
        assert c.ensure_capacity("a", 5)       # 2 blocks
        assert len(c.table("a")) == 2
        assert c.ensure_capacity("a", 8)       # still 2
        assert len(c.table("a")) == 2
        assert c.ensure_capacity("a", 9)       # grows to 3
        t = c.table("a")
        assert c.slot("a", 0) == t[0] * 4
        assert c.slot("a", 6) == t[1] * 4 + 2
        c.free_seq("a")
        assert c.allocator.num_used == 0

    def test_no_aliasing_across_live_seqs(self):
        c = self.make(blocks=8)
        for sid, n in (("a", 9), ("b", 5), ("c", 12)):
            assert c.ensure_capacity(sid, n)
        assert_no_block_aliasing(c)
        c.free_seq("b")
        assert c.ensure_capacity("d", 8)
        assert_no_block_aliasing(c)

    def test_oom_takes_nothing(self):
        c = self.make(blocks=2)
        assert c.ensure_capacity("a", 8)       # both blocks
        assert not c.ensure_capacity("b", 5)   # needs 2, has 0
        assert c.table("b") == []
        assert c.allocator.num_used == 2

    def test_defrag_preserves_page_data(self):
        c = self.make(blocks=6, bs=4)
        c.ensure_capacity("a", 8)
        c.ensure_capacity("b", 8)
        # write a recognizable value into b's first slot
        slot_b = c.slot("b", 0)
        k, v = c._pages[0]
        c._pages[0] = (k.at[slot_b].set(7.5), v)
        c.free_seq("a")
        assert c.defrag() is True
        # b's tables were renumbered to the compact prefix; its data moved
        assert sorted(c.table("b")) == [0, 1]
        new_slot = c.slot("b", 0)
        assert float(c._pages[0][0][new_slot, 0, 0]) == 7.5
        # idempotent when already compact
        assert c.defrag() is False


# ---------------------------------------------------------------------------
# Ragged paged attention numerics
# ---------------------------------------------------------------------------
class TestPagedAttention:
    def test_pallas_matches_reference_incl_empty_rows(self):
        rng = np.random.RandomState(0)
        B, H, D, bs, nb, T = 4, 2, 8, 4, 12, 5
        q = jnp.asarray(rng.randn(B, H, D).astype(np.float32))
        kp = jnp.asarray(rng.randn(nb * bs + 1, H, D).astype(np.float32))
        vp = jnp.asarray(rng.randn(nb * bs + 1, H, D).astype(np.float32))
        tbl = jnp.asarray(rng.randint(0, nb, (B, T)), jnp.int32)
        lens = jnp.asarray([7, 0, 20, 1], jnp.int32)
        ref = paged_attention_reference(q, kp, vp, tbl, lens, bs)
        pal = paged_attention_pallas(q, kp, vp, tbl, lens, bs,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                                   atol=1e-5)
        assert float(jnp.max(jnp.abs(ref[1]))) == 0.0   # len-0 row

    def test_reference_matches_dense_gather(self):
        rng = np.random.RandomState(1)
        H, D, bs, nb = 3, 16, 4, 8
        q = jnp.asarray(rng.randn(1, H, D).astype(np.float32))
        kp = jnp.asarray(rng.randn(nb * bs + 1, H, D).astype(np.float32))
        vp = jnp.asarray(rng.randn(nb * bs + 1, H, D).astype(np.float32))
        tbl = jnp.asarray([[5, 2, 7, 0]], jnp.int32)
        ln = 11
        out = paged_attention_reference(q, kp, vp, tbl,
                                        jnp.asarray([ln], jnp.int32), bs)
        slots = (np.asarray(tbl[0])[:, None] * bs
                 + np.arange(bs)).reshape(-1)[:ln]
        k = np.asarray(kp)[slots]
        v = np.asarray(vp)[slots]
        s = np.einsum("hd,lhd->hl", np.asarray(q[0]), k) * D ** -0.5
        p = np.exp(s - s.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        o = np.einsum("hl,lhd->hd", p, v)
        np.testing.assert_allclose(np.asarray(out[0]), o, atol=1e-5)


# ---------------------------------------------------------------------------
# Scheduler policy (pure host logic against a real cache)
# ---------------------------------------------------------------------------
class TestScheduler:
    def make(self, blocks=4, bs=4, max_seqs=3, max_len=16):
        cache = PagedKVCache(num_layers=1, num_heads=1, head_dim=4,
                             num_blocks=blocks, block_size=bs)
        return cache, ContinuousBatchingScheduler(cache, max_seqs, max_len)

    @staticmethod
    def seq(rid, prompt_len=4, max_new=4):
        return SequenceState(request_id=rid,
                             prompt=list(range(1, prompt_len + 1)),
                             max_new_tokens=max_new)

    def test_admission_is_block_budgeted(self):
        cache, sch = self.make(blocks=2, bs=4, max_len=8)
        a = self.seq("a", prompt_len=5, max_new=3)   # needs both blocks
        b = self.seq("b", prompt_len=4, max_new=4)
        sch.submit(a)
        sch.submit(b)
        plan = sch.schedule()
        assert plan.kind == "prefill" and plan.seqs[0].request_id == "a"
        sch.mark_prefilled(a)
        a.output.append(9)
        a.pending = 9
        # "b" cannot be admitted while "a" holds the pool
        plan2 = sch.schedule()
        assert plan2.kind == "decode"
        assert [s.request_id for s in plan2.seqs] == ["a"]
        # finishing "a" frees the pool; "b" admits next step
        sch.complete(a, "eos")
        plan3 = sch.schedule()
        assert plan3.kind == "prefill" and plan3.seqs[0].request_id == "b"

    def test_preempt_newest_on_oom_and_requeue_front(self):
        cache, sch = self.make(blocks=3, bs=2, max_seqs=3, max_len=6)
        a, b = self.seq("a", 3, 3), self.seq("b", 2, 4)
        for s in (a, b):
            sch.submit(s)
        p = sch.schedule()                 # prefill a: 2 blocks, 1 free
        assert p.kind == "prefill" and p.seqs[0].request_id == "a"
        sch.mark_prefilled(a)
        a.output.append(5)
        a.pending = 5
        p = sch.schedule()                 # prefill b: 1 block, 0 free
        assert p.kind == "prefill" and p.seqs[0].request_id == "b"
        sch.mark_prefilled(b)
        b.output.append(6)
        b.pending = 6
        # decode: a grows into its 2nd block's spare slot; b needs a 2nd
        # block for position 2 and the pool is dry -> the NEWEST running
        # sequence (b itself) is preempted, a (the oldest) survives
        p = sch.schedule()
        assert p.kind == "decode"
        assert [s.request_id for s in p.seqs] == ["a"]
        assert [s.request_id for s in p.preempted] == ["b"]
        assert b.state == "preempted" and b.computed_len == 0
        # preempted work requeues at the FRONT, ahead of new arrivals
        c = self.seq("c", 2, 2)
        sch.submit(c)
        assert sch.waiting[0].request_id == "b"
        # b's blocks all returned; its recompute context keeps the
        # already-streamed token out (pending's KV is written on replay)
        assert b.context() == b.prompt
        assert_no_block_aliasing(cache)
        # a finishing frees space; b re-admits before c
        sch.complete(a, "eos")
        p = sch.schedule()
        assert p.kind == "prefill" and p.seqs[0].request_id == "b"

    def test_prefill_bucket_shapes(self):
        assert prefill_bucket(1, 64) == 8
        assert prefill_bucket(8, 64) == 8
        assert prefill_bucket(9, 64) == 16
        assert prefill_bucket(33, 64) == 64
        assert prefill_bucket(60, 64) == 64

    def test_submit_rejects_impossible_requests(self):
        cache, sch = self.make(blocks=2, bs=2, max_len=16)
        with pytest.raises(Exception):
            sch.submit(self.seq("x", prompt_len=10, max_new=10))  # > max_len
        with pytest.raises(Exception):
            # fits max_len but can never fit the whole pool
            sch.submit(self.seq("y", prompt_len=6, max_new=2))


# ---------------------------------------------------------------------------
# Engine end-to-end
# ---------------------------------------------------------------------------
class TestServingEngine:
    def test_ragged_decode_matches_dense_logits(self):
        model = tiny_model()
        prompts = [[1, 2, 3, 4, 5], [7, 8], [9, 10, 11, 12, 13, 14, 15]]
        max_new = 5
        dense = [dense_continuation(model, p, max_new) for p in prompts]
        eng = ServingEngine(model, max_seqs=4, kv_block_size=4,
                            capture_logits=True, registry=MetricsRegistry())
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        eng.run(max_steps=200)
        for rid, p, want in zip(rids, prompts, dense):
            r = eng.collect(rid)
            assert r["tokens"] == want, (p, r["tokens"], want)
            # logits through the paged path == dense no-cache forward
            full = p + r["tokens"]
            ref = np.asarray(model(jnp.asarray([full], jnp.int32)))[0]
            for i, row in enumerate(r["logits"]):
                np.testing.assert_allclose(
                    row, ref[len(p) - 1 + i], atol=1e-4)

    def test_tight_pool_preempts_but_stays_exact(self):
        model = tiny_model()
        prompts = [[1, 2, 3, 4], [5, 6, 7], [8, 9], [10, 11, 12, 13, 14]]
        max_new = 6
        dense = [dense_continuation(model, p, max_new) for p in prompts]
        reg = MetricsRegistry()
        # pool far too small for 4 concurrent sequences
        eng = ServingEngine(model, max_seqs=4, kv_block_size=4,
                            num_kv_blocks=5, registry=reg)
        rids = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
        while eng.has_work():
            eng.step()
            assert_no_block_aliasing(eng.cache)
        assert eng.sched.preemptions > 0
        for rid, want in zip(rids, dense):
            assert eng.collect(rid)["tokens"] == want
        # every block returned to the pool
        assert eng.cache.allocator.num_used == 0
        assert reg.counter("serve.preemptions").value > 0

    def test_one_compile_per_bucket_no_storms(self):
        model = tiny_model()
        tracker = CompileTracker(registry=MetricsRegistry())
        import paddle_tpu.observability.compilation as comp
        eng = ServingEngine(model, max_seqs=3, kv_block_size=4,
                            registry=MetricsRegistry())
        # route this engine's track_jit through a private tracker
        orig = comp.get_tracker
        comp.get_tracker = lambda: tracker
        try:
            prompts = [[1, 2], [3, 4, 5, 6, 7, 8, 9], [1, 2, 3],
                       [4, 5, 6, 7, 8, 9, 10, 11, 12]]
            eng.generate(prompts, max_new_tokens=4)
        finally:
            comp.get_tracker = orig
        names = [f for f in tracker.functions() if f.startswith("serve")]
        assert "serve_decode" in names
        assert "serve_prefill_b8" in names
        assert "serve_prefill_b16" in names
        for fn in names:
            st = tracker.stats(fn)
            assert st["traces"] == 1, (fn, st)      # one compile per shape
            assert st["retraces"] == 0 and st["storms"] == 0, (fn, st)

    def test_eos_stops_early_and_frees(self):
        model = tiny_model()
        eng = ServingEngine(model, max_seqs=2, kv_block_size=4,
                            registry=MetricsRegistry())
        # pick the model's own first greedy token as "eos" so it fires
        probe = dense_continuation(model, [1, 2, 3], 1)[0]
        rid = eng.submit([1, 2, 3], max_new_tokens=8, eos_token_id=probe)
        out = eng.collect(rid, max_steps=50)
        assert out["finish_reason"] == "eos"
        assert out["tokens"][-1] == probe and len(out["tokens"]) < 8
        assert eng.cache.allocator.num_used == 0

    @pytest.mark.faults
    def test_slow_consumer_does_not_stall_the_batch(self):
        model = tiny_model()
        eng = ServingEngine(model, max_seqs=4, kv_block_size=4,
                            registry=MetricsRegistry())
        # warm the compiles so the timed window measures scheduling only
        eng.generate([[1, 2]], max_new_tokens=2)
        delay, max_new = 0.15, 6
        got = {"slow": [], "fast": []}
        slow_cb = faults.slow_call(
            lambda rid, tok, fin: got["slow"].append(tok), delay)
        fast_cb = lambda rid, tok, fin: got["fast"].append(tok)  # noqa: E731
        t0 = time.monotonic()
        eng.submit([1, 2, 3], max_new_tokens=max_new, on_token=slow_cb)
        r_fast = eng.submit([4, 5, 6], max_new_tokens=max_new,
                            on_token=fast_cb)
        eng.run(max_steps=100)
        elapsed = time.monotonic() - t0
        # the batch finished without serializing behind the slow consumer:
        # its callbacks alone would take max_new * delay seconds
        assert elapsed < max_new * delay * 0.8, elapsed
        assert len(eng.collect(r_fast)["tokens"]) == max_new
        assert eng.drain_callbacks(timeout=max_new * delay * 3 + 5)
        assert len(got["slow"]) == max_new
        assert len(got["fast"]) == max_new

    def test_status_pages_and_load_shed(self):
        model = tiny_model()
        reg = MetricsRegistry()
        eng = ServingEngine(model, max_seqs=2, kv_block_size=4,
                            shed_queue_depth=1, registry=reg)
        from paddle_tpu.observability.monitor import StatusServer
        srv = StatusServer(registry=reg, engine=eng)
        for _ in range(2):
            eng.submit([1, 2, 3], max_new_tokens=3)
        for _ in range(3):
            eng.step()
        sz = srv.statusz()
        serving = sz["serving"]
        assert serving["ttft_ms"]["count"] >= 1
        assert serving["ttft_ms"]["p50"] > 0
        assert serving["kv_occupancy"] > 0
        code, _state = srv.healthz()
        assert code == 200
        # flood past the shed threshold -> 503
        for _ in range(4):
            eng.submit([1, 2], max_new_tokens=2)
        code, state = srv.healthz()
        assert code == 503 and state.startswith("load-shed")
        eng.run(max_steps=300)
        code, _ = srv.healthz()
        assert code == 200


# ---------------------------------------------------------------------------
# Legacy facade routing
# ---------------------------------------------------------------------------
class TestLegacyFacadeRouting:
    def test_enable_continuous_batching_routes_to_engine(self):
        model = tiny_model()
        cfg = Config()
        cfg.enable_continuous_batching(max_seqs=4, kv_block_size=4)
        cfg.set_decoder_model(model, max_new_tokens=4, eos_token_id=None,
                              pad_token_id=0)
        pred = create_predictor(cfg)
        assert type(pred).__name__ == "EnginePredictor"
        prompts = [[1, 2, 3], [4, 5, 6, 7, 8]]
        width = max(len(p) for p in prompts)
        ids = np.zeros((2, width), np.int64)
        for i, p in enumerate(prompts):
            ids[i, :len(p)] = p
        # reference call shapes: named input handle -> run -> output handle
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(ids)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        assert out.shape[0] == 2
        for i, p in enumerate(prompts):
            want = p + dense_continuation(model, p, 4)
            assert out[i, :len(want)].tolist() == want

    def test_plain_config_still_builds_plain_predictor(self, tmp_path):
        cfg = Config(str(tmp_path))
        assert not cfg.continuous_batching_enabled()
        with pytest.raises(Exception):
            # CB enabled without a decoder model is an explicit error
            cfg2 = Config()
            cfg2.enable_continuous_batching()
            create_predictor(cfg2)
