"""auto_parallel facade: ProcessMesh / shard_tensor / shard_op
(reference process_mesh.py:39, interface.py:34/:73) mapped onto
NamedSharding + with_sharding_constraint.  The annotate-then-run flow must
work end-to-end: user annotations + GSPMD propagation produce a correctly
sharded, numerically-identical program."""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


def _mesh_2x4():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                            dim_names=["x", "y"])


class TestProcessMesh:
    def test_reference_accessors(self):
        mesh = dist.ProcessMesh([[2, 4, 5], [0, 1, 3]])
        assert mesh.topology == [2, 3]
        assert mesh.processes == [2, 4, 5, 0, 1, 3]
        assert mesh.ndim == 2
        assert mesh.dim_names == ["d0", "d1"]

    def test_jax_mesh_topology(self):
        pm = _mesh_2x4()
        m = pm.jax_mesh
        assert m.axis_names == ("x", "y")
        assert dict(m.shape) == {"x": 2, "y": 4}


class TestShardTensor:
    def test_eager_placement(self):
        pm = _mesh_2x4()
        x = dist.shard_tensor(jnp.ones((8, 12)),
                              dist_attr={"process_mesh": pm,
                                         "dims_mapping": [0, -1]})
        assert x.sharding.spec == P("x", None)

    def test_nested_list_mesh(self):
        # the reference's raw nested-list process_mesh form
        x = dist.shard_tensor(
            jnp.ones((4, 8)),
            dist_attr={"process_mesh": [[0, 1, 2, 3], [4, 5, 6, 7]],
                       "dims_mapping": [0, 1]})
        assert x.sharding.spec == P("d0", "d1")

    def test_traced_constraint(self):
        pm = _mesh_2x4()

        @jax.jit
        def f(x):
            x = dist.shard_tensor(x, {"process_mesh": pm,
                                      "dims_mapping": [1, -1]})
            return (x * 2).sum()

        out = f(jnp.ones((8, 4)))
        assert float(out) == 64.0
        hlo = jax.jit(f).lower(jnp.ones((8, 4))).as_text()
        assert "sharding" in hlo

    def test_default_mesh_fallback(self):
        dist.auto_parallel.set_default_mesh(_mesh_2x4())
        try:
            x = dist.shard_tensor(jnp.ones((2, 8)),
                                  {"dims_mapping": [-1, 1]})
            assert x.sharding.spec == P(None, "y")
        finally:
            dist.auto_parallel.set_default_mesh(None)


class TestShardOp:
    def test_positional_and_identity_keys(self):
        pm = _mesh_2x4()
        x = jnp.ones((8, 6))
        y = jnp.ones((8, 6))
        dist_add = dist.shard_op(jnp.add,
                                 {"process_mesh": pm,
                                  0: {"dims_mapping": [0, -1]},
                                  1: {"dims_mapping": [0, -1]}})
        out = dist_add(x, y)
        np.testing.assert_array_equal(np.asarray(out), 2.0)

    def test_output_annotation(self):
        pm = _mesh_2x4()
        matmul = dist.shard_op(jnp.matmul,
                               {"process_mesh": pm,
                                0: {"dims_mapping": [0, -1]},
                                1: {"dims_mapping": [-1, 1]},
                                "out_dims_mappings": [[0, 1]]})
        out = matmul(jnp.ones((8, 4)), jnp.ones((4, 8)))
        assert out.sharding.spec == P("x", "y")
        np.testing.assert_array_equal(np.asarray(out), 4.0)


class TestAnnotateThenRun:
    def test_end_to_end_training_step(self):
        """The reference flow: annotate params + batch, run one jitted
        train step, GSPMD completes everything else; numerics must match
        the unannotated serial run."""
        pm = _mesh_2x4()
        R = np.random.RandomState(0)
        w1 = jnp.asarray(R.randn(16, 32), jnp.float32)
        w2 = jnp.asarray(R.randn(32, 16), jnp.float32)
        x = jnp.asarray(R.randn(8, 16), jnp.float32)
        y = jnp.asarray(R.randn(8, 16), jnp.float32)

        def loss_fn(params, xb, yb):
            h = jnp.tanh(xb @ params["w1"])
            return jnp.mean((h @ params["w2"] - yb) ** 2)

        serial = jax.grad(loss_fn)({"w1": w1, "w2": w2}, x, y)

        # annotate: batch over x, w1 column-parallel, w2 row-parallel
        params = {
            "w1": dist.shard_tensor(w1, {"process_mesh": pm,
                                         "dims_mapping": [-1, 1]}),
            "w2": dist.shard_tensor(w2, {"process_mesh": pm,
                                         "dims_mapping": [1, -1]}),
        }
        xs = dist.shard_tensor(x, {"process_mesh": pm,
                                   "dims_mapping": [0, -1]})
        ys = dist.shard_tensor(y, {"process_mesh": pm,
                                   "dims_mapping": [0, -1]})
        grads = jax.jit(jax.grad(loss_fn))(params, xs, ys)
        for k in serial:
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(serial[k]),
                                       rtol=2e-5, atol=2e-6)
        # grads inherit the param shardings (GSPMD completion)
        assert grads["w1"].sharding.spec == P(None, "y")


class TestEngine:
    """Engine (reference auto_parallel/engine.py:50): annotate-then-run
    driver — prepare compiles one SPMD step over the ProcessMesh, fit
    iterates batches, and numerics match a serial hand-written loop."""

    def _data(self, n=4, B=8):
        R = np.random.RandomState(1)
        return [(jnp.asarray(R.randn(B, 16), jnp.float32),
                 jnp.asarray(R.randint(0, 4, (B,)), jnp.int32))
                for _ in range(n)]

    def _model(self):
        import paddle_tpu as pt
        from paddle_tpu import nn
        pt.seed(7)
        return nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 4))

    def test_fit_matches_serial(self):
        import paddle_tpu as pt
        from paddle_tpu import nn, optimizer as opt
        from paddle_tpu.distributed.auto_parallel import Engine

        batches = self._data()

        # serial baseline: plain functional loop, no mesh
        model_s = self._model()
        params = model_s.trainable_variables()
        o = opt.SGD(learning_rate=0.1)
        state = o.init(params)
        serial_losses = []
        for x, y in batches:
            def loss_fn(p):
                out = model_s.apply(p, x)
                return nn.functional.cross_entropy(out, y)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params, state = o.apply_gradients(grads, params, state)
            serial_losses.append(float(loss))

        # engine on a dp x mp mesh; identical init via the same seed
        pm = dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                              dim_names=["dp", "mp"])
        model_e = self._model()
        eng = Engine(model_e, loss_fn=nn.functional.cross_entropy,
                     optimizer=opt.SGD(learning_rate=0.1), process_mesh=pm)
        hist = eng.fit(batches, epochs=1, verbose=0)
        engine_mean = hist[0]["loss"]
        np.testing.assert_allclose(engine_mean, np.mean(serial_losses),
                                   rtol=2e-5, atol=2e-6)

    def test_evaluate_predict_save_load(self, tmp_path):
        from paddle_tpu import nn, optimizer as opt
        from paddle_tpu.metric import Accuracy
        from paddle_tpu.distributed.auto_parallel import Engine

        batches = self._data()
        pm = dist.ProcessMesh(np.arange(8).reshape(8,).tolist(),
                              dim_names=["dp"])
        eng = Engine(self._model(), loss_fn=nn.functional.cross_entropy,
                     optimizer=opt.SGD(learning_rate=0.1),
                     metrics=Accuracy(), process_mesh=pm)
        eng.fit(batches, epochs=1, verbose=0)
        row = eng.evaluate(batches)
        assert "loss" in row and "acc" in row

        preds = eng.predict([x for x, _ in batches])
        assert len(preds) == len(batches)
        assert preds[0].shape == (8, 4)

        path = str(tmp_path / "engine_ckpt")
        eng.save(path)
        eng2 = Engine(self._model(), loss_fn=nn.functional.cross_entropy,
                      optimizer=opt.SGD(learning_rate=0.1), process_mesh=pm)
        eng2.prepare()
        eng2.load(path)
        p1 = eng.predict([batches[0][0]])[0]
        p2 = eng2.predict([batches[0][0]])[0]
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   rtol=1e-6, atol=1e-6)

    def test_fit_requires_optimizer_even_after_evaluate(self):
        import pytest
        from paddle_tpu import nn
        from paddle_tpu.distributed.auto_parallel import Engine
        from paddle_tpu.framework.errors import InvalidArgumentError

        batches = self._data(n=1)
        eng = Engine(self._model(), loss_fn=nn.functional.cross_entropy)
        eng.evaluate(batches)           # prepares in eval mode
        with pytest.raises((InvalidArgumentError, ValueError)):
            eng.fit(batches)

    def test_repeated_fit_returns_only_new_rows(self):
        from paddle_tpu import nn, optimizer as opt
        from paddle_tpu.distributed.auto_parallel import Engine

        batches = self._data(n=2)
        eng = Engine(self._model(), loss_fn=nn.functional.cross_entropy,
                     optimizer=opt.SGD(learning_rate=0.05))
        first = eng.fit(batches, epochs=2, verbose=0)
        second = eng.fit(batches, epochs=1, verbose=0)
        assert [r["epoch"] for r in first] == [0, 1]
        assert [r["epoch"] for r in second] == [2]
        assert len(eng._history) == 3
