"""auto_parallel facade: ProcessMesh / shard_tensor / shard_op
(reference process_mesh.py:39, interface.py:34/:73) mapped onto
NamedSharding + with_sharding_constraint.  The annotate-then-run flow must
work end-to-end: user annotations + GSPMD propagation produce a correctly
sharded, numerically-identical program."""
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet


def _mesh_2x4():
    return dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                            dim_names=["x", "y"])


class TestProcessMesh:
    def test_reference_accessors(self):
        mesh = dist.ProcessMesh([[2, 4, 5], [0, 1, 3]])
        assert mesh.topology == [2, 3]
        assert mesh.processes == [2, 4, 5, 0, 1, 3]
        assert mesh.ndim == 2
        assert mesh.dim_names == ["d0", "d1"]

    def test_jax_mesh_topology(self):
        pm = _mesh_2x4()
        m = pm.jax_mesh
        assert m.axis_names == ("x", "y")
        assert dict(m.shape) == {"x": 2, "y": 4}


class TestShardTensor:
    def test_eager_placement(self):
        pm = _mesh_2x4()
        x = dist.shard_tensor(jnp.ones((8, 12)),
                              dist_attr={"process_mesh": pm,
                                         "dims_mapping": [0, -1]})
        assert x.sharding.spec == P("x", None)

    def test_nested_list_mesh(self):
        # the reference's raw nested-list process_mesh form
        x = dist.shard_tensor(
            jnp.ones((4, 8)),
            dist_attr={"process_mesh": [[0, 1, 2, 3], [4, 5, 6, 7]],
                       "dims_mapping": [0, 1]})
        assert x.sharding.spec == P("d0", "d1")

    def test_traced_constraint(self):
        pm = _mesh_2x4()

        @jax.jit
        def f(x):
            x = dist.shard_tensor(x, {"process_mesh": pm,
                                      "dims_mapping": [1, -1]})
            return (x * 2).sum()

        out = f(jnp.ones((8, 4)))
        assert float(out) == 64.0
        hlo = jax.jit(f).lower(jnp.ones((8, 4))).as_text()
        assert "sharding" in hlo

    def test_default_mesh_fallback(self):
        dist.auto_parallel.set_default_mesh(_mesh_2x4())
        try:
            x = dist.shard_tensor(jnp.ones((2, 8)),
                                  {"dims_mapping": [-1, 1]})
            assert x.sharding.spec == P(None, "y")
        finally:
            dist.auto_parallel.set_default_mesh(None)


class TestShardOp:
    def test_positional_and_identity_keys(self):
        pm = _mesh_2x4()
        x = jnp.ones((8, 6))
        y = jnp.ones((8, 6))
        dist_add = dist.shard_op(jnp.add,
                                 {"process_mesh": pm,
                                  0: {"dims_mapping": [0, -1]},
                                  1: {"dims_mapping": [0, -1]}})
        out = dist_add(x, y)
        np.testing.assert_array_equal(np.asarray(out), 2.0)

    def test_output_annotation(self):
        pm = _mesh_2x4()
        matmul = dist.shard_op(jnp.matmul,
                               {"process_mesh": pm,
                                0: {"dims_mapping": [0, -1]},
                                1: {"dims_mapping": [-1, 1]},
                                "out_dims_mappings": [[0, 1]]})
        out = matmul(jnp.ones((8, 4)), jnp.ones((4, 8)))
        assert out.sharding.spec == P("x", "y")
        np.testing.assert_array_equal(np.asarray(out), 4.0)


class TestAnnotateThenRun:
    def test_end_to_end_training_step(self):
        """The reference flow: annotate params + batch, run one jitted
        train step, GSPMD completes everything else; numerics must match
        the unannotated serial run."""
        pm = _mesh_2x4()
        R = np.random.RandomState(0)
        w1 = jnp.asarray(R.randn(16, 32), jnp.float32)
        w2 = jnp.asarray(R.randn(32, 16), jnp.float32)
        x = jnp.asarray(R.randn(8, 16), jnp.float32)
        y = jnp.asarray(R.randn(8, 16), jnp.float32)

        def loss_fn(params, xb, yb):
            h = jnp.tanh(xb @ params["w1"])
            return jnp.mean((h @ params["w2"] - yb) ** 2)

        serial = jax.grad(loss_fn)({"w1": w1, "w2": w2}, x, y)

        # annotate: batch over x, w1 column-parallel, w2 row-parallel
        params = {
            "w1": dist.shard_tensor(w1, {"process_mesh": pm,
                                         "dims_mapping": [-1, 1]}),
            "w2": dist.shard_tensor(w2, {"process_mesh": pm,
                                         "dims_mapping": [1, -1]}),
        }
        xs = dist.shard_tensor(x, {"process_mesh": pm,
                                   "dims_mapping": [0, -1]})
        ys = dist.shard_tensor(y, {"process_mesh": pm,
                                   "dims_mapping": [0, -1]})
        grads = jax.jit(jax.grad(loss_fn))(params, xs, ys)
        for k in serial:
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(serial[k]),
                                       rtol=2e-5, atol=2e-6)
        # grads inherit the param shardings (GSPMD completion)
        assert grads["w1"].sharding.spec == P(None, "y")
