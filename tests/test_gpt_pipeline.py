"""mp×pp×dp GPT composition tests — the north-star workload's hybrid path.

Mirrors the reference's hybrid_parallel_pp_transformer.py /
hybrid_parallel_pp_save_load.py doctrine: train both ways (serial vs the
1F1B pipeline on the 8-device CPU mesh) and assert numeric equality.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.framework import random as fw_random
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPipeline

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-device CPU mesh")


def _cfg(**kw):
    base = dict(hidden_size=128, num_layers=4, num_heads=4,
                max_position_embeddings=256, vocab_size=1024,
                hidden_dropout=0.0, attention_dropout=0.0)
    base.update(kw)
    return GPTConfig(**base)


def _data(B=8, S=32, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randint(0, 1024, (B, S)), jnp.int32),
            jnp.asarray(rng.randint(0, 1024, (B, S)), jnp.int32))


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


def _init_hybrid(dp=2, mp=2, pp=2, micro=4):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": dp, "mp_degree": mp, "pp_degree": pp}
    strategy.pipeline_configs = {"accumulate_steps": micro}
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


class TestOneFOneBMatchesSerial:
    def test_loss_and_all_grads(self):
        """dp=2 × mp=2 × pp=2 1F1B == serial, loss and every grad leaf."""
        pt.seed(3)
        model = GPTForCausalLM(_cfg())
        model.train()
        params = model.state_dict()
        ids, labels = _data()
        key = jax.random.key(7)

        def serial_loss(p):
            with fw_random.key_scope(key):
                loss, _ = model.apply(p, ids, labels=labels)
            return loss

        loss_s, grads_s = jax.value_and_grad(serial_loss)(params)

        _init_hybrid()
        pipe = fleet.distributed_model(model)
        assert isinstance(pipe, GPTPipeline)
        assert pipe.num_stages == 2 and pipe.num_microbatches == 4
        state = pipe.place_state(pipe.split_state(params))
        qkv = state["stacked"]["attn.qkv_proj.weight"]
        assert qkv.sharding.spec == P("pp", None, None, "mp"), qkv.sharding

        loss_p, grads_p = jax.jit(pipe.loss_and_grads)(
            state, dist.shard_batch(ids), dist.shard_batch(labels), key)
        np.testing.assert_allclose(float(loss_p), float(loss_s), rtol=2e-5)

        merged = pipe.merge_state(grads_p)
        assert set(merged) == set(grads_s)
        for k in grads_s:
            np.testing.assert_allclose(
                np.asarray(merged[k]), np.asarray(grads_s[k]),
                rtol=5e-4, atol=5e-5, err_msg=k)

    def test_state_split_merge_roundtrip(self):
        pt.seed(1)
        model = GPTForCausalLM(_cfg())
        params = model.state_dict()
        pipe = GPTPipeline(model, num_stages=2, num_microbatches=4)
        back = pipe.merge_state(pipe.split_state(params))
        assert set(back) == set(params)
        for k in params:
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(params[k]))


class TestPipelineTrainBatch:
    def test_loss_decreases_with_optimizer(self):
        pt.seed(5)
        model = GPTForCausalLM(_cfg())
        model.train()
        _init_hybrid()
        pipe = fleet.distributed_model(model)
        state = pipe.place_state(pipe.split_state(model.state_dict()))
        opt = fleet.distributed_optimizer(pt.optimizer.AdamW(
            learning_rate=1e-3,
            grad_clip=pt.optimizer.ClipGradByGlobalNorm(1.0)))
        opt_state = opt.init(state)
        ids, labels = _data()
        ids, labels = dist.shard_batch(ids), dist.shard_batch(labels)

        import functools
        jitted = jax.jit(functools.partial(pipe.train_batch, opt=opt))
        losses = []
        key = jax.random.key(0)
        for i in range(5):
            loss, state, opt_state = jitted(
                state=state, opt_state=opt_state, input_ids=ids,
                labels=labels, key=jax.random.fold_in(key, i))
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses), losses
        assert losses[-1] < losses[0], losses

    def test_dropout_deterministic_per_key(self):
        """Same step key → identical loss; different key → different loss
        (the per-(micro-batch, layer) fold keeps masks deterministic, the
        counter-based Philox analog)."""
        pt.seed(9)
        model = GPTForCausalLM(_cfg(hidden_dropout=0.1,
                                    attention_dropout=0.0))
        model.train()
        _init_hybrid()
        pipe = fleet.distributed_model(model)
        state = pipe.place_state(pipe.split_state(model.state_dict()))
        ids, labels = _data()
        ids, labels = dist.shard_batch(ids), dist.shard_batch(labels)
        f = jax.jit(pipe.loss_and_grads)
        l1, _ = f(state, ids, labels, jax.random.key(1))
        l1b, _ = f(state, ids, labels, jax.random.key(1))
        l2, _ = f(state, ids, labels, jax.random.key(2))
        assert float(l1) == float(l1b)
        assert float(l1) != float(l2)
