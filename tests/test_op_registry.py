"""Registry-driven OpTest sweep (≙ the reference's api.yaml → OpTest
pipeline): every registered op is checked against its numpy reference and,
where declared, analytic-vs-numeric gradients — one parametrized test per
entry, so adding an op to the registry automatically adds its tests."""
import numpy as np
import pytest

from paddle_tpu.ops.spec import registry
from op_test import check_grad, check_output

_SPECS = registry()
_IDS = [s.name for s in _SPECS]


@pytest.mark.parametrize("spec", _SPECS, ids=_IDS)
def test_op_output_matches_reference(spec):
    rng = np.random.RandomState(0)
    args = spec.sample(rng)
    check_output(spec.fn, spec.ref, args, rtol=spec.rtol, atol=spec.atol)


@pytest.mark.parametrize(
    "spec", [s for s in _SPECS if s.grad_wrt],
    ids=[s.name for s in _SPECS if s.grad_wrt])
def test_op_grad_matches_numeric(spec):
    rng = np.random.RandomState(1)
    args = spec.sample(rng)
    check_grad(spec.fn, args, wrt=spec.grad_wrt, rtol=spec.grad_rtol,
               atol=spec.grad_atol)


def test_registry_nonempty_and_unique():
    names = [s.name for s in _SPECS]
    assert len(names) >= 40
    assert len(set(names)) == len(names)
