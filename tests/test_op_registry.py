"""Registry-driven OpTest sweep (≙ the reference's api.yaml → OpTest
pipeline): every registered op is checked against its numpy reference and,
where declared, analytic-vs-numeric gradients — one parametrized test per
entry, so adding an op to the registry automatically adds its tests."""
import numpy as np
import pytest

from paddle_tpu.ops.spec import registry
from op_test import check_grad, check_output

_SPECS = registry()
_IDS = [s.name for s in _SPECS]


@pytest.mark.parametrize("spec", _SPECS, ids=_IDS)
def test_op_output_matches_reference(spec):
    rng = np.random.RandomState(0)
    args = spec.sample(rng)
    check_output(spec.fn, spec.ref, args, rtol=spec.rtol, atol=spec.atol)


@pytest.mark.parametrize(
    "spec", [s for s in _SPECS if s.grad_wrt],
    ids=[s.name for s in _SPECS if s.grad_wrt])
def test_op_grad_matches_numeric(spec):
    rng = np.random.RandomState(1)
    args = spec.sample(rng)
    check_grad(spec.fn, args, wrt=spec.grad_wrt, rtol=spec.grad_rtol,
               atol=spec.grad_atol)


def _all_float_sample(spec):
    if not spec.bf16:   # declared dtype-limited (no bf16 kernel exists)
        return False
    args = spec.sample(np.random.RandomState(2))
    return all(np.issubdtype(np.asarray(a).dtype, np.floating)
               for a in args)


_BF16_SPECS = [s for s in _SPECS if _all_float_sample(s)]


@pytest.mark.parametrize("spec", _BF16_SPECS,
                         ids=[s.name for s in _BF16_SPECS])
def test_op_bf16_close_to_f32(spec):
    """bf16 dtype sweep (the TPU compute dtype): every float op must
    run in bf16 and stay within bf16 rounding of its f32 result —
    the reference OpTest's multi-dtype sweep, bf16-first."""
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    args = spec.sample(rng)
    f32 = np.asarray(spec.fn(*args), np.float32)
    bf16_args = [jnp.asarray(a, jnp.bfloat16) for a in args]
    try:
        out = np.asarray(spec.fn(*bf16_args), np.float32)
    except (NotImplementedError, KeyError):
        # LAPACK-backed factorizations are f32/f64-only — same dtype
        # support as the reference's decomposition kernels
        pytest.skip(f"{spec.name} has no bf16 kernel")
    scale = max(1.0, float(np.max(np.abs(f32))))
    assert np.max(np.abs(out - f32)) / scale < 0.1, (
        f"{spec.name}: bf16 deviates "
        f"{np.max(np.abs(out - f32)) / scale:.4f} from f32")


def test_registry_nonempty_and_unique():
    names = [s.name for s in _SPECS]
    assert len(names) >= 40
    assert len(set(names)) == len(names)
