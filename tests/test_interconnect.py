"""Interconnect microscope tests (ISSUE 20): the ICI spec table, the
algorithm-aware cost model, the sub-budget sum invariant, the synthetic
drill, the schema v3 round-trip, and the doctor's comm_budget verdict.

Pinned math doctrine (mirrors test_roofline): the cost-model factors
and modeled wire times are asserted against hand-computed figures, so
a silent change to the model is a test failure, not a drifting
dashboard.
"""
import json
import os

import pytest

from paddle_tpu.bench import ledger, schema
from paddle_tpu.observability import interconnect as ic
from paddle_tpu.observability import doctor
from paddle_tpu.observability.registry import split_labels


# -- ICI spec table ---------------------------------------------------------
class TestIciSpec:
    def test_known_generations(self):
        for gen in ("v2", "v3", "v4", "v5e", "v5p", "v6e"):
            spec = ic.ici_spec(f"TPU {gen}")
            assert spec["known"] is True
            assert spec["gen"] == gen
            assert spec["ici_gbps"] == ic.ICI_SPECS[gen]["ici_gbps"]
            assert spec["links"] == ic.ICI_SPECS[gen]["links"]

    def test_v4_figures(self):
        spec = ic.ici_spec("TPU v4")
        assert spec["ici_gbps"] == 2400.0
        assert spec["links"] == 6
        assert spec["topology"] == "3d_torus"

    def test_unknown_degrades_honestly(self, monkeypatch):
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        spec = ic.ici_spec("cpu")
        assert spec["known"] is False
        assert spec["gen"] is None
        # nominal figures still present so the math runs — but callers
        # must gate on known before trusting it
        assert spec["ici_gbps"] == ic.ICI_SPECS["v5e"]["ici_gbps"]

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
        spec = ic.ici_spec("some-future-chip")
        assert spec["known"] is True
        assert spec["gen"] == "v5p"
        assert spec["ici_gbps"] == 4800.0


# -- cost model -------------------------------------------------------------
class TestWireFactor:
    def test_ring_all_reduce(self):
        # 2(n-1)/n: reduce-scatter + all-gather rings
        assert ic.wire_factor("all_reduce", 8) == pytest.approx(2 * 7 / 8)
        assert ic.wire_factor("sync_gradients", 4) == pytest.approx(1.5)

    def test_gather_scatter_family(self):
        for op in ("all_gather", "reduce_scatter", "broadcast", "reduce",
                   "scatter"):
            assert ic.wire_factor(op, 8) == pytest.approx(7 / 8), op

    def test_all_to_all_bisection_penalty(self):
        # (n-1)/n for small groups, × n/4 once the torus bisection binds
        assert ic.wire_factor("all_to_all", 4) == pytest.approx(3 / 4)
        assert ic.wire_factor("all_to_all", 8) == pytest.approx(
            (7 / 8) * 2.0)
        assert ic.wire_factor("ragged_all_to_all", 16) == pytest.approx(
            (15 / 16) * 4.0)

    def test_permute_and_free_ops(self):
        assert ic.wire_factor("send_recv_permute", 8) == 1.0
        assert ic.wire_factor("ppermute", 2) == 1.0
        assert ic.wire_factor("split", 8) == 0.0
        assert ic.wire_factor("barrier", 8) == 0.0

    def test_single_rank_ships_nothing(self):
        assert ic.wire_factor("all_reduce", 1) == 0.0
        assert ic.wire_factor("all_reduce", 0) == 0.0
        assert ic.wire_factor("all_reduce", None) == 0.0

    def test_unknown_op_crosses_once(self):
        assert ic.wire_factor("mystery_collective", 8) == 1.0


class TestModeledWireTime:
    def test_v4_all_gather_pinned(self):
        # v4: 2400 Gbps / 6 links / 8 = 50 GB/s per link; the
        # bidirectional ring uses two links -> 100 GB/s.  1 GB payload
        # all-gathered over 8 ranks ships 0.875 GB -> 8.75 ms.
        spec = ic.ici_spec("TPU v4")
        t = ic.modeled_wire_time_ms("all_gather", 1e9, 8, spec)
        assert t == pytest.approx(8.75)

    def test_v5e_all_reduce_pinned(self):
        # v5e: 1600/4/8 = 50 GB/s per link, ring 100 GB/s; all_reduce
        # over 4 ranks ships 1.5x the payload: 1 MB -> 0.015 ms
        spec = ic.ici_spec("TPU v5e")
        t = ic.modeled_wire_time_ms("all_reduce", 1e6, 4, spec)
        assert t == pytest.approx(1e6 * 1.5 / 100e9 * 1e3)

    def test_zero_payload_or_solo(self):
        spec = ic.ici_spec("TPU v4")
        assert ic.modeled_wire_time_ms("all_reduce", 0, 8, spec) == 0.0
        assert ic.modeled_wire_time_ms("all_reduce", 1e9, 1, spec) == 0.0


# -- sub-budget assembly ----------------------------------------------------
def _per_op(**over):
    rec = {"op": "all_reduce", "axis": "dp", "participants": 8,
           "calls": 1.0, "ms": 2.0, "payload_bytes": 1e6}
    rec.update(over)
    return rec


class TestBuildBlock:
    def test_sum_invariant_by_construction(self):
        blk = ic.build_block(
            10.0, [_per_op(), _per_op(op="all_gather", ms=3.0)],
            spec=ic.ici_spec("TPU v4"))
        total = sum(e["measured_ms"] for e in blk["entries"])
        assert total == pytest.approx(blk["comm_bucket_ms"], abs=1e-6)
        assert ic.unattributed_ms(blk) == pytest.approx(5.0)
        assert ic.attributed_total_ms(blk) == pytest.approx(5.0)

    def test_negative_unattributed_still_sums(self):
        # nested observation (reduce wraps all_reduce) can attribute
        # MORE than the bucket — the signed remainder absorbs it
        blk = ic.build_block(1.0, [_per_op(ms=2.0)],
                             spec=ic.ici_spec("TPU v4"))
        assert ic.unattributed_ms(blk) == pytest.approx(-1.0)
        total = sum(e["measured_ms"] for e in blk["entries"])
        assert total == pytest.approx(blk["comm_bucket_ms"], abs=1e-6)

    def test_efficiency_is_modeled_over_measured(self):
        spec = ic.ici_spec("TPU v4")
        blk = ic.build_block(10.0, [_per_op()], spec=spec)
        e = blk["entries"][0]
        want = ic.modeled_wire_time_ms("all_reduce", 1e6, 8, spec)
        assert e["modeled_ms"] == pytest.approx(want, abs=1e-6)
        assert e["efficiency"] == pytest.approx(want / 2.0, abs=1e-4)
        assert e["wire_bytes"] == pytest.approx(1e6 * 2 * 7 / 8)

    def test_unknown_device_has_no_model(self, monkeypatch):
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
        blk = ic.build_block(10.0, [_per_op()], spec=ic.ici_spec("cpu"))
        e = blk["entries"][0]
        assert blk["device"]["known"] is False
        # measured attribution still happens; the model refuses to guess
        assert e["measured_ms"] == pytest.approx(2.0)
        assert e["modeled_ms"] is None
        assert e["efficiency"] is None
        assert blk["modeled_ms_total"] is None
        assert blk["hlo_modeled_ms"] is None
        assert blk["overlapped_ms"] is None

    def test_hlo_ops_and_overlap_estimate(self):
        spec = ic.ici_spec("TPU v4")
        hlo = {"all-reduce": {"count": 2, "bytes": 1e9,
                              "participants": 8}}
        blk = ic.build_block(1.0, [_per_op()], hlo_comm=hlo, spec=spec)
        rec = blk["hlo_ops"]["all-reduce"]
        want = ic.modeled_wire_time_ms("all_reduce", 1e9, 8, spec)
        assert rec["modeled_ms"] == pytest.approx(want, abs=1e-5)
        assert blk["hlo_modeled_ms"] == pytest.approx(want, abs=1e-5)
        # exposed = the whole comm bucket; anything modeled beyond it is
        # what XLA's schedule hid behind compute
        assert blk["exposed_ms"] == pytest.approx(1.0)
        assert blk["overlapped_ms"] == pytest.approx(
            max(0.0, want - 1.0), abs=1e-5)

    def test_hlo_default_participants_backfill(self):
        spec = ic.ici_spec("TPU v4")
        hlo = {"all-gather": {"count": 1, "bytes": 1e6,
                              "participants": None}}
        blk = ic.build_block(1.0, None, hlo_comm=hlo, spec=spec,
                             default_participants=4)
        assert blk["hlo_ops"]["all-gather"]["participants"] == 4

    def test_degraded_block(self):
        blk = ic.degraded_block(5.0, reason="test reason",
                                spec=ic.ici_spec("TPU v4"))
        assert blk["degraded"] == "test reason"
        assert ic.attributed_total_ms(blk) == 0.0
        assert ic.unattributed_ms(blk) == pytest.approx(5.0)


class TestInflationDrill:
    def test_injects_named_op_axis(self, monkeypatch):
        monkeypatch.setenv(ic.INFLATE_ENV, "all_to_all:ep:0.8")
        blk = ic.build_block(10.0, [_per_op()],
                             spec=ic.ici_spec("TPU v4"))
        assert blk["injected"] == {"op": "all_to_all", "axis": "ep",
                                   "frac": 0.8}
        named = next(e for e in blk["entries"]
                     if e["op"] == "all_to_all")
        assert named["axis"] == "ep"
        assert named["measured_ms"] == pytest.approx(8.0)
        # the invariant survives the drill
        total = sum(e["measured_ms"] for e in blk["entries"])
        assert total == pytest.approx(10.0, abs=1e-6)

    def test_rescales_existing_entries(self, monkeypatch):
        monkeypatch.setenv(ic.INFLATE_ENV, "all_reduce:dp:0.5")
        blk = ic.build_block(
            10.0, [_per_op(ms=2.0), _per_op(op="all_gather", ms=2.0)],
            spec=ic.ici_spec("TPU v4"))
        named = next(e for e in blk["entries"]
                     if e["op"] == "all_reduce")
        other = next(e for e in blk["entries"]
                     if e["op"] == "all_gather")
        assert named["measured_ms"] == pytest.approx(5.0)
        # the other attributed entry absorbs the rest of the bucket
        assert other["measured_ms"] == pytest.approx(5.0)
        assert ic.unattributed_ms(blk) == pytest.approx(0.0, abs=1e-6)

    def test_bad_spec_is_ignored(self, monkeypatch):
        for bad in ("all_to_all:ep", "all_to_all", "a:b:notafloat", ":"):
            monkeypatch.setenv(ic.INFLATE_ENV, bad)
            blk = ic.build_block(10.0, [_per_op()],
                                 spec=ic.ici_spec("TPU v4"))
            assert blk["injected"] is None, bad

    def test_zero_bucket_skips_drill(self, monkeypatch):
        monkeypatch.setenv(ic.INFLATE_ENV, "all_to_all:ep:0.8")
        blk = ic.build_block(0.0, None, spec=ic.ici_spec("TPU v4"))
        assert blk["injected"] is None


# -- schema v3 round-trip ---------------------------------------------------
def _mk_row(interconnect=None, phases=None):
    return schema.new_row(
        "gpt_pretrain_fused", "smoke",
        step_times_ms=[10.0] * 8,
        phases_ms=phases or {"data": 1.0, "compute": 7.0,
                             "readback": 1.0, "collective": 1.0},
        interconnect=interconnect)


class TestSchemaV3:
    def test_version_and_metrics(self):
        assert schema.SCHEMA_VERSION == 3
        assert 3 in schema.KNOWN_SCHEMA_VERSIONS
        assert schema.COMM_METRICS == ("comm_modeled_ms",
                                       "comm_overlapped_ms",
                                       "comm_unattributed_ms")
        for m in schema.COMM_METRICS:
            assert m in schema.METRICS

    def test_new_row_synthesizes_degraded_block(self):
        row = _mk_row()
        blk = row["interconnect"]
        assert blk is not None and blk["degraded"]
        assert schema.validate_row(row) == []
        # the synthesized block's bucket tracks the roofline comm bucket
        rl_comm = row["roofline"]["buckets_ms"]["comm"]
        assert blk["comm_bucket_ms"] == pytest.approx(rl_comm, abs=1e-6)

    def test_explicit_block_round_trips(self):
        row = _mk_row()
        rl_comm = float(row["roofline"]["buckets_ms"]["comm"])
        blk = ic.build_block(rl_comm, [_per_op(ms=rl_comm / 2)],
                             spec=ic.ici_spec("TPU v4"))
        row2 = _mk_row(interconnect=blk)
        assert schema.validate_row(row2) == []

    def test_validate_catches_sum_violation(self):
        row = _mk_row()
        row["interconnect"]["entries"][0]["measured_ms"] += 5.0
        errs = schema.validate_row(row)
        assert any("sum" in e or "bucket" in e for e in errs), errs

    def test_validate_catches_bucket_mismatch(self):
        row = _mk_row()
        row["interconnect"]["comm_bucket_ms"] += 7.0
        for e in row["interconnect"]["entries"]:
            if e["op"] == ic.UNATTRIBUTED:
                e["measured_ms"] += 7.0
        errs = schema.validate_row(row)
        assert any("roofline" in e for e in errs), errs

    def test_metric_value_reads_comm_axes(self):
        row = _mk_row()
        blk = row["interconnect"]
        assert (schema.metric_value(row, "comm_unattributed_ms")
                == blk["unattributed_ms"])
        assert (schema.metric_value(row, "comm_modeled_ms")
                == blk["modeled_ms_total"])
        assert (schema.metric_value(row, "comm_overlapped_ms")
                == blk["overlapped_ms"])


# -- CLI reconciliation gate ------------------------------------------------
class TestCLI:
    def _ledger(self, tmp_path, rows):
        path = str(tmp_path / "ledger.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        return path

    def test_ok_on_valid_rows(self, tmp_path, capsys):
        path = self._ledger(tmp_path, [_mk_row()])
        rc = ic.main(["--ledger", path, "--mode", "smoke"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "reconciliation OK" in out

    def test_fails_on_sum_violation(self, tmp_path, capsys):
        row = _mk_row()
        row["interconnect"]["entries"][0]["measured_ms"] += 5.0
        path = self._ledger(tmp_path, [row])
        rc = ic.main(["--ledger", path, "--mode", "smoke"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "RECONCILIATION FAILURES" in out

    def test_fails_on_missing_block(self, tmp_path, capsys):
        row = _mk_row()
        row.pop("interconnect")
        path = self._ledger(tmp_path, [row])
        rc = ic.main(["--ledger", path, "--mode", "smoke"])
        assert rc == 1
        assert "no interconnect block" in capsys.readouterr().out

    def test_unattributed_bound(self, tmp_path, capsys):
        path = self._ledger(tmp_path, [_mk_row()])
        # the synthesized degraded block is 100% unattributed — a tight
        # bound must flag it, the default (1.0) must not
        rc = ic.main(["--ledger", path, "--mode", "smoke",
                      "--max-unattributed-frac", "0.5"])
        assert rc == 1
        assert "unattributed" in capsys.readouterr().out


# -- doctor verdict ---------------------------------------------------------
def _bench_rec(ic_block, measured=10.0, scenario="gpt_pretrain_fused"):
    return {"kind": "bench.row", "scenario": scenario, "ts": 1.0,
            "roofline": {"measured_step_ms": measured},
            "interconnect": {
                "comm_bucket_ms": ic_block["comm_bucket_ms"],
                "unattributed_ms": ic_block["unattributed_ms"],
                "overlapped_ms": ic_block["overlapped_ms"],
                "entries": ic_block["entries"],
                "injected": ic_block["injected"],
                "degraded": bool(ic_block.get("degraded"))}}


class TestDoctorCommBudget:
    def test_names_dominant_op_and_axis(self):
        blk = ic.build_block(5.0, [_per_op(ms=4.0)],
                             spec=ic.ici_spec("TPU v4"))
        (f,) = doctor.check_comm_budget({0: [_bench_rec(blk)]})
        assert f["kind"] == "comm_budget"
        assert f["data"]["op"] == "all_reduce"
        assert f["data"]["axis"] == "dp"
        assert f["data"]["efficiency"] is not None
        assert "all_reduce[axis=dp]" in f["title"]

    def test_quiet_below_threshold(self):
        blk = ic.build_block(1.0, [_per_op(ms=0.5)],
                             spec=ic.ici_spec("TPU v4"))
        assert doctor.check_comm_budget({0: [_bench_rec(blk)]}) == []

    def test_honest_when_unattributed_dominates(self):
        blk = ic.degraded_block(5.0, spec=ic.ici_spec("TPU v4"))
        (f,) = doctor.check_comm_budget({0: [_bench_rec(blk)]})
        assert f["data"]["op"] == ic.UNATTRIBUTED
        assert f["data"]["axis"] is None
        assert any("lower bound" in ev for ev in f["evidence"])

    def test_injected_fires_and_is_flagged(self, monkeypatch):
        monkeypatch.setenv(ic.INFLATE_ENV, "all_to_all:ep:0.8")
        blk = ic.build_block(1.0, [_per_op(ms=0.2)],
                             spec=ic.ici_spec("TPU v4"))
        # share is only 10% of the step — the injected marker alone
        # must make the drill verdict fire, flagged as staged
        (f,) = doctor.check_comm_budget({0: [_bench_rec(blk)]})
        assert f["data"]["op"] == "all_to_all"
        assert f["data"]["axis"] == "ep"
        assert any("drill" in ev for ev in f["evidence"])

    def test_newest_row_wins(self):
        old = ic.build_block(5.0, [_per_op(ms=4.0)],
                             spec=ic.ici_spec("TPU v4"))
        new = ic.build_block(5.0, [_per_op(op="all_gather", axis="mp",
                                           ms=4.0)],
                             spec=ic.ici_spec("TPU v4"))
        r_old = _bench_rec(old)
        r_old["ts"] = 1.0
        r_new = _bench_rec(new)
        r_new["ts"] = 2.0
        (f,) = doctor.check_comm_budget({0: [r_old, r_new]})
        assert f["data"]["op"] == "all_gather"


# -- label plumbing ---------------------------------------------------------
class TestSplitLabels:
    def test_labeled(self):
        base, labels = split_labels("collective.all_reduce.ms[axis=dp,n=8]")
        assert base == "collective.all_reduce.ms"
        assert labels == {"axis": "dp", "n": "8"}

    def test_unlabeled_passthrough(self):
        assert split_labels("collective.all_reduce.ms") == (
            "collective.all_reduce.ms", {})

    def test_comm_bound_reads_both_name_forms(self):
        def window(name):
            snap = {name: {"type": "histogram", "count": 8, "sum": 40.0,
                           "p50": 5.0, "p99": 5.0}}
            steps = [{"kind": "step", "step_time_ms": 10.0}
                     for _ in range(8)]
            return {0: steps + [{"kind": "metrics.snapshot",
                                 "snapshot": snap}]}
        for name in ("collective.all_reduce.ms",
                     "collective.all_reduce.ms[axis=dp,n=8]"):
            findings = doctor.check_comm_bound(window(name), frac=0.25)
            assert len(findings) == 1, name
            assert findings[0]["data"]["op"] == "all_reduce"

    def test_comm_bound_no_double_count_across_labels(self):
        # the same op on two axes: two family members, one op verdict
        snap = {
            "collective.all_reduce.ms[axis=dp,n=8]":
                {"type": "histogram", "count": 8, "sum": 40.0,
                 "p50": 5.0, "p99": 5.0},
            "collective.all_reduce.ms[axis=mp,n=2]":
                {"type": "histogram", "count": 8, "sum": 48.0,
                 "p50": 6.0, "p99": 6.0},
        }
        steps = [{"kind": "step", "step_time_ms": 10.0}
                 for _ in range(8)]
        workers = {0: steps + [{"kind": "metrics.snapshot",
                                "snapshot": snap}]}
        findings = doctor.check_comm_bound(workers, frac=0.25)
        assert len(findings) == 1
        f = findings[0]
        # worst family member wins; its axis is named
        assert f["data"]["p50_ms"] == 6.0
        assert f["data"]["axis"] == "mp"
