"""Run-doctor tests (ISSUE 4): compile/retrace tracking (storm detection
naming the offending argument), HBM watermark sampling + OOM postmortem,
cross-worker straggler attribution on synthetic skewed streams, schema-
version drop accounting, Prometheus label escaping, and the e2e
acceptance drill — a scripted degraded run (shape churn + an injected
slow worker) whose ``diagnosis.json`` names the retrace-causing argument
and the straggler worker index."""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.observability import aggregate as agg_mod
from paddle_tpu.observability import compilation, doctor
from paddle_tpu.observability import memory as mem_mod
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.observability.sinks import PrometheusTextfile

pytestmark = pytest.mark.telemetry


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(record)

    def flush(self):
        pass

    def close(self):
        pass


def _tracked_registry():
    reg = MetricsRegistry()
    sink = _ListSink()
    reg.add_sink(sink)
    return reg, sink


# -- compile/retrace tracking ----------------------------------------------
class TestCompileTracking:
    def test_hit_miss_classification(self):
        reg, sink = _tracked_registry()
        tr = compilation.CompileTracker(registry=reg)
        f = compilation.track_jit(jax.jit(lambda x: x + 1), name="f",
                                  arg_names=("x",), tracker=tr)
        f(jnp.zeros((2, 4)))
        f(jnp.zeros((2, 4)))            # same signature → cache hit
        f(jnp.zeros((2, 5)))            # new shape → retrace
        stats = tr.stats("f")
        assert stats == {"calls": 3, "traces": 2, "retraces": 1,
                         "storms": 0}
        compiles = [r for r in sink.records if r["kind"] == "compile"]
        assert len(compiles) == 2
        assert compiles[0]["retrace"] is False
        assert compiles[1]["retrace"] is True
        assert compiles[1]["changed"] == [
            {"arg": "x", "detail": "float32[2,4] -> float32[2,5]"}]
        assert compiles[1]["wall_ms"] > 0

    def test_retrace_storm_names_offending_argument(self):
        """Force shape churn on ONE argument and assert the storm record
        names it (the ISSUE 4 satellite contract)."""
        reg, sink = _tracked_registry()
        tr = compilation.CompileTracker(registry=reg, storm_threshold=3,
                                        storm_window=16)
        f = compilation.track_jit(
            jax.jit(lambda w, seq: (w * seq).sum()), name="step",
            arg_names=("weights", "seq"), tracker=tr)
        w = jnp.ones((4,))
        for n in (8, 9, 10, 11):        # seq churns, weights stable
            f(w, jnp.zeros((n, 4)))
        storms = [r for r in sink.records
                  if r["kind"] == "compile.retrace_storm"]
        assert len(storms) == 1
        assert storms[0]["culprit"] == "seq"
        assert storms[0]["function"] == "step"
        assert storms[0]["retraces"] >= 3
        assert reg.counter("compile.storms[fn=step]").value == 1

    def test_storm_rearms_after_firing(self):
        reg, sink = _tracked_registry()
        tr = compilation.CompileTracker(registry=reg, storm_threshold=2,
                                        storm_window=8)
        f = compilation.track_jit(jax.jit(lambda x: x), name="g",
                                  arg_names=("x",), tracker=tr)
        for n in range(1, 6):
            f(jnp.zeros((n,)))
        storms = [r for r in sink.records
                  if r["kind"] == "compile.retrace_storm"]
        assert len(storms) == 2         # 4 retraces, threshold 2, re-armed

    def test_structure_change_named(self):
        prev = [compilation.arg_signature({"a": 1})]
        cur = [compilation.arg_signature({"a": 1, "b": 2})]
        changed = compilation.diff_signatures(prev, cur, ["state"])
        assert changed == [{"arg": "state", "detail": "structure changed"}]

    def test_tracking_never_breaks_the_call(self):
        tr = compilation.CompileTracker(registry=MetricsRegistry())
        f = compilation.track_jit(lambda x: x * 2, name="plain",
                                  tracker=tr)
        assert f(21) == 42              # non-jitted callables work too

    def test_hapi_prepare_is_tracked(self):
        compilation.reset_tracker()
        net = pt.nn.Sequential(pt.nn.Linear(8, 4))
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3),
                      loss=pt.nn.CrossEntropyLoss())
        x = np.random.randn(4, 8).astype("float32")
        y = np.random.randint(0, 4, (4,)).astype("int64")
        model.train_batch([x], [y])
        assert compilation.get_tracker().stats(
            "hapi.train_step")["traces"] == 1
        model.train_batch([x], [y])     # same shapes → no new trace
        assert compilation.get_tracker().stats(
            "hapi.train_step")["traces"] == 1


# -- HBM watermarks ---------------------------------------------------------
class TestMemorySampler:
    @staticmethod
    def _stats_seq(rows):
        it = iter(rows)
        return lambda: next(it)

    def test_cadence_and_deltas(self):
        reg, sink = _tracked_registry()
        rows = [{"tpu:0": {"bytes_in_use": 100 * (i + 1),
                           "peak_bytes_in_use": 150 * (i + 1),
                           "largest_alloc_size": 64,
                           "bytes_limit": 1000}} for i in range(4)]
        ms = mem_mod.MemorySampler(every=2, stats_fn=self._stats_seq(rows),
                                   registry=reg)
        for step in range(8):
            ms.sample(step)
        recs = [r for r in sink.records if r["kind"] == "memory"]
        assert len(recs) == 4           # every=2 over 8 steps
        assert recs[0]["devices"]["tpu:0"]["in_use_delta"] == 0
        assert recs[1]["devices"]["tpu:0"]["in_use_delta"] == 100
        assert recs[1]["devices"]["tpu:0"]["largest_alloc_delta"] == 0
        assert recs[1]["devices"]["tpu:0"]["utilization"] == 0.2
        assert reg.gauge(
            "memory.bytes_in_use[device=tpu:0]").value == 400

    def test_cpu_backend_is_silent(self):
        reg, sink = _tracked_registry()
        ms = mem_mod.MemorySampler(every=1, registry=reg)
        assert ms.sample(0) is None     # CPU: no allocator stats
        assert sink.records == []

    def test_oom_postmortem_dumps_last_table(self):
        reg, sink = _tracked_registry()
        rows = [{"tpu:0": {"bytes_in_use": 900, "peak_bytes_in_use": 980,
                           "bytes_limit": 1000}}]
        ms = mem_mod.MemorySampler(every=1,
                                   stats_fn=self._stats_seq(rows),
                                   registry=reg)
        ms.sample(0)
        err = RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                           "allocating 512 bytes")
        assert mem_mod.is_oom_error(err)
        assert not mem_mod.is_oom_error(ValueError("shape mismatch"))
        rec = mem_mod.oom_postmortem(sampler=ms, error=err, step=7)
        assert rec["step"] == 7
        assert rec["devices"]["tpu:0"]["bytes_in_use"] == 900
        oom = [r for r in sink.records if r["kind"] == "memory.oom"]
        assert len(oom) == 1 and "RESOURCE_EXHAUSTED" in oom[0]["error"]
        assert reg.counter("memory.oom_count").value == 1

    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv(mem_mod.MEM_SAMPLE_ENV, "5")
        assert mem_mod.default_sample_every() == 5
        assert mem_mod.MemorySampler().every == 5


# -- Prometheus label escaping ---------------------------------------------
class TestPrometheusLabels:
    def test_labeled_gauges_and_escaping(self, tmp_path):
        reg = MetricsRegistry()
        reg.gauge("memory.bytes_in_use[device=tpu:0]").set(42)
        reg.gauge('memory.bytes_in_use[device=we"ird\\dev]').set(7)
        reg.histogram("compile.wall_ms[fn=hapi.train_step]").observe(3.0)
        p = PrometheusTextfile(str(tmp_path / "m.prom"), interval=0)
        p.bind(reg)
        text = p.render()
        assert ('paddle_tpu_memory_bytes_in_use{device="tpu:0"} 42'
                in text)
        # label VALUES escaped, not name-sanitized
        assert ('device="we\\"ird\\\\dev"') in text
        assert ('paddle_tpu_compile_wall_ms_count{fn="hapi.train_step"}'
                in text)
        # one TYPE line per base metric even with multiple label sets
        assert text.count("# TYPE paddle_tpu_memory_bytes_in_use") == 1


# -- schema versioning ------------------------------------------------------
class TestSchemaVersion:
    def test_unknown_schema_dropped_with_accounting(self, tmp_path):
        path = tmp_path / "worker-0.jsonl"
        lines = [{"ts": 1.0, "kind": "step", "step": 0,
                  "step_time_ms": 5.0},
                 {"ts": 2.0, "kind": "step", "schema_version": 1,
                  "step": 1, "step_time_ms": 5.0},
                 {"ts": 3.0, "kind": "future-thing",
                  "schema_version": 99}]
        path.write_text("\n".join(json.dumps(l) for l in lines)
                        + "\n{torn")
        drops = {}
        recs = agg_mod.read_worker_stream(str(path), drops=drops)
        assert len(recs) == 2           # v-less (=v1) and v1 kept
        assert drops == {"torn_lines": 1, "unknown_schema": 1}

    def test_summary_stamped_and_drops_surface(self, tmp_path):
        mdir = tmp_path / "run" / "metrics"
        mdir.mkdir(parents=True)
        (mdir / "worker-0.jsonl").write_text(
            json.dumps({"ts": 1.0, "kind": "step", "step": 0,
                        "step_time_ms": 1.0}) + "\n"
            + json.dumps({"ts": 2.0, "kind": "x",
                          "schema_version": 42}) + "\n")
        summary = obs.aggregate_run(str(tmp_path / "run"))
        assert summary["schema_version"] == agg_mod.SCHEMA_VERSION
        assert summary["dropped"]["unknown_schema"] == 1


# -- straggler attribution on synthetic streams ----------------------------
def _synthetic_workers(n_steps=40, slow_worker=2, slow_ms=30.0,
                       base_ms=100.0):
    rng = np.random.RandomState(7)
    workers = {}
    for wid in range(3):
        recs = []
        for s in range(n_steps):
            t = base_ms + float(rng.rand()) * 2.0
            if wid == slow_worker:
                t += slow_ms
            recs.append({"ts": 1000.0 + s, "kind": "step", "step": s,
                         "step_time_ms": t, "data_ms": 1.0})
        workers[wid] = recs
    return workers


class TestStragglerStats:
    def test_attributes_slowest_worker(self):
        stats = agg_mod.straggler_stats(_synthetic_workers())
        assert stats["straggler"] == 2
        assert stats["straggler_fraction"] == 1.0
        assert stats["aligned_steps"] == 40
        assert stats["spread_ms"]["p50"] == pytest.approx(30.0, abs=5.0)
        assert stats["relative_spread"]["p99"] == pytest.approx(
            0.3, abs=0.1)
        assert stats["worker_mean_step_ms"]["2"] > \
            stats["worker_mean_step_ms"]["0"]

    def test_single_worker_returns_none(self):
        workers = {0: _synthetic_workers()[0]}
        assert agg_mod.straggler_stats(workers) is None

    def test_rollback_revisited_steps_keep_last(self):
        workers = _synthetic_workers(n_steps=10)
        # worker 0 rolled back and replayed step 3 fast
        workers[0].append({"ts": 2000.0, "kind": "step", "step": 3,
                           "step_time_ms": 50.0, "data_ms": 1.0})
        stats = agg_mod.straggler_stats(workers)
        assert stats["aligned_steps"] == 10


# -- the doctor -------------------------------------------------------------
def _write_stream(mdir, wid, records):
    os.makedirs(mdir, exist_ok=True)
    with open(os.path.join(mdir, f"worker-{wid}.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def _degraded_run(tmp_path):
    run_dir = str(tmp_path / "run")
    mdir = os.path.join(run_dir, "metrics")
    workers = _synthetic_workers(n_steps=30, slow_worker=1)
    streams = {0: list(workers[0]), 1: list(workers[1])}
    streams[0] += [
        {"ts": 1000.5, "kind": "compile", "function": "hapi.train_step",
         "retrace": False, "changed": [], "wall_ms": 500.0, "nargs": 6},
        *[{"ts": 1001.0 + i, "kind": "compile",
           "function": "hapi.train_step", "retrace": True,
           "changed": [{"arg": "data[1]",
                        "detail": "int32[2,8] -> int32[2,12]"}],
           "wall_ms": 400.0, "nargs": 6} for i in range(4)],
        {"ts": 1006.0, "kind": "compile.retrace_storm",
         "function": "hapi.train_step", "retraces": 4, "window": 16,
         "culprits": ["data[1]"], "culprit": "data[1]"},
    ]
    for wid, recs in streams.items():
        _write_stream(mdir, wid, recs)
    return run_dir


class TestDoctor:
    def test_degraded_run_ranked_findings(self, tmp_path):
        run_dir = _degraded_run(tmp_path)
        diag = doctor.diagnose(run_dir)
        assert not diag["healthy"]
        kinds = [f["kind"] for f in diag["findings"]]
        assert "retrace_storm" in kinds and "straggler" in kinds
        storm = next(f for f in diag["findings"]
                     if f["kind"] == "retrace_storm")
        assert storm["data"]["argument"] == "data[1]"
        assert storm["data"]["function"] == "hapi.train_step"
        assert any("int32[2,8] -> int32[2,12]" in ev
                   for ev in storm["evidence"])
        strag = next(f for f in diag["findings"]
                     if f["kind"] == "straggler")
        assert strag["data"]["worker"] == 1
        # severities rank the list
        sevs = [f["severity"] for f in diag["findings"]]
        assert sevs == sorted(sevs, reverse=True)
        # diagnosis.json landed next to the metrics
        on_disk = json.load(open(os.path.join(run_dir,
                                              "diagnosis.json")))
        assert on_disk["findings"] == diag["findings"]

    def test_oom_outranks_everything(self, tmp_path):
        run_dir = _degraded_run(tmp_path)
        extra = [{"ts": 1030.0, "kind": "memory.oom", "step": 29,
                  "error": "RESOURCE_EXHAUSTED",
                  "devices": {"tpu:0": {"bytes_in_use": 990,
                                        "peak_bytes_in_use": 999,
                                        "bytes_limit": 1000,
                                        "utilization": 0.99}}}]
        _write_stream(os.path.join(run_dir, "metrics"), 2, extra)
        diag = doctor.diagnose(run_dir)
        assert diag["findings"][0]["kind"] == "oom"
        assert diag["findings"][0]["data"]["device"] == "tpu:0"

    def test_hbm_creep_detected(self, tmp_path):
        run_dir = str(tmp_path / "run")
        recs = [{"ts": 1000.0 + i, "kind": "step", "step": i,
                 "step_time_ms": 100.0, "data_ms": 1.0}
                for i in range(10)]
        recs += [{"ts": 1000.0 + i, "kind": "memory", "step": i,
                  "devices": {"tpu:0": {
                      "bytes_in_use": 500 + 40 * i,
                      "peak_bytes_in_use": 600 + 40 * i,
                      "bytes_limit": 10_000}}} for i in range(10)]
        _write_stream(os.path.join(run_dir, "metrics"), 0, recs)
        diag = doctor.diagnose(run_dir)
        creeps = [f for f in diag["findings"] if f["kind"] == "hbm_creep"]
        assert len(creeps) == 1
        assert creeps[0]["data"]["device"] == "tpu:0"
        assert creeps[0]["data"]["growth"] == pytest.approx(0.72)

    def test_data_starved_detected(self, tmp_path):
        run_dir = str(tmp_path / "run")
        recs = [{"ts": 1000.0 + i, "kind": "step", "step": i,
                 "step_time_ms": 100.0, "data_ms": 60.0}
                for i in range(10)]
        _write_stream(os.path.join(run_dir, "metrics"), 0, recs)
        diag = doctor.diagnose(run_dir)
        assert any(f["kind"] == "data_starved" for f in diag["findings"])

    def test_healthy_run(self, tmp_path):
        run_dir = str(tmp_path / "run")
        recs = [{"ts": 1000.0 + i, "kind": "step", "step": i,
                 "step_time_ms": 100.0 + (i % 3), "data_ms": 1.0}
                for i in range(10)]
        _write_stream(os.path.join(run_dir, "metrics"), 0, recs)
        diag = doctor.diagnose(run_dir)
        assert diag["healthy"] and diag["findings"] == []

    def test_no_telemetry_returns_none(self, tmp_path):
        assert doctor.diagnose(str(tmp_path / "empty")) is None

    def test_verdicts_mirrored_into_supervisor_report(self, tmp_path):
        from paddle_tpu.supervisor.report import SupervisorReport
        run_dir = _degraded_run(tmp_path)
        report = SupervisorReport(os.path.join(run_dir,
                                               "supervisor_report.json"))
        report.record("run_start", run_dir=run_dir)
        doctor.diagnose(run_dir)
        loaded = SupervisorReport.load(
            os.path.join(run_dir, "supervisor_report.json"))
        verdicts = loaded.of_kind("doctor.verdict")
        assert {v["verdict"] for v in verdicts} >= {"retrace_storm",
                                                    "straggler"}

    def test_cli_main(self, tmp_path, capsys):
        run_dir = _degraded_run(tmp_path)
        assert doctor.main([run_dir]) == 0
        out = capsys.readouterr().out
        assert "retrace_storm" in out and "straggler" in out
        assert doctor.main([str(tmp_path / "nothing")]) == 1
        assert doctor.main([]) == 2
        assert doctor.main(["--json", run_dir]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"]


# -- the acceptance drill ---------------------------------------------------
class _RaggedLoader(pt.io.DataLoader):
    """Batches whose batch dimension churns — the classic leaky data
    pipeline that forces a retrace per distinct shape."""

    def __init__(self, sizes, n_feat=8):
        self.sizes = list(sizes)
        self.n_feat = n_feat

    def __iter__(self):
        rng = np.random.RandomState(3)
        for b in self.sizes:
            x = rng.randn(b, self.n_feat).astype("float32")
            y = rng.randint(0, 4, (b,)).astype("int64")
            yield [x, y]

    def __len__(self):
        return len(self.sizes)


class TestDoctorE2E:
    def test_degraded_fit_diagnosed(self, tmp_path):
        """ISSUE 4 acceptance: scripted degraded run — retraces injected
        via shape churn, a slow worker injected via
        ``testing/faults.slow_call`` — and the doctor's top findings
        name the retrace-causing argument and the straggler worker."""
        from paddle_tpu.supervisor import RunSupervisor
        from paddle_tpu.testing import faults
        compilation.reset_tracker()
        run_dir = str(tmp_path / "run")
        sizes = [4, 6, 8, 10, 4, 6, 8, 10]    # 4 distinct shapes →
        # 3 retraces inside the storm window
        for wid in (0, 1):
            net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                                   pt.nn.Linear(16, 4))
            model = pt.Model(net)
            model.prepare(
                optimizer=pt.optimizer.Adam(learning_rate=1e-3),
                loss=pt.nn.CrossEntropyLoss())
            if wid == 1:                       # the straggler
                model._train_step = faults.slow_call(
                    model._train_step, 0.25)
            sup = RunSupervisor(run_dir, watchdog_secs=120.0,
                                worker_id=wid)
            model.fit(_RaggedLoader(sizes), epochs=1, verbose=0,
                      supervisor=sup)
        diag = doctor.diagnose(run_dir)
        assert diag is not None and not diag["healthy"]
        top_kinds = {f["kind"] for f in diag["findings"][:3]}
        assert "retrace_storm" in top_kinds
        assert "straggler" in top_kinds
        storm = next(f for f in diag["findings"]
                     if f["kind"] == "retrace_storm")
        assert storm["data"]["function"] == "hapi.train_step"
        assert str(storm["data"]["argument"]).startswith("data[")
        strag = next(f for f in diag["findings"]
                     if f["kind"] == "straggler")
        assert strag["data"]["worker"] == 1
        # the CLI renders the same verdicts
        assert doctor.main([run_dir]) == 0
