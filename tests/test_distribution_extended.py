"""Round-5 distribution fill-in (reference distribution/kl.py registry,
multinomial.py, independent.py, transformed_distribution.py + transform.py):
scipy.stats parity for log_prob/kl, transform bijection laws."""
import numpy as np
import scipy.stats as st

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import distribution as D

R = np.random.RandomState(0)


class TestKlRegistry:
    def test_register_kl_dispatch(self):
        class MyNormal(D.Normal):
            pass

        calls = []

        @D.register_kl(MyNormal, D.Normal)
        def _kl_mine(p, q):
            calls.append(1)
            return jnp.zeros(())

        out = D.kl_divergence(MyNormal(0.0, 1.0), D.Normal(0.0, 1.0))
        assert calls and float(out) == 0.0
        # base pair still uses the closed form
        kl = float(D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)))
        want = np.log(2.0) + (1 + 1) / 8 - 0.5
        np.testing.assert_allclose(kl, want, rtol=1e-6)

    def test_beta_kl_vs_numeric(self):
        p, q = D.Beta(2.0, 3.0), D.Beta(4.0, 1.5)
        x = np.linspace(1e-4, 1 - 1e-4, 20001)
        pp = st.beta.pdf(x, 2.0, 3.0)
        want = np.trapezoid(pp * (st.beta.logpdf(x, 2.0, 3.0)
                                  - st.beta.logpdf(x, 4.0, 1.5)), x)
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), want,
                                   rtol=1e-3)

    def test_dirichlet_kl_vs_monte_carlo(self):
        c1 = np.asarray([2.0, 3.0, 4.0])
        c2 = np.asarray([1.0, 1.0, 5.0])
        p, q = D.Dirichlet(jnp.asarray(c1)), D.Dirichlet(jnp.asarray(c2))
        s = st.dirichlet.rvs(c1, size=200000, random_state=R)
        want = np.mean(st.dirichlet.logpdf(s.T, c1)
                       - st.dirichlet.logpdf(s.T, c2))
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), want,
                                   rtol=2e-2)

    def test_bernoulli_uniform_kl(self):
        kl = float(D.kl_divergence(D.Bernoulli(0.3), D.Bernoulli(0.6)))
        want = 0.3 * np.log(0.3 / 0.6) + 0.7 * np.log(0.7 / 0.4)
        np.testing.assert_allclose(kl, want, rtol=1e-5)
        ku = float(D.kl_divergence(D.Uniform(0.0, 1.0),
                                   D.Uniform(-1.0, 2.0)))
        np.testing.assert_allclose(ku, np.log(3.0), rtol=1e-6)
        assert np.isinf(float(D.kl_divergence(D.Uniform(-2.0, 1.0),
                                              D.Uniform(0.0, 1.0))))


class TestMultinomial:
    def test_log_prob_vs_scipy(self):
        probs = np.asarray([0.2, 0.3, 0.5])
        m = D.Multinomial(10, jnp.asarray(probs))
        for counts in ([2, 3, 5], [0, 0, 10], [4, 4, 2]):
            want = st.multinomial.logpmf(counts, 10, probs)
            got = float(m.log_prob(jnp.asarray(counts, jnp.float32)))
            np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_sample_counts(self):
        pt.seed(3)
        m = D.Multinomial(20, jnp.asarray([0.1, 0.9]))
        s = np.asarray(m.sample((2000,)))
        assert s.shape == (2000, 2)
        np.testing.assert_array_equal(s.sum(-1), 20)
        np.testing.assert_allclose(s[:, 1].mean(), 18.0, rtol=0.03)

    def test_entropy_exact(self):
        # exact by enumeration for n=2, p=(0.5, 0.5): outcomes
        # (2,0) p=.25, (1,1) p=.5, (0,2) p=.25
        m = D.Multinomial(2, jnp.asarray([0.5, 0.5]))
        want = -(0.25 * np.log(0.25) + 0.5 * np.log(0.5)
                 + 0.25 * np.log(0.25))
        np.testing.assert_allclose(float(m.entropy()), want, rtol=1e-5)
        # and against scipy for an asymmetric case
        me = D.Multinomial(5, jnp.asarray([0.2, 0.3, 0.5]))
        np.testing.assert_allclose(
            float(me.entropy()),
            st.multinomial.entropy(5, [0.2, 0.3, 0.5]), rtol=1e-5)

    def test_mean_variance(self):
        m = D.Multinomial(10, jnp.asarray([0.25, 0.75]))
        np.testing.assert_allclose(np.asarray(m.mean), [2.5, 7.5])
        np.testing.assert_allclose(np.asarray(m.variance),
                                   [10 * .25 * .75, 10 * .75 * .25])


class TestIndependent:
    def test_sums_event_dims(self):
        base = D.Normal(jnp.zeros((4, 3)), jnp.ones((4, 3)))
        ind = D.Independent(base, 1)
        v = jnp.asarray(R.randn(4, 3), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(ind.log_prob(v)),
            np.asarray(base.log_prob(v)).sum(-1), rtol=1e-6)
        assert ind.entropy().shape == (4,)


class TestTransforms:
    def test_bijection_and_logdet(self):
        x = jnp.asarray(R.randn(50) * 0.8, jnp.float32)
        for t in [D.AffineTransform(1.5, -2.0), D.ExpTransform(),
                  D.SigmoidTransform(), D.TanhTransform()]:
            y = t.forward(x)
            back = t.inverse(y)
            np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                                       rtol=1e-4, atol=1e-5)
            # analytic log|dy/dx| vs autodiff
            ld = np.asarray(t.forward_log_det_jacobian(x))
            auto = np.log(np.abs(np.asarray(jax.vmap(jax.grad(
                lambda v: t.forward(v)))(x))))
            np.testing.assert_allclose(ld, auto, rtol=1e-4, atol=1e-4)

    def test_chain(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0),
                                  D.ExpTransform()])
        x = jnp.asarray(0.5, jnp.float32)
        np.testing.assert_allclose(float(chain.forward(x)), np.exp(1.0),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(chain.inverse(chain.forward(x))),
                                   0.5, rtol=1e-5)


class TestTransformedDistribution:
    def test_lognormal_matches_scipy(self):
        # exp(Normal(mu, sigma)) is LogNormal(s=sigma, scale=e^mu)
        td = D.TransformedDistribution(D.Normal(0.5, 0.75),
                                       D.ExpTransform())
        x = np.asarray([0.3, 1.0, 2.5], np.float32)
        want = st.lognorm.logpdf(x, s=0.75, scale=np.exp(0.5))
        np.testing.assert_allclose(np.asarray(td.log_prob(jnp.asarray(x))),
                                   want, rtol=1e-5)
        pt.seed(5)
        s = np.asarray(td.sample((200000,)))
        np.testing.assert_allclose(s.mean(),
                                   st.lognorm.mean(0.75,
                                                   scale=np.exp(0.5)),
                                   rtol=0.05)

    def test_affine_of_uniform(self):
        td = D.TransformedDistribution(D.Uniform(0.0, 1.0),
                                       D.AffineTransform(2.0, 3.0))
        # U[2, 5): density 1/3
        np.testing.assert_allclose(float(td.log_prob(4.0)),
                                   -np.log(3.0), rtol=1e-6)


class TestNewDatasets:
    def test_flowers_splits(self):
        from paddle_tpu.vision.datasets import Flowers
        tr = Flowers(mode="train", synthetic_size=64)
        te = Flowers(mode="test", synthetic_size=16)
        img, lab = tr[0]
        assert img.shape == (64, 64, 3) and img.dtype == np.uint8
        assert 1 <= int(lab[0]) <= 102
        assert len(tr) == 64 and len(te) == 16

    def test_voc2012_mask_alignment(self):
        from paddle_tpu.vision.datasets import VOC2012
        ds = VOC2012(mode="train", synthetic_size=8)
        img, mask = ds[0]
        assert img.shape == (64, 64, 3) and mask.shape == (64, 64)
        assert mask.max() >= 1 and mask.min() == 0
        # the labeled region really is visually distinct from background
        fg = img[mask > 0].astype(np.float32).mean()
        bg = img[mask == 0].astype(np.float32).mean()
        assert abs(fg - bg) > 10.0

    def test_cifar100(self):
        from paddle_tpu.vision.datasets import Cifar100
        ds = Cifar100(synthetic_size=32)
        assert len(ds) == 32 and ds.NUM_CLASSES == 100


class TestTransformFill:
    """Round-5 transform tail (reference transform.py __all__ parity)."""

    def test_reshape_roundtrip_zero_logdet(self):
        from paddle_tpu.distribution import ReshapeTransform
        rt = ReshapeTransform((4,), (2, 2))
        x = jnp.arange(8.0).reshape(2, 4)
        assert rt.forward(x).shape == (2, 2, 2)
        np.testing.assert_allclose(np.asarray(rt.inverse(rt.forward(x))),
                                   np.asarray(x))
        np.testing.assert_allclose(
            np.asarray(rt.forward_log_det_jacobian(x)), 0.0)

    def test_stick_breaking_simplex_and_logdet_vs_autodiff(self):
        from paddle_tpu.distribution import StickBreakingTransform
        sb = StickBreakingTransform()
        v = jnp.asarray(np.random.RandomState(0).randn(5, 3)
                        .astype(np.float32))
        y = sb.forward(v)
        np.testing.assert_allclose(np.asarray(y.sum(-1)), 1.0, rtol=1e-5)
        assert np.all(np.asarray(y) > 0)
        np.testing.assert_allclose(np.asarray(sb.inverse(y)),
                                   np.asarray(v), atol=1e-4)
        jac = jax.vmap(jax.jacfwd(lambda t: sb.forward(t)[:-1]))(v)
        ref = np.log(np.abs(np.linalg.det(np.asarray(jac))))
        np.testing.assert_allclose(
            np.asarray(sb.forward_log_det_jacobian(v)), ref, rtol=1e-4)

    def test_independent_stack_softmax(self):
        from paddle_tpu.distribution import (AffineTransform, ExpTransform,
                                             IndependentTransform,
                                             SoftmaxTransform,
                                             StackTransform)
        it = IndependentTransform(ExpTransform(), 1)
        assert it.forward_log_det_jacobian(jnp.ones((3, 4))).shape == (3,)
        st = StackTransform([ExpTransform(), AffineTransform(0.0, 2.0)])
        out = st.forward(jnp.stack([jnp.zeros(3), jnp.ones(3)]))
        np.testing.assert_allclose(np.asarray(out[0]), 1.0)
        np.testing.assert_allclose(np.asarray(out[1]), 2.0)
        sm = SoftmaxTransform()
        np.testing.assert_allclose(
            float(sm.forward(jnp.ones(4)).sum()), 1.0, rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(sm.inverse(sm.forward(jnp.zeros(3)))),
            np.asarray(jnp.full(3, np.log(1 / 3))), rtol=1e-5)
