"""Profiler tests (≙ reference test_profiler.py doctrine: scheduler state
machine, RecordEvent stats, trace files on disk)."""
import glob
import os

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 make_scheduler, profiler_summary,
                                 record_function)


class TestScheduler:
    def test_cycle_states(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                               skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == [
            ProfilerState.CLOSED,            # skip_first
            ProfilerState.CLOSED,
            ProfilerState.READY,
            ProfilerState.RECORD,
            ProfilerState.RECORD_AND_RETURN,  # last record step of cycle
            ProfilerState.CLOSED,            # repeat exhausted
        ]


class TestRecordEvent:
    def test_stats_accumulate(self):
        profiler_summary(reset=True)
        with RecordEvent("fwd"):
            pass
        with RecordEvent("fwd"):
            pass

        @record_function("bwd")
        def f():
            return 1

        f()
        stats = profiler_summary(reset=True)
        assert stats["fwd"][0] == 2
        assert stats["bwd"][0] == 1


class TestProfiler:
    def test_trace_produces_files_and_summary(self, tmp_path):
        log_dir = str(tmp_path / "prof")
        ready = []
        p = Profiler(
            scheduler=make_scheduler(closed=0, ready=1, record=2, repeat=1),
            on_trace_ready=lambda prof: ready.append(prof.step_num),
            log_dir=log_dir)
        x = jnp.asarray(np.random.RandomState(0).randn(64, 64), jnp.float32)
        f = jax.jit(lambda a: a @ a)
        p.start()
        for _ in range(4):
            with RecordEvent("matmul_step"):
                f(x).block_until_ready()
            p.step()
        p.stop()
        assert ready, "on_trace_ready never fired"
        produced = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                             recursive=True)
        assert produced, f"no xplane trace under {log_dir}"
        text = p.summary()
        assert "matmul_step" in text
