"""Run-supervisor proof (ISSUE 2): watchdog, heartbeats, divergence
guard, auto-rollback, and the post-mortem report.

Fault drills use ``paddle_tpu.testing.faults`` injectors (``hang``,
``slow_call``, ``diverge_after``, ``hang_on_write``) so no test hangs for
real: every blocking fault is interruptible and every deadline is short.

End-to-end acceptance (ISSUE 2): with injected hang + injected
divergence, a hapi training run completes by firing the watchdog,
skipping / rolling back to the last committed checkpoint, and finishing
within the rollback budget — with every event recorded in the
supervisor's JSON report.
"""
import json
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.supervisor import (DivergenceGuard, GuardAction,
                                   HeartbeatMonitor, HeartbeatWriter,
                                   RollbackBudgetExceeded, RollbackManager,
                                   RunState, RunSupervisor, StepTimeout,
                                   SupervisorReport, Watchdog,
                                   global_watchdog, guarded, install_global)
from paddle_tpu.testing import faults

pytestmark = pytest.mark.faults


# -- report ----------------------------------------------------------------
class TestReport:
    def test_record_flush_load_roundtrip(self, tmp_path):
        path = str(tmp_path / "report.json")
        report = SupervisorReport(path)
        report.record("watchdog_timeout", label="train_batch")
        report.record("rollback", reason="divergence", start_step=7)
        loaded = SupervisorReport.load(path)
        assert loaded.counts() == {"watchdog_timeout": 1, "rollback": 1}
        assert loaded.of_kind("rollback")[0]["start_step"] == 7

    def test_durable_after_every_record(self, tmp_path):
        path = str(tmp_path / "report.json")
        report = SupervisorReport(path)
        report.record("step_failure", step=3)
        # the file on disk already holds the event (post-mortem property)
        doc = json.loads((tmp_path / "report.json").read_text())
        assert doc["events"][0]["kind"] == "step_failure"

    def test_memory_only_mode(self):
        report = SupervisorReport(None)
        report.record("x")
        assert report.counts() == {"x": 1}


# -- watchdog --------------------------------------------------------------
class TestWatchdog:
    def test_fires_on_injected_hang(self):
        report = SupervisorReport()
        with Watchdog(timeout=0.25, report=report) as wd:
            t0 = time.monotonic()
            with pytest.raises(StepTimeout):
                with wd.armed("train_batch"):
                    faults.hang(30.0)
            # interrupted promptly, not after the full 30s hang
            assert time.monotonic() - t0 < 5.0
        (event,) = report.of_kind("watchdog_timeout")
        assert event["label"] == "train_batch"
        assert "MainThread" in event["stacks"]  # all-thread dump attached
        assert wd.timeouts == 1

    def test_does_not_fire_on_slow_but_alive(self):
        with Watchdog(timeout=5.0) as wd:
            with wd.armed("step"):
                faults.slow_call(lambda: "ok", 0.05)()
            assert wd.timeouts == 0

    def test_per_section_timeout_override(self):
        with Watchdog(timeout=60.0) as wd:
            with pytest.raises(StepTimeout):
                with wd.armed("barrier", timeout=0.2):
                    faults.hang(30.0)

    def test_global_install_and_guarded(self):
        assert global_watchdog() is None
        with Watchdog(timeout=0.2) as wd:
            prev = install_global(wd)
            try:
                with pytest.raises(StepTimeout):
                    with guarded("collective.barrier"):
                        faults.hang(30.0)
            finally:
                install_global(prev)
        assert global_watchdog() is None

    def test_guarded_is_noop_without_global(self):
        with guarded("barrier"):
            pass  # must not raise nor require a watchdog

    def test_env_knob_seeds_default(self, monkeypatch):
        monkeypatch.setenv("PTPU_WATCHDOG_SECS", "123.5")
        wd = Watchdog()
        wd.close()
        assert wd.timeout == 123.5

    def test_barrier_runs_under_global_watchdog(self):
        from paddle_tpu.distributed.collective import barrier
        # single-process: must complete instantly, armed or not
        with Watchdog(timeout=5.0) as wd:
            prev = install_global(wd)
            try:
                barrier()
                barrier(timeout=1.0)
            finally:
                install_global(prev)
            assert wd.timeouts == 0


# -- heartbeats ------------------------------------------------------------
class TestHeartbeat:
    def test_beat_goes_through_fsio_seam(self, tmp_path):
        writer = HeartbeatWriter(str(tmp_path), worker_id=0, interval=60)
        with faults.FaultInjector() as fi:
            writer.beat(step=5)
        assert fi.write_count >= 1  # durable write, injectable like all
        payload = json.loads(writer.path and open(writer.path).read())
        assert payload["worker"] == 0 and payload["step"] == 5

    def test_staleness_classification(self, tmp_path):
        clock = {"t": 1000.0}
        w0 = HeartbeatWriter(str(tmp_path), worker_id=0, interval=1,
                             clock=lambda: clock["t"])
        w1 = HeartbeatWriter(str(tmp_path), worker_id=1, interval=1,
                             clock=lambda: clock["t"])
        report = SupervisorReport()
        monitor = HeartbeatMonitor(str(tmp_path), stale_after=3,
                                   lost_after=9, expected=2,
                                   clock=lambda: clock["t"], report=report)
        w0.beat(); w1.beat()
        assert monitor.poll()["state"] == RunState.HEALTHY
        # worker 1 goes quiet: stale first...
        clock["t"] += 5
        w0.beat()
        detail = monitor.poll()
        assert detail["state"] == RunState.DEGRADED
        assert detail["stale"] == [1]
        # ...then lost
        clock["t"] += 6
        w0.beat()
        detail = monitor.poll()
        assert detail["state"] == RunState.LOST_WORKER
        assert detail["lost"] == [1]
        # every transition recorded
        states = [e["state"] for e in report.of_kind("run_state")]
        assert states == [RunState.HEALTHY, RunState.DEGRADED,
                          RunState.LOST_WORKER]

    def test_expected_worker_never_appearing_is_lost(self, tmp_path):
        clock = {"t": 0.0}
        w0 = HeartbeatWriter(str(tmp_path), worker_id=0, interval=1,
                             clock=lambda: clock["t"])
        monitor = HeartbeatMonitor(str(tmp_path), stale_after=3,
                                   lost_after=9, expected=2,
                                   clock=lambda: clock["t"])
        w0.beat()
        assert monitor.poll()["state"] == RunState.HEALTHY  # grace window
        clock["t"] += 10
        w0.beat()
        detail = monitor.poll()
        assert detail["state"] == RunState.LOST_WORKER
        assert detail["missing"] == [1]

    def test_maybe_beat_throttles(self, tmp_path):
        clock = {"t": 0.0}
        writer = HeartbeatWriter(str(tmp_path), worker_id=0, interval=10,
                                 clock=lambda: clock["t"])
        clock["t"] = 100.0
        assert writer.maybe_beat(1) is True
        assert writer.maybe_beat(2) is False  # half-interval not elapsed
        clock["t"] += 6.0
        assert writer.maybe_beat(3) is True


# -- divergence guard ------------------------------------------------------
class TestDivergenceGuard:
    def _guard(self, **kw):
        kw.setdefault("skip_budget", 2)
        kw.setdefault("max_lr_backoffs", 1)
        kw.setdefault("min_history", 2)
        return DivergenceGuard(**kw)

    def test_escalation_ladder(self):
        guard = self._guard()
        for i in range(4):
            assert guard.observe(i, 1.0) == GuardAction.OK
        inject = faults.diverge_after(4, mode="spike")
        seq = [guard.observe(s, inject(s, 1.0)) for s in range(4, 8)]
        assert seq == [GuardAction.SKIP, GuardAction.SKIP,
                       GuardAction.LOWER_LR, GuardAction.ROLLBACK]
        assert guard.lr_scale == 0.5

    def test_one_off_spike_costs_one_update(self):
        guard = self._guard()
        for i in range(4):
            guard.observe(i, 1.0)
        assert guard.observe(4, 1e6) == GuardAction.SKIP
        assert guard.observe(5, 1.0) == GuardAction.OK
        assert guard.consecutive_bad == 0 and guard.total_bad == 1

    def test_nan_and_inf_are_bad(self):
        guard = self._guard()
        assert guard.observe(0, float("nan")) == GuardAction.SKIP
        assert guard.observe(1, float("inf")) == GuardAction.SKIP

    def test_grad_norm_spike_detected(self):
        guard = self._guard()
        for i in range(4):
            guard.observe(i, 1.0, grad_norm=1.0)
        assert guard.observe(4, 1.0, grad_norm=1e5) == GuardAction.SKIP

    def test_amp_grace_does_not_escalate(self):
        guard = self._guard(amp_grace=3)
        # loss-scale search overflows: skipped but never climb the ladder
        for i in range(3):
            assert guard.observe(i, float("inf"),
                                 amp_active=True) == GuardAction.SKIP
        assert guard.consecutive_bad == 0
        # grace spent: a further overflow escalates normally
        assert guard.observe(3, float("inf"), amp_active=True) \
            == GuardAction.SKIP
        assert guard.consecutive_bad == 1

    def test_reset_after_rollback_keeps_lowered_lr(self):
        guard = self._guard()
        inject = faults.diverge_after(0, mode="nan")
        for s in range(4):
            guard.observe(s, inject(s, 1.0))
        assert guard.lr_scale == 0.5
        guard.reset_after_rollback()
        assert guard.consecutive_bad == 0 and guard.lr_scale == 0.5
        guard.restore_lr()
        assert guard.lr_scale == 1.0

    def test_diverge_after_modes_and_count(self):
        nan_inj = faults.diverge_after(2, mode="nan")
        assert nan_inj(1, 5.0) == 5.0
        assert np.isnan(nan_inj(2, 5.0))
        spike = faults.diverge_after(0, mode="spike", factor=10.0, count=2)
        poisoned = [spike(s, 1.0) for s in range(3)]
        assert poisoned[0] == 20.0 and poisoned[1] == 200.0
        assert poisoned[2] == 1.0 and spike.triggered == 2


# -- elastic satellites ----------------------------------------------------
class TestElasticSupervision:
    def _mgr(self, tmp_path, **kw):
        from paddle_tpu.distributed.elastic import ElasticTrainState
        kw.setdefault("install_sigterm_handler", False)
        return ElasticTrainState(str(tmp_path), **kw)

    def _state(self, seed=0):
        return {"w": jnp.asarray(np.random.RandomState(seed)
                                 .randn(8).astype(np.float32))}

    def test_last_good_step(self, tmp_path):
        mgr = self._mgr(tmp_path, keep=5)
        assert mgr.last_good_step() == -1
        mgr.save(3, self._state(3), use_async=False)
        mgr.save(7, self._state(7), use_async=False)
        assert mgr.last_good_step() == 7

    def test_quarantine_emits_supervisor_event(self, tmp_path):
        report = SupervisorReport()
        mgr = self._mgr(tmp_path, keep=5, event_sink=report.record)
        mgr.save(1, self._state(1), use_async=False)
        mgr.save(2, self._state(2), use_async=False)
        faults.corrupt_shard(str(tmp_path / "step-2"))
        state, start = mgr.restore_or(lambda: self._state(0),
                                      lambda: self._state(0))
        assert start == 2  # fell back to step 1
        (event,) = report.of_kind("checkpoint_quarantined")
        assert event["step"] == 2
        assert event["next_good_step"] == 1


# -- retry_reader exhaustion (satellite) -----------------------------------
class TestRetryReaderExhaustion:
    def test_final_error_carries_attempts_and_cause(self):
        from paddle_tpu.reader import retry_reader
        from paddle_tpu.utils.retry import RetriesExhausted

        def always_fails():
            yield 0
            raise OSError("disk on fire")

        robust = retry_reader(always_fails, max_attempts=3,
                              sleep=lambda _t: None)
        with pytest.raises(RetriesExhausted) as ei:
            list(robust())
        assert "3 attempt(s)" in str(ei.value)
        assert isinstance(ei.value.__cause__, OSError)
        assert "disk on fire" in str(ei.value.__cause__)
        # still an OSError for callers filtering on the old contract
        assert isinstance(ei.value, OSError)


# -- rollback manager ------------------------------------------------------
class TestRollbackManager:
    def test_budget_exhaustion_raises_with_report(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticTrainState
        report = SupervisorReport(str(tmp_path / "report.json"))
        mgr = ElasticTrainState(str(tmp_path / "ckpt"),
                                install_sigterm_handler=False)
        rb = RollbackManager(mgr, budget=1, report=report)
        state = {"w": jnp.zeros((4,), jnp.float32)}
        mgr.save(5, state, use_async=False)
        restored, start = rb.rollback(lambda: state, lambda: state)
        assert start == 6
        with pytest.raises(RollbackBudgetExceeded) as ei:
            rb.rollback(lambda: state, lambda: state)
        assert "report.json" in str(ei.value)
        assert report.counts()["rollback_budget_exhausted"] == 1

    def test_reseed_hook_called(self, tmp_path):
        from paddle_tpu.distributed.elastic import ElasticTrainState
        mgr = ElasticTrainState(str(tmp_path),
                                install_sigterm_handler=False)
        seeds = []
        rb = RollbackManager(mgr, budget=2, reseed=seeds.append)
        state = {"w": jnp.zeros((4,), jnp.float32)}
        mgr.save(3, state, use_async=False)
        rb.rollback(lambda: state, lambda: state)
        assert seeds == [4]

    def test_env_knob_seeds_budget(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PTPU_ROLLBACK_BUDGET", "7")
        rb = RollbackManager(None)
        assert rb.budget == 7


# -- fault injector registry additions -------------------------------------
class TestHangInjection:
    def test_hang_on_write_reuses_registry(self, tmp_path):
        from paddle_tpu.utils import fsio
        with faults.FaultInjector() as fi:
            fi.hang_on_write(1, seconds=0.05)
            t0 = time.monotonic()
            fsio.write_bytes(str(tmp_path / "f"), b"payload")
            assert time.monotonic() - t0 >= 0.05
        assert fi.injected == [(1, "hang", str(tmp_path / "f"))]
        assert (tmp_path / "f").read_bytes() == b"payload"


# -- end-to-end drills on a tiny hapi model --------------------------------
def _tiny_supervised(tmp_path, calibrate_watchdog=None, **sup_kw):
    """``calibrate_watchdog=K``: measure one compiled train step on THIS
    machine under THIS load and arm the watchdog at K× that (bounded to
    [1, 10] seconds) — the hang drills need a deadline that a merely
    load-slowed step can never cross (a fixed 0.3s deadline was
    load-flaky: the suite running in parallel pushed honest steps past
    it), while the injected 30s hang still crosses it immediately."""
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset
    pt.seed(0)
    model = Model(nn.Linear(4, 2))
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=1e-2),
                  loss=lambda out, y: jnp.mean((out - y) ** 2))
    rng = np.random.RandomState(0)
    ds = TensorDataset([rng.randn(24, 4).astype(np.float32),
                        rng.randn(24, 2).astype(np.float32)])
    if calibrate_watchdog is not None:
        x, y = rng.randn(1, 4).astype(np.float32), \
            rng.randn(1, 2).astype(np.float32)
        model.train_batch([x], y)            # compile outside the timing
        t0 = time.monotonic()
        model.train_batch([x], y)
        stepped = time.monotonic() - t0
        sup_kw["watchdog_secs"] = min(
            10.0, max(1.0, calibrate_watchdog * stepped))
    sup_kw.setdefault("save_interval_steps", 4)
    sup_kw.setdefault("watchdog_secs", 30.0)
    sup_kw.setdefault("heartbeat_secs", 60.0)
    sup_kw.setdefault("sigterm_handler", False)
    sup_kw.setdefault("guard", DivergenceGuard(skip_budget=2,
                                               max_lr_backoffs=1,
                                               min_history=2))
    sup = RunSupervisor(str(tmp_path / "run"), **sup_kw)
    return model, ds, sup


class TestSupervisedFitEndToEnd:
    def test_divergence_skip_rollback_resume(self, tmp_path):
        """The acceptance drill: injected divergence → skip ×2 →
        LR backoff → rollback to the last committed step → resume →
        the run COMPLETES, with every event in the JSON report."""
        model, ds, sup = _tiny_supervised(tmp_path, rollback_budget=2)
        inject = faults.diverge_after(8, mode="spike", count=4)
        sup.inject_loss(inject)
        history = model.fit(ds, batch_size=1, epochs=1, verbose=0,
                            supervisor=sup)
        assert sup.rollback.used == 1  # within budget
        assert np.isfinite(history["loss"][-1])
        counts = SupervisorReport.load(
            str(tmp_path / "run" / "supervisor_report.json")).counts()
        for kind in ("run_start", "divergence_skip", "lr_backoff",
                     "divergence_rollback", "rollback", "run_end"):
            assert counts.get(kind), f"missing {kind} in report: {counts}"
        assert counts["divergence_skip"] == 2
        # rollback landed on the newest committed step at the time (8)
        assert SupervisorReport.load(
            str(tmp_path / "run" / "supervisor_report.json")
        ).of_kind("rollback")[0]["start_step"] == 9
        assert model._supervisor is None  # detached after the run

    def test_watchdog_hang_skipped_run_completes(self, tmp_path):
        model, ds, sup = _tiny_supervised(tmp_path, calibrate_watchdog=50)
        hung = []

        def hang_once(step, loss):
            if step == 5 and not hung:
                hung.append(step)
                faults.hang(30.0)
            return loss

        sup.inject_loss(hang_once)
        history = model.fit(ds, batch_size=1, epochs=1, verbose=0,
                            supervisor=sup)
        counts = sup.report.counts()
        assert counts["watchdog_timeout"] == 1
        assert counts["step_failure"] == 1
        assert counts.get("rollback") is None  # one timeout → skip only
        assert len(history["loss"]) == 23  # one batch lost to the hang

    def test_repeated_hang_rolls_back(self, tmp_path):
        model, ds, sup = _tiny_supervised(
            tmp_path, calibrate_watchdog=50, rollback_budget=2,
            step_failure_budget=1)
        hangs = {"n": 0}

        def hang_twice(step, loss):
            if step >= 6 and hangs["n"] < 2:
                hangs["n"] += 1
                faults.hang(30.0)
            return loss

        sup.inject_loss(hang_twice)
        model.fit(ds, batch_size=1, epochs=1, verbose=0, supervisor=sup)
        counts = sup.report.counts()
        assert counts["watchdog_timeout"] == 2
        assert counts["step_failure"] == 2
        assert counts["rollback"] == 1
        assert sup.report.of_kind("rollback")[0]["reason"] == "step-timeout"

    def test_budget_exhaustion_fails_loudly_with_report(self, tmp_path):
        model, ds, sup = _tiny_supervised(tmp_path, rollback_budget=1)
        sup.inject_loss(faults.diverge_after(6, mode="spike"))  # forever
        with pytest.raises(RollbackBudgetExceeded) as ei:
            model.fit(ds, batch_size=1, epochs=1, verbose=0,
                      supervisor=sup)
        assert "supervisor_report.json" in str(ei.value)
        counts = SupervisorReport.load(
            str(tmp_path / "run" / "supervisor_report.json")).counts()
        assert counts["rollback_budget_exhausted"] == 1
        (end,) = SupervisorReport.load(
            str(tmp_path / "run" / "supervisor_report.json")
        ).of_kind("run_end")
        assert end["status"] == "failed"

    def test_lr_backoff_applied_to_updates(self, tmp_path):
        model, ds, sup = _tiny_supervised(tmp_path, rollback_budget=2)
        sup.inject_loss(faults.diverge_after(8, mode="spike", count=3))
        model.fit(ds, batch_size=1, epochs=1, verbose=0, supervisor=sup)
        # ladder reached LOWER_LR (sticky) but not ROLLBACK
        assert sup.guard.lr_scale == 0.5
        assert sup.rollback.used == 0
