"""paddle.flops / summary, distributed.spawn, sparse_attention tests."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class TestFlops:
    def test_flops_counts_matmuls(self):
        pt.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        f = pt.flops(net, (2, 8))
        # 2 matmuls at 2*B*I*O flops each (XLA counts mul+add)
        expected = 2 * 2 * 8 * 16 + 2 * 2 * 16 * 4
        assert f >= expected
        assert f < expected * 2  # no phantom work

    def test_summary_counts_params(self, capsys):
        pt.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Linear(16, 4))
        info = pt.summary(net)
        assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
        assert "Total params" in capsys.readouterr().out


class TestSparseAttention:
    def _qkv(self, B=2, H=2, S=4, D=8, seed=0):
        r = np.random.RandomState(seed)
        return tuple(jnp.asarray(r.randn(B, H, S, D), jnp.float32)
                     for _ in range(3))

    def test_dense_pattern_matches_sdpa(self):
        q, k, v = self._qkv()
        B, H, S, _ = q.shape
        offset = jnp.broadcast_to(jnp.arange(0, (S + 1) * S, S),
                                  (B, H, S + 1))
        cols = jnp.broadcast_to(jnp.tile(jnp.arange(S), S), (B, H, S * S))
        out = F.sparse_attention(q, k, v, offset, cols)
        ref = F.scaled_dot_product_attention(q, k, v, training=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_causal_pattern_matches_causal_sdpa(self):
        q, k, v = self._qkv(seed=1)
        B, H, S, _ = q.shape
        offs, coll = np.zeros(S + 1, np.int64), []
        for i in range(S):
            coll += list(range(i + 1))
            offs[i + 1] = len(coll)
        offset = jnp.broadcast_to(jnp.asarray(offs), (B, H, S + 1))
        cols = jnp.broadcast_to(jnp.asarray(coll), (B, H, len(coll)))
        out = F.sparse_attention(q, k, v, offset, cols)
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_key_padding_mask(self):
        q, k, v = self._qkv(seed=2)
        B, H, S, _ = q.shape
        offset = jnp.broadcast_to(jnp.arange(0, (S + 1) * S, S),
                                  (B, H, S + 1))
        cols = jnp.broadcast_to(jnp.tile(jnp.arange(S), S), (B, H, S * S))
        kpm = jnp.zeros((B, S)).at[:, -1].set(float("-inf"))
        out = F.sparse_attention(q, k, v, offset, cols,
                                 key_padding_mask=kpm)
        ref = F.scaled_dot_product_attention(
            q, k, v, attn_mask=kpm[:, None, None, :], training=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def _spawn_target(path):
    import os
    with open(f"{path}/rank_{os.environ['PADDLE_TRAINER_ID']}", "w") as f:
        f.write(os.environ["PADDLE_TRAINERS_NUM"])


def _spawn_failing():
    raise ValueError("boom")


def _spawn_hang_or_fail():
    import os
    import time
    if os.environ["PADDLE_TRAINER_ID"] == "0":
        raise ValueError("rank0 crashed")
    time.sleep(300)  # a peer blocked on rank 0 forever


class TestSpawn:
    def test_spawn_runs_and_wires_env(self, tmp_path):
        from paddle_tpu.distributed.spawn import spawn
        spawn(_spawn_target, args=(str(tmp_path),), nprocs=2)
        assert (tmp_path / "rank_0").read_text() == "2"
        assert (tmp_path / "rank_1").read_text() == "2"

    def test_spawn_propagates_failure(self):
        from paddle_tpu.distributed.spawn import spawn
        with pytest.raises(RuntimeError, match="boom"):
            spawn(_spawn_failing, nprocs=1)

    def test_spawn_kills_blocked_peers_on_failure(self):
        """A crashed rank must terminate survivors promptly, not hang the
        parent in join (regression: unconditional join loop)."""
        import time
        from paddle_tpu.distributed.spawn import spawn
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="rank0 crashed"):
            spawn(_spawn_hang_or_fail, nprocs=2)
        assert time.monotonic() - t0 < 60

    def test_spawn_rejects_unknown_options(self):
        from paddle_tpu.distributed.spawn import spawn
        with pytest.raises(Exception, match="unsupported options"):
            spawn(_spawn_failing, nprocs=1, backend="nccl")
