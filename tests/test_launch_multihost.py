"""Multi-host launch proof (reference launch/controllers/collective.py:89-92
+ the localhost-multiprocess test doctrine, test_dist_base.py:782):
``launch --nnodes 2`` must bring up a real 2-process jax.distributed CPU
cluster in which a global psum spans both processes."""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Some jaxlib CPU builds (this container's among them) cannot run
# cross-process collectives at all — the 2-process cluster forms, but the
# psum dies with this exact backend error.  That is an environment
# limitation, not a launcher regression, so it skips rather than fails;
# any other nonzero exit still fails the test.
_NO_MULTIPROC = "Multiprocess computations aren't implemented on the CPU"

_SCRIPT = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed.launch import init_from_env
    init_from_env()   # idempotent: the launcher already initialized us
    import jax.numpy as jnp
    assert jax.process_count() == 2, jax.process_count()
    # one CPU device per process -> 2 global devices; psum spans BOTH
    x = jnp.ones((jax.local_device_count(),)) * (jax.process_index() + 1)
    out = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
    import sys
    sys.stdout.write(f"RANK{jax.process_index()}_PSUM={float(out[0])}\\n")
    sys.stdout.flush()
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_launch_nnodes2_global_psum(tmp_path):
    script = tmp_path / "train.py"
    script.write_text(_SCRIPT)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # children must not inherit a single-process cluster config
    for k in ["PADDLE_MASTER", "PADDLE_TRAINERS_NUM", "PADDLE_TRAINER_ID"]:
        env.pop(k, None)
    # nor the CI harness's forced 8-device CPU mesh — the proof needs
    # exactly one local device per "host" so the psum must cross processes
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "2", "--master", f"127.0.0.1:{_free_port()}",
         str(script)],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    out = r.stdout + r.stderr
    if r.returncode != 0 and _NO_MULTIPROC in out:
        pytest.skip("jaxlib CPU backend cannot run multiprocess "
                    "collectives in this container")
    assert r.returncode == 0, out[-3000:]
    # both ranks computed the same global sum 1 + 2 = 3 over the 2-process
    # device set — the collective really crossed process boundaries
    assert "RANK0_PSUM=3.0" in out, out[-3000:]
    assert "RANK1_PSUM=3.0" in out, out[-3000:]
