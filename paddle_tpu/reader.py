"""Legacy reader decorators + paddle.batch (reference python/paddle/
reader/decorator.py and batch.py).

These are pure-python generator combinators; they survive unchanged on
TPU because they run entirely on the host feeding the DataLoader.  The
multiprocess variants map onto the DataLoader's worker pool rather than
re-implementing a pipe zoo (xmap_readers/multiprocess_reader keep their
signatures and run the mapper in-process — on TPU hosts the win of those
decorators was CPU-side decode overlap, which io.DataLoader's
num_workers already provides).
"""
from __future__ import annotations

import itertools
import random as _random
from typing import Callable

__all__ = ["batch", "cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader",
           "retry_reader"]


def retry_reader(reader: Callable, max_attempts: int = 3,
                 retryable=(OSError,), base_delay: float = 0.05,
                 sleep=None):
    """Absorb transient errors from a flaky reader (resilience layer).

    Remote/filesystem-backed readers raise transient ``OSError``s under
    the fleet-style workload.  A generator is dead the moment it raises,
    so a plain retry loses the epoch; this combinator re-creates the
    underlying iterator and fast-forwards past the samples already
    delivered, with exponential backoff between attempts.  The error
    budget resets after each successfully delivered sample, so one flaky
    sample can't starve a long epoch.  Non-retryable exceptions propagate
    immediately; when the budget is exhausted a
    :class:`~paddle_tpu.utils.retry.RetriesExhausted` (an ``OSError``)
    carrying the attempt count is raised, chained to the final
    underlying error."""
    from .utils.retry import RetriesExhausted, RetryPolicy

    policy = RetryPolicy(max_attempts=max_attempts, base_delay=base_delay,
                         retryable=tuple(retryable),
                         **({"sleep": sleep} if sleep is not None else {}))

    def robust():
        delivered = 0
        failures = 0
        while True:
            it = reader()
            try:
                for i, sample in enumerate(it):
                    if i < delivered:
                        continue  # replayed prefix after a retry
                    yield sample
                    delivered += 1
                    failures = 0
                return
            except policy.retryable as e:
                failures += 1
                if failures >= policy.max_attempts:
                    raise RetriesExhausted(
                        f"reader failed after {failures} attempt(s) at "
                        f"sample {delivered}; last error: {e!r}") from e
                policy.sleep(policy.delay(failures))
    return robust


def batch(reader: Callable, batch_size: int, drop_last: bool = False,
          retries: int = 0):
    """paddle.batch (reference batch.py:18): group samples into lists.

    ``retries > 0`` wraps the sample fetch in :func:`retry_reader` so up
    to ``retries`` consecutive transient ``OSError``s per sample are
    absorbed instead of killing the epoch."""
    if retries:
        reader = retry_reader(reader, max_attempts=retries + 1)

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def cache(reader: Callable):
    """Cache all samples in memory on first pass (decorator.py:52).
    The cache commits atomically: a reader that raises mid-pass leaves
    nothing cached, so a retry re-reads from scratch (no duplicates)."""
    data = []
    filled = []

    def cached():
        if not filled:
            fresh = list(reader())      # all-or-nothing
            data.extend(fresh)
            filled.append(True)
        return iter(data)
    return cached


def map_readers(func: Callable, *readers):
    """Zip readers, map func over the tuples (decorator.py:92)."""
    def mapped():
        its = [r() for r in readers]
        for args in zip(*its):
            yield func(*args)
    return mapped


def shuffle(reader: Callable, buf_size: int):
    """Buffered shuffle (decorator.py:134)."""
    def shuffled():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    """Concatenate readers end to end (decorator.py:183)."""
    def chained():
        return itertools.chain(*(r() for r in readers))
    return chained


def compose(*readers, **kwargs):
    """Zip readers into flat tuples (decorator.py:248).
    check_alignment=True raises when readers run out unevenly."""
    check_alignment = kwargs.pop("check_alignment", True)

    def _flatten(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    _END = object()

    def composed():
        its = [r() for r in readers]
        if check_alignment:
            # zip() would silently eat one extra element from earlier
            # readers; a sentinel-padded zip sees EVERY ragged tail
            for items in itertools.zip_longest(*its, fillvalue=_END):
                if any(i is _END for i in items):
                    raise ValueError("readers have different lengths "
                                     "(check_alignment=True)")
                yield sum((_flatten(i) for i in items), ())
        else:
            for items in itertools.zip_longest(*its, fillvalue=_END):
                yield sum((_flatten(i) for i in items if i is not _END),
                          ())
    return composed


def buffered(reader: Callable, size: int):
    """Read-ahead buffer (decorator.py:308) — the DataLoader prefetch
    thread is the TPU-native version; kept for API parity as a pass-through
    buffer."""
    def buffered_reader():
        buf = []
        it = reader()
        for sample in it:
            buf.append(sample)
            if len(buf) >= size:
                yield from buf
                buf = []
        yield from buf
    return buffered_reader


def firstn(reader: Callable, n: int):
    """First n samples (decorator.py:367)."""
    def firstn_reader():
        return itertools.islice(reader(), n)
    return firstn_reader


def xmap_readers(mapper: Callable, reader: Callable, process_num: int,
                 buffer_size: int, order: bool = False):
    """Signature-compatible mapper (decorator.py:412); the mapper runs
    in-process — use io.DataLoader(num_workers=...) for real host
    parallelism on TPU machines."""
    def xmapped():
        for sample in reader():
            yield mapper(sample)
    return xmapped


def multiprocess_reader(readers, use_pipe: bool = True,
                        queue_size: int = 1000):
    """Signature-compatible merge of readers (decorator.py:505),
    sequential in-process; see xmap_readers note."""
    def merged():
        for r in readers:
            yield from r()
    return merged
