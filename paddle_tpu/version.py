"""paddle.version analog (reference: python/paddle/version.py generated at
build time — full_version/major/minor/patch/rc + show())."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "tpu-native"
with_gpu = "OFF"          # source-compat fields: this build targets TPU
with_tpu = "ON"


def show():
    print(f"full_version: {full_version}")  # noqa: print
    print(f"commit: {commit}")  # noqa: print
    print(f"with_tpu: {with_tpu}")  # noqa: print


def cuda():
    return False


def cudnn():
    return False
