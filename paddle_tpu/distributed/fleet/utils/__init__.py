"""fleet.utils (reference python/paddle/distributed/fleet/utils/):
``recompute`` (the reference's canonical import path,
fleet/utils/recompute.py:331) plus the hybrid_parallel_util helpers that
remain meaningful on TPU — the grad-sync fns are GSPMD-derived no-ops
kept for ported-script compatibility."""
from __future__ import annotations

from ..recompute import recompute, recompute_wrapper  # noqa: F401

__all__ = ["recompute", "recompute_wrapper", "fused_allreduce_gradients",
           "broadcast_dp_parameters", "broadcast_mp_parameters"]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """≙ hybrid_parallel_util.py:156 — under GSPMD the data-parallel grad
    all-reduce is emitted by the partitioner; nothing to do eagerly."""
    return None


def broadcast_dp_parameters(model, hcg=None):
    """≙ hybrid_parallel_util.py:128 — parameters created under a shared
    seed are already consistent; replicated placement is the broadcast."""
    return None


def broadcast_mp_parameters(model, hcg=None):
    return None
