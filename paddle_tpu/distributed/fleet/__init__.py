"""fleet: the unified distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py — ``Fleet``
(:139), ``init``:206, ``distributed_optimizer``:875, ``distributed_model``:932
— plus ``DistributedStrategy`` (distributed_strategy.py:109, proto-backed,
framework/distributed_strategy.proto:276-336).

TPU-native: ``fleet.init(strategy)`` turns hybrid_configs degrees into a
named ``jax.sharding.Mesh`` (the whole of the reference's per-axis NCCL group
zoo); ``distributed_model`` places parameters by their PartitionSpecs;
``distributed_optimizer`` wraps the optimizer with the hybrid global-norm
clip.  The strategy object keeps the reference's field names so fleet user
scripts port mechanically.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from ...framework.errors import enforce
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        get_hybrid_communicate_group, get_mesh,
                        set_hybrid_communicate_group)
from ..parallel import device_put_sharded_variables, get_rank, get_world_size
from .recompute import recompute
from . import utils  # noqa: F401  (fleet.utils.recompute import path)
from . import meta_parallel  # noqa: F401  (ported-script import path)

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "get_mesh", "recompute", "worker_index", "worker_num"]


class DistributedStrategy:
    """Reference distributed_strategy.proto fields that are meaningful on
    TPU.  amp/recompute carry config dicts; hybrid_configs carries the mesh
    degrees (proto :328)."""

    def __init__(self):
        self.amp = False
        self.amp_configs: Dict[str, Any] = {}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "micro_batch_size": 1}
        self.hybrid_configs: Dict[str, int] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "ep_degree": 1,
        }
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}
        # ISSUE 8: how gradient-sync collectives ship their payload —
        # CommConfig fields (dtype/bits/block_size/error_feedback/
        # min_size_to_compress); installed as the process-wide default by
        # fleet.init so comm.all_reduce/sync_gradients compress without
        # per-call plumbing.  Empty dict = exact fp32.
        self.comm_configs: Dict[str, Any] = {}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


_strategy: Optional[DistributedStrategy] = None


def _amp_dtype(amp_configs) -> str:
    """amp dtype default: bfloat16 (the TPU compute dtype) unless the
    config asks for fp16 (use_fp16_guard is the reference's fp16 knob)."""
    cfg = amp_configs or {}
    return cfg.get("dtype",
                   "float16" if cfg.get("use_fp16_guard") else "bfloat16")


def _sharding_stage(sharding_configs) -> int:
    cfg = sharding_configs or {}
    return int(cfg.get("stage", cfg.get("sharding_stage", 1)))


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None) -> None:
    """Build the hybrid mesh from strategy.hybrid_configs
    (reference fleet_base.py:206 + topology build at :279-311)."""
    global _strategy
    _strategy = strategy or DistributedStrategy()
    cfg = _strategy.hybrid_configs
    degrees = {
        "data": int(cfg.get("dp_degree", 1)),
        "pipe": int(cfg.get("pp_degree", 1)),
        "sharding": int(cfg.get("sharding_degree", 1)),
        "sequence": int(cfg.get("sp_degree", 1)),
        "expert": int(cfg.get("ep_degree", 1)),
        "model": int(cfg.get("mp_degree", 1)),
    }
    # auto-fill dp like the reference launcher: unset (-1) → devices / rest
    n_dev = jax.device_count()
    rest = 1
    for k, v in degrees.items():
        if k != "data":
            rest *= v
    if degrees["data"] <= 0:
        enforce(n_dev % rest == 0, "device count not divisible by degrees")
        degrees["data"] = n_dev // rest
    # drop degenerate axes except data (keep 'dp' so batch specs always work)
    names = [n for n in ("data", "pipe", "sharding", "sequence", "expert",
                         "model")
             if degrees[n] > 1 or n in ("data", "model")]
    dims = [degrees[n] for n in names]
    topo = CommunicateTopology(names, dims)
    # DCN factors for multi-slice pods: hybrid_configs dcn_<axis>_degree
    # says how much of that axis spans slices (scaling-book recipe: dp/pp
    # over DCN, everything else inside a slice)
    dcn = {}
    for name, short in (("data", "dp"), ("pipe", "pp"),
                        ("sharding", "sharding")):
        d = int(cfg.get(f"dcn_{short}_degree", 1))
        if d > 1:
            dcn[name] = d
    set_hybrid_communicate_group(
        HybridCommunicateGroup(topo, dcn_dims=dcn or None))
    # ISSUE 8: strategy.comm_configs → the process-wide CommConfig, so a
    # training script flips to compressed gradient sync with one line
    # (`strategy.comm_configs = {"dtype": "int8", "error_feedback": True}`)
    from ..comm.config import set_default_comm_config
    set_default_comm_config(_strategy.comm_configs or None)


def fleet_initialized() -> bool:
    return get_hybrid_communicate_group() is not None


def _enable_recompute(model, configs):
    """strategy.recompute → rematerialization on the model (reference
    meta_optimizers/recompute_optimizer.py:20, dygraph side
    fleet/utils/recompute.py).  Models that understand recompute natively
    (GPT: ``_use_recompute``) get the flag flipped; otherwise every direct
    child of each LayerList/Sequential — the transformer-block granularity
    the reference's ``checkpoints`` list names — has its forward wrapped
    in ``jax.checkpoint``."""
    policy = (configs or {}).get("policy")
    from ...nn.layer import Layer
    native = [l for l in model.sublayers(include_self=True)
              if hasattr(l, "_use_recompute")]
    if native:
        for l in native:
            l._use_recompute = True
            if policy is not None and hasattr(l, "_recompute_policy"):
                l._recompute_policy = policy
        return model

    def _wrap(layer):
        if getattr(layer, "_fleet_recompute", False):
            return
        fwd = layer.forward
        plist = [p for _, p in layer.named_parameters()]

        def wrapped(*args, **kw):
            # params ride through jax.checkpoint as explicit inputs (a
            # closure over them would leak tracers into the remat replay)
            vals = [p.value for p in plist]

            def inner(vals, *args):
                old = [p.value for p in plist]
                for p, v in zip(plist, vals):
                    p.value = v
                try:
                    return fwd(*args, **kw)
                finally:
                    for p, o in zip(plist, old):
                        p.value = o

            return recompute(inner, vals, *args, policy=policy)

        layer.forward = wrapped
        layer._fleet_recompute = True

    def _walk(layer, covered):
        # wrap children of the OUTERMOST container on each path only —
        # nesting checkpoints multiplies recompute FLOPs for no memory win
        is_container = type(layer).__name__ in ("LayerList", "Sequential")
        for child in layer._sub_layers.values():
            if not isinstance(child, Layer):
                continue
            if is_container and not covered:
                _wrap(child)
                _walk(child, True)
            else:
                _walk(child, covered)

    _walk(model, False)
    return model


def _amp_wrap_model(model, configs):
    """strategy.amp → run the model's forward under auto_cast (reference
    amp_optimizer.py rewrites the program with cast ops; here the amp
    policy state drives the white/black-listed op casts).  O2
    (``use_pure_fp16``) additionally casts parameters to the amp dtype."""
    from ... import amp as amp_mod
    cfg = dict(configs or {})
    dtype = _amp_dtype(cfg)
    level = "O2" if cfg.get("use_pure_fp16") else "O1"
    if level == "O2":
        amp_mod.decorate(model, level="O2", dtype=dtype)
    if getattr(model, "_fleet_amp", False):
        return model
    fwd = model.forward

    def _amp_forward(*a, **kw):
        with amp_mod.auto_cast(True, cfg.get("custom_white_list"),
                               cfg.get("custom_black_list"),
                               level=level, dtype=dtype):
            return fwd(*a, **kw)

    model.forward = _amp_forward
    model._fleet_amp = True
    return model


def distributed_model(model):
    """Wrap/place the model for the hybrid mesh (reference fleet_base.py:932
    wrap selection :1027-1062).  Sharding/DP/TP collapse into one GSPMD
    program, so those cases just place parameters per their specs; with
    pp_degree > 1 and a pipeline-capable model this returns the
    PipelineParallel-style wrapper (GPTPipeline) whose ``train_batch``
    runs the 1F1B schedule.  strategy.recompute / strategy.amp /
    strategy.sharding(stage 3) are honored here — the meta-optimizer
    composition of fleet_base.py:1027."""
    enforce(fleet_initialized(), "call fleet.init() first")
    strat = _strategy or DistributedStrategy()
    if strat.recompute:
        _enable_recompute(model, strat.recompute_configs)
    if strat.amp:
        _amp_wrap_model(model, strat.amp_configs)
    if strat.sharding and _sharding_stage(strat.sharding_configs) >= 3:
        from ..sharding import shard_params_stage3
        mesh = get_mesh()
        if mesh is not None:
            axis = "sharding" if "sharding" in mesh.axis_names else "dp"
            shard_params_stage3(model, mesh, axis)
    mesh = get_mesh()
    pp = int(mesh.shape.get("pp", 1)) if mesh is not None else 1
    if pp > 1:
        enforce(hasattr(model, "build_pipeline"),
                f"pp_degree={pp} but {type(model).__name__} has no "
                "build_pipeline — a non-pipeline model under a pp mesh "
                "would silently replicate the whole computation across "
                "the pp axis (reference raises likewise)")
        micro = int((_strategy.pipeline_configs or {}).get(
            "accumulate_steps", pp)) if _strategy else pp
        return model.build_pipeline(pp, micro)
    return device_put_sharded_variables(model)


def distributed_optimizer(optimizer,
                          strategy: Optional[DistributedStrategy] = None,
                          model=None):
    """Wrap the optimizer per the strategy (reference fleet_base.py:875 →
    the meta-optimizer stack).  On TPU the DP grad all-reduce is
    GSPMD-derived; what the wrapper adds is strategy.amp (dynamic loss
    scaling + skip-on-inf), strategy.gradient_merge (k-step grad
    accumulation usable with or without pp) and strategy.sharding (ZeRO
    optimizer-state sharding at init).  With no strategy flags set the
    inner optimizer is returned unwrapped — ClipGradByGlobalNorm already
    computes the global norm under pjit (unlike the reference's per-group
    manual allreduces, hybrid_parallel_optimizer.py:45)."""
    enforce(fleet_initialized(), "call fleet.init() first")
    strat = strategy or _strategy or DistributedStrategy()
    if strat.amp or strat.gradient_merge or strat.sharding:
        from .optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, strat, model=model)
    return optimizer


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


# ---------------------------------------------------------------------------
# Reference fleet __all__ parity: the module-level facade object, util
# base, role makers (single-controller jax.distributed owns rendezvous;
# the role surface answers identity queries), and the PS-era data
# generators (config/format surface; PS compute is out of scope per
# SURVEY A11 — documented in docs/MIGRATION.md).
# ---------------------------------------------------------------------------
import enum as _enum
import sys as _sys


class Role(_enum.IntEnum):
    """Reference fleet.base.role_maker.Role."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class UtilBase:
    """Reference fleet.UtilBase: cross-worker util helpers.  These are
    HOST-side (eager) utilities, so the cross-process path rides
    multihost_utils.process_allgather, not the in-program mesh
    collectives (which only exist inside shard_map/jit)."""

    def all_gather(self, input, comm_world: str = "worker"):  # noqa: A002
        import numpy as _np
        if jax.process_count() == 1:
            return [input]
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(
            _np.asarray(input), tiled=False)
        return [_np.asarray(g) for g in gathered]

    def all_reduce(self, input, mode: str = "sum",  # noqa: A002
                   comm_world: str = "worker"):
        import numpy as _np
        parts = _np.stack([_np.asarray(p) for p in self.all_gather(input)])
        ops = {"sum": _np.sum, "min": _np.min, "max": _np.max}
        enforce(mode in ops, f"all_reduce mode must be one of {list(ops)}")
        return ops[mode](parts, axis=0)

    def barrier(self, comm_world: str = "worker"):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("fleet_util_barrier")

    def get_file_shard(self, files):
        """Shard a file list over workers with a balanced remainder
        (reference UtilBase.get_file_shard: 5 files / 4 workers →
        [2, 1, 1, 1], no idle worker while others hold 2)."""
        n = jax.process_count()
        i = jax.process_index()
        base, rem = divmod(len(files), n)
        start = i * base + min(i, rem)
        return files[start:start + base + (1 if i < rem else 0)]

    def print_on_rank(self, message: str, rank_id: int = 0):
        if jax.process_index() == rank_id:
            print(message)  # noqa: print


class PaddleCloudRoleMaker:
    """Reference role_maker.PaddleCloudRoleMaker: env-derived identity.
    jax.distributed owns rendezvous; this answers the identity queries
    ported scripts make."""

    def __init__(self, is_collective: bool = True, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self) -> int:
        return jax.process_index()

    def _worker_num(self) -> int:
        return jax.process_count()

    worker_index = _worker_index
    worker_num = _worker_num

    def _role(self):
        return Role.WORKER

    def _is_first_worker(self) -> bool:
        return jax.process_index() == 0

    is_first_worker = _is_first_worker

    def _server_num(self) -> int:
        return 0        # no parameter servers on this stack


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective: bool = True, init_gloo: bool = False,
                 **kwargs):
        super().__init__(is_collective)
        self._kwargs = kwargs


class MultiSlotDataGenerator:
    """Reference fleet MultiSlotDataGenerator: line-protocol generator
    for slot data files.  The generate/run machinery works (it is plain
    text IO); feeding a parameter server does not exist here."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclass MultiSlotDataGenerator and implement "
            "generate_sample(line) -> iterable of (name, values) lists")

    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_stdin(self):
        for line in _sys.stdin:
            for sample in self.generate_sample(line)():
                _sys.stdout.write(self._format(sample) + "\n")

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            for sample in self.generate_sample(line)():
                out.append(self._format(sample))
        return out


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant: the line protocol is identical (values are
    str()-ed either way); the class exists for the reference surface."""


class Fleet:
    """Reference fleet.Fleet: the class behind the module-level facade.
    An instance delegates to this module's functions, so
    `fleet.Fleet().init(...)` ≡ `fleet.init(...)`."""

    def __init__(self):
        self.util = UtilBase()

    def __getattr__(self, name):
        mod = _sys.modules[__name__]
        if hasattr(mod, name):
            return getattr(mod, name)
        raise AttributeError(name)


__all__ += ["Role", "UtilBase", "PaddleCloudRoleMaker",
            "UserDefinedRoleMaker", "MultiSlotDataGenerator",
            "MultiSlotStringDataGenerator", "Fleet"]
