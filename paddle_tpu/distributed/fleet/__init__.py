"""fleet: the unified distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py — ``Fleet``
(:139), ``init``:206, ``distributed_optimizer``:875, ``distributed_model``:932
— plus ``DistributedStrategy`` (distributed_strategy.py:109, proto-backed,
framework/distributed_strategy.proto:276-336).

TPU-native: ``fleet.init(strategy)`` turns hybrid_configs degrees into a
named ``jax.sharding.Mesh`` (the whole of the reference's per-axis NCCL group
zoo); ``distributed_model`` places parameters by their PartitionSpecs;
``distributed_optimizer`` wraps the optimizer with the hybrid global-norm
clip.  The strategy object keeps the reference's field names so fleet user
scripts port mechanically.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax

from ...framework.errors import enforce
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        get_hybrid_communicate_group, get_mesh,
                        set_hybrid_communicate_group)
from ..parallel import device_put_sharded_variables, get_rank, get_world_size
from .recompute import recompute

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "get_mesh", "recompute", "worker_index", "worker_num"]


class DistributedStrategy:
    """Reference distributed_strategy.proto fields that are meaningful on
    TPU.  amp/recompute carry config dicts; hybrid_configs carries the mesh
    degrees (proto :328)."""

    def __init__(self):
        self.amp = False
        self.amp_configs: Dict[str, Any] = {}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "micro_batch_size": 1}
        self.hybrid_configs: Dict[str, int] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "ep_degree": 1,
        }
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None) -> None:
    """Build the hybrid mesh from strategy.hybrid_configs
    (reference fleet_base.py:206 + topology build at :279-311)."""
    global _strategy
    _strategy = strategy or DistributedStrategy()
    cfg = _strategy.hybrid_configs
    degrees = {
        "data": int(cfg.get("dp_degree", 1)),
        "pipe": int(cfg.get("pp_degree", 1)),
        "sharding": int(cfg.get("sharding_degree", 1)),
        "sequence": int(cfg.get("sp_degree", 1)),
        "expert": int(cfg.get("ep_degree", 1)),
        "model": int(cfg.get("mp_degree", 1)),
    }
    # auto-fill dp like the reference launcher: unset (-1) → devices / rest
    n_dev = jax.device_count()
    rest = 1
    for k, v in degrees.items():
        if k != "data":
            rest *= v
    if degrees["data"] <= 0:
        enforce(n_dev % rest == 0, "device count not divisible by degrees")
        degrees["data"] = n_dev // rest
    # drop degenerate axes except data (keep 'dp' so batch specs always work)
    names = [n for n in ("data", "pipe", "sharding", "sequence", "expert",
                         "model")
             if degrees[n] > 1 or n in ("data", "model")]
    dims = [degrees[n] for n in names]
    topo = CommunicateTopology(names, dims)
    # DCN factors for multi-slice pods: hybrid_configs dcn_<axis>_degree
    # says how much of that axis spans slices (scaling-book recipe: dp/pp
    # over DCN, everything else inside a slice)
    dcn = {}
    for name, short in (("data", "dp"), ("pipe", "pp"),
                        ("sharding", "sharding")):
        d = int(cfg.get(f"dcn_{short}_degree", 1))
        if d > 1:
            dcn[name] = d
    set_hybrid_communicate_group(
        HybridCommunicateGroup(topo, dcn_dims=dcn or None))


def fleet_initialized() -> bool:
    return get_hybrid_communicate_group() is not None


def distributed_model(model):
    """Wrap/place the model for the hybrid mesh (reference fleet_base.py:932
    wrap selection :1027-1062).  Sharding/DP/TP collapse into one GSPMD
    program, so those cases just place parameters per their specs; with
    pp_degree > 1 and a pipeline-capable model this returns the
    PipelineParallel-style wrapper (GPTPipeline) whose ``train_batch``
    runs the 1F1B schedule."""
    enforce(fleet_initialized(), "call fleet.init() first")
    mesh = get_mesh()
    pp = int(mesh.shape.get("pp", 1)) if mesh is not None else 1
    if pp > 1:
        enforce(hasattr(model, "build_pipeline"),
                f"pp_degree={pp} but {type(model).__name__} has no "
                "build_pipeline — a non-pipeline model under a pp mesh "
                "would silently replicate the whole computation across "
                "the pp axis (reference raises likewise)")
        micro = int((_strategy.pipeline_configs or {}).get(
            "accumulate_steps", pp)) if _strategy else pp
        return model.build_pipeline(pp, micro)
    return device_put_sharded_variables(model)


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """Wrap the optimizer for hybrid parallelism (reference fleet_base.py:875
    → HybridParallelOptimizer).  On TPU the DP grad all-reduce and ZeRO state
    sharding are GSPMD-derived; what remains real is the global-norm clip
    semantics, which ClipGradByGlobalNorm already computes globally under
    pjit (unlike the reference's per-group manual allreduces,
    hybrid_parallel_optimizer.py:45)."""
    enforce(fleet_initialized(), "call fleet.init() first")
    return optimizer


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()
