"""Strategy-driven optimizer composition.

Reference: fleet_base.py:875 ``distributed_optimizer`` composes the
meta-optimizer stack from ``DistributedStrategy`` flags —
meta_optimizers/amp_optimizer.py:20 (dynamic loss scaling + skip-on-inf),
gradient_merge_optimizer.py:20 (k-step gradient accumulation),
sharding_optimizer.py:45 (ZeRO state sharding) — plus the dygraph
``HybridParallelOptimizer`` (hybrid_parallel_optimizer.py:216).

TPU-native: the composition is a pure functional wrapper around the inner
optimizer's ``init``/``apply_gradients`` contract, so the whole stack stays
jit/pjit-safe and the gradient-merge counter, loss-scale state and slot
sharding all live in ONE state pytree that shards/checkpoints like any
other.  The skip-on-inf is a ``jnp.where`` select (no host sync), exactly
how the reference's ``update_loss_scaling`` op behaves on-device.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ...amp import GradScaler
from ..sharding import shard_optimizer_state
from ..topology import get_mesh

__all__ = ["HybridParallelOptimizer"]


def _stage(sharding_configs) -> int:
    from . import _sharding_stage
    return _sharding_stage(sharding_configs)


def _tree_where(pred, a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.where(pred, x, y), a, b)


class HybridParallelOptimizer:
    """Functional meta-optimizer stack over ``inner``.

    State layout::

        {"inner": <inner opt state>,
         "amp":   {scale, good, bad}          # when strategy.amp w/ scaling
         "gm":    {"buf": grads-like, "step"} # when strategy.gradient_merge
        }

    ``apply_gradients(grads, params, state)`` applies, in order: unscale +
    found_inf check (amp), k-step accumulation (gradient_merge), inner
    update gated on ``do_update`` — parameters and inner state only change
    on real update ticks and never on a nonfinite step.
    """

    def __init__(self, inner, strategy, model=None):
        sh_cfg = dict(strategy.sharding_configs or {})
        self._zero1 = bool(strategy.sharding
                           and _stage(sh_cfg) == 1
                           and sh_cfg.get("shard_weight_update"))
        if self._zero1:
            # ISSUE 8: ZeRO-1 weight-update sharding — the inner
            # optimizer becomes a ShardedOptimizer (reduce-scatter grads,
            # 1/n-shard update, all-gather params; state placement is the
            # wrapper's own job, so the PartitionSpec pass below is off)
            from ..comm.zero import ShardedOptimizer
            if not isinstance(inner, ShardedOptimizer):
                inner = ShardedOptimizer(
                    inner, axis=sh_cfg.get("axis"),
                    comm=sh_cfg.get("comm"),
                    grad_op=sh_cfg.get("grad_op", "avg"))
        self._inner = inner
        self._strategy = strategy
        self._model = model
        from . import _amp_dtype
        amp_cfg = dict(strategy.amp_configs or {})
        dtype = _amp_dtype(amp_cfg)
        # loss scaling exists for fp16's narrow exponent; bf16 shares the
        # f32 exponent range so the scaler stays off unless asked for
        scale_on = bool(strategy.amp) and (
            dtype == "float16" or "init_loss_scaling" in amp_cfg)
        self._scaler = GradScaler(
            enable=scale_on,
            init_loss_scaling=float(amp_cfg.get("init_loss_scaling", 2.0 ** 15)),
            incr_ratio=float(amp_cfg.get("incr_ratio", 2.0)),
            decr_ratio=float(amp_cfg.get("decr_ratio", 0.5)),
            incr_every_n_steps=int(amp_cfg.get("incr_every_n_steps", 1000)),
            decr_every_n_nan_or_inf=int(
                amp_cfg.get("decr_every_n_nan_or_inf", 2)))
        gm_cfg = dict(strategy.gradient_merge_configs or {})
        self._k = int(gm_cfg.get("k_steps", 1)) \
            if strategy.gradient_merge else 1
        self._gm_avg = bool(gm_cfg.get("avg", True))
        self._shard = bool(strategy.sharding) and not self._zero1

    # -- delegation ---------------------------------------------------------
    @property
    def inner(self):
        return self._inner

    @property
    def scaler(self) -> GradScaler:
        return self._scaler

    def __getattr__(self, name):  # get_lr/set_lr/state_dict passthrough
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._inner, name)

    # -- functional contract ------------------------------------------------
    def init(self, params) -> Dict[str, Any]:
        state: Dict[str, Any] = {"inner": self._inner.init(params)}
        if self._scaler.is_enable():
            state["amp"] = self._scaler.init_state()
        if self._k > 1:
            state["gm"] = {
                "buf": jax.tree_util.tree_map(
                    lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params),
                "step": jnp.zeros((), jnp.int32),
            }
        if self._shard:
            mesh = get_mesh()
            axis = "sharding" if mesh is not None \
                and "sharding" in mesh.axis_names else "dp"
            state["inner"] = shard_optimizer_state(
                state["inner"], params_layer=self._model, mesh=mesh,
                axis=axis)
        return state

    def scale_loss(self, loss, state):
        """Multiply the loss by the current loss scale (no-op when the
        scaler is off) — call inside the loss fn before grad."""
        if isinstance(state, dict) and "amp" in state:
            return self._scaler.scale_value(loss, state["amp"])
        return loss

    def apply_gradients(self, grads, params, state, lr=None):
        new_state = dict(state)
        found_inf = jnp.zeros((), jnp.bool_)
        if "amp" in state:
            grads, found_inf = self._scaler.unscale_and_check(
                grads, state["amp"])
            new_state["amp"] = self._scaler.update_state(
                state["amp"], found_inf)

        if self._k > 1:
            _none = lambda x: x is None  # noqa: E731  (None = frozen param)
            buf, gstep = state["gm"]["buf"], state["gm"]["step"]
            acc = jax.tree_util.tree_map(
                lambda g, b: b if g is None
                else b + jnp.where(found_inf, 0.0, g.astype(jnp.float32)),
                grads, buf, is_leaf=_none)
            gstep = gstep + jnp.where(found_inf, 0, 1)
            do_update = gstep >= self._k
            scale = 1.0 / self._k if self._gm_avg else 1.0
            eff = jax.tree_util.tree_map(
                lambda g, a: None if g is None
                else (a * scale).astype(g.dtype),
                grads, acc, is_leaf=_none)
            new_state["gm"] = {
                "buf": _tree_where(do_update,
                                   jax.tree_util.tree_map(jnp.zeros_like,
                                                          acc), acc),
                "step": jnp.where(do_update, 0, gstep),
            }
        else:
            do_update = ~found_inf
            eff = grads

        upd_params, upd_inner = self._inner.apply_gradients(
            eff, params, state["inner"], lr=lr)
        new_state["inner"] = _tree_where(do_update, upd_inner,
                                         state["inner"])
        return _tree_where(do_update, upd_params, params), new_state

    def update(self, grads, params, state):
        return self.apply_gradients(grads, params, state)

    # -- stateful (dygraph-parity) path -------------------------------------
    _hp_state: Optional[Dict[str, Any]] = None

    def step(self, grads=None):
        """Eager convenience over the bound-parameter inner optimizer
        (mirrors Optimizer.step); the amp/gm state rides on ``self``."""
        from ...framework.errors import enforce
        from ...optimizer import LRScheduler
        inner = self._inner
        enforce(inner._parameters is not None,
                "stateful step() needs parameters= at construction")
        keys = inner._param_keys()
        if grads is None:
            grads = [p._grad for p in inner._parameters]
        values = dict(zip(keys, (p.value for p in inner._parameters)))
        gdict = dict(zip(keys, (None if not t.trainable else g
                                for g, t in zip(grads, inner._parameters))))
        if self._hp_state is None:
            self._hp_state = self.init(values)   # ZeRO-sharded when asked
            if inner._state is not None:         # adopt restored state
                self._hp_state["inner"] = inner._state
        lr = inner.get_lr() if isinstance(inner._lr, LRScheduler) else None
        new_values, self._hp_state = self.apply_gradients(
            gdict, values, self._hp_state, lr=lr)
        inner._state = self._hp_state["inner"]
        for p, k in zip(inner._parameters, keys):
            p.value = new_values[k]
            p._grad = None

    def clear_grad(self):
        self._inner.clear_grad()

    def state_dict(self):
        """Inner state_dict plus the wrapper's amp/gm state — a restored
        run must keep its decayed loss scale and accumulation buffer."""
        sd = dict(self._inner.state_dict())
        if self._hp_state is not None:
            sd["hybrid"] = {k: v for k, v in self._hp_state.items()
                            if k != "inner"}
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        hybrid = sd.pop("hybrid", None)
        self._inner.set_state_dict(sd)
        if hybrid is not None:
            self._hp_state = dict(hybrid)
            self._hp_state["inner"] = self._inner._state
