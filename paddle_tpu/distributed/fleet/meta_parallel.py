"""fleet.meta_parallel import-path compatibility (reference
python/paddle/distributed/fleet/meta_parallel/__init__.py): the
Megatron-style TP layers, the TP-correct RNG tracker, and the pipeline
machinery under the names ported hybrid-parallel scripts import."""
from ..mp_layers import (ColumnParallelLinear,  # noqa: F401
                         RowParallelLinear, VocabParallelEmbedding)
from ..pipeline import (gpipe_spmd, one_f_one_b_spmd,  # noqa: F401
                        split_microbatches, stack_stage_params)
from ..random import (RNGStatesTracker,  # noqa: F401
                      get_rng_state_tracker)

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "RNGStatesTracker",
           "get_rng_state_tracker", "gpipe_spmd", "one_f_one_b_spmd",
           "split_microbatches", "stack_stage_params"]
