"""Activation recompute (gradient checkpointing).

Reference: python/paddle/distributed/fleet/utils/recompute.py —
``RecomputeFunction(PyLayer)``:199 (saves RNG state, drops activations,
replays forward in backward) and the public ``recompute(function, *args)``
API :331.

TPU-native: ``jax.checkpoint`` (rematerialization) is the whole mechanism —
XLA replays the forward subgraph during the backward pass, and JAX's
functional PRNG makes the reference's save/restore of RNG state unnecessary
(the same keys are folded in on replay).  We keep the reference's API shape
and add checkpoint policies (``preserve_rng_state`` accepted for parity;
always effectively True).
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

__all__ = ["recompute", "recompute_wrapper"]

_POLICIES = {
    None: None,
    "full": None,  # recompute everything
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def recompute(function: Callable, *args, preserve_rng_state: bool = True,
              policy: Optional[str] = None, **kwargs):
    """Run ``function(*args)`` under rematerialization (reference
    recompute.py:331): activations inside are not stored for backward; they
    are recomputed, trading FLOPs for HBM — the enabling trick for the 1.3B+
    configs (BASELINE.json #4).

    ``policy`` selects what XLA may keep: None/'full' recomputes everything;
    'dots_saveable' keeps matmul outputs (cheaper backward, more memory).
    """
    fn = jax.checkpoint(function, policy=_POLICIES.get(policy))
    return fn(*args, **kwargs)


def recompute_wrapper(function: Callable, policy: Optional[str] = None):
    """Decorator form: a Layer.forward or block fn that always recomputes."""
    ck = jax.checkpoint(function, policy=_POLICIES.get(policy))

    @functools.wraps(function)
    def wrapped(*args, **kwargs):
        return ck(*args, **kwargs)

    return wrapped
