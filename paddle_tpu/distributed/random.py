"""Tensor-parallel RNG state tracking.

Reference: fleet/meta_parallel/parallel_layers/random.py:32
``RNGStatesTracker`` + ``get_rng_state_tracker``:82 — Megatron-style seed
bookkeeping so that (a) dropout on *replicated* activations uses the same
mask on every mp rank, and (b) dropout on *sharded* activations uses a
different mask per mp rank (otherwise the "random" mask would be correlated
across the hidden-dim shards).

TPU-native design: states are threefry keys, not generator snapshots.  A
named state is a base key; drawing from it folds in a per-state counter and —
for ``local`` states — the device's mesh-axis index (``lax.axis_index``),
which is a traced value, so one jitted SPMD program yields per-device
distinct masks deterministically.  This is the same counter-based scheme the
reference's fused kernels use (fused_dropout_common.h GetSeedDataAndIncrement)
lifted to the framework level.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

import jax
from jax import lax

from ..framework import random as fw_random
from ..framework.errors import enforce

__all__ = ["RNGStatesTracker", "get_rng_state_tracker",
           "model_parallel_random_seed", "MODEL_PARALLEL_RNG"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    """Named key streams with scoped activation (reference random.py:32)."""

    def __init__(self):
        self._states: Dict[str, jax.Array] = {}
        self._seeds: Dict[str, int] = {}
        self._counters: Dict[str, int] = {}
        self._local_axes: Dict[str, Optional[str]] = {}
        self._tls = threading.local()

    def reset(self):
        self._states.clear()
        self._seeds.clear()
        self._counters.clear()
        self._local_axes.clear()

    def get_states_tracker(self):
        return dict(self._states)

    def set_states_tracker(self, states):
        self._states = dict(states)

    def add(self, name: str, seed: int, local_axis: Optional[str] = None):
        """Register a named stream.  ``local_axis``: mesh axis whose index is
        folded into every draw → per-shard-distinct randomness (the
        reference's `seed + tp_rank` trick, random.py:42-47)."""
        enforce(name not in self._states, f"rng state {name!r} already exists")
        self._states[name] = jax.random.key(seed)
        self._seeds[name] = seed
        self._counters[name] = 0
        self._local_axes[name] = local_axis

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        """Ops drawing via framework op_key() inside this scope use the named
        stream (reference rng_state contextmanager, random.py:52)."""
        enforce(name in self._states, f"unknown rng state {name!r}")
        prev = getattr(self._tls, "active", None)
        self._tls.active = name
        try:
            yield
        finally:
            self._tls.active = prev

    def active_name(self) -> Optional[str]:
        return getattr(self._tls, "active", None)

    def draw_key(self, name: str, base: Optional[jax.Array] = None) -> jax.Array:
        """One key from the named stream.

        ``base`` is the (possibly traced) key_scope-derived per-op key: when
        given, the stream only folds its seed on top, so under jit the
        per-step entropy stays traced (a concrete key here would be baked
        into the compiled program as a constant → identical dropout masks
        every step).  Without a base (eager mode) the stream's own counter
        provides per-draw variation."""
        if base is not None:
            key = jax.random.fold_in(base, self._seeds[name])
        else:
            key = jax.random.fold_in(self._states[name], self._counters[name])
            self._counters[name] += 1
        axis = self._local_axes[name]
        if axis is not None:
            try:
                key = jax.random.fold_in(key, lax.axis_index(axis))
            except (NameError, KeyError, ValueError):
                pass  # outside shard_map: single shard, no offset needed
        return key


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed: int = 100):
    """Seed the global + model-parallel streams (reference random.py:82
    model_parallel_random_seed): 'global' is identical across mp ranks,
    MODEL_PARALLEL_RNG differs per mp rank."""
    _tracker.reset()
    fw_random.seed(seed)
    _tracker.add("global_seed", seed)
    _tracker.add(MODEL_PARALLEL_RNG, seed + 2718, local_axis="mp")


# hook the framework op_key() path: when a tracker scope is active, stochastic
# ops (F.dropout etc.) draw from the named stream instead of the global one.
def _tracked_op_key(scope_key=None):
    name = _tracker.active_name()
    if name is not None:
        return _tracker.draw_key(name, base=scope_key)
    return None


fw_random.set_op_key_provider(_tracked_op_key)
