"""Hybrid-parallel topology: named device meshes.

Reference: python/paddle/distributed/fleet/base/topology.py —
``CommunicateTopology`` (:52, cartesian coords over [dp, pp, sharding, mp])
and ``HybridCommunicateGroup`` (:133, per-axis process groups).

TPU-native design: an axis is a dimension of a ``jax.sharding.Mesh``, not a
set of NCCL communicators.  A "process group" is just a mesh-axis name that
collectives reference (``jax.lax.psum(x, 'mp')``) and GSPMD partitions over
(``PartitionSpec('dp', None)``).  The cartesian-coordinate bookkeeping the
reference does by hand is what ``Mesh`` *is*; what we keep is the naming
scheme and the rank/degree query API so fleet-style user code ports 1:1.

Axis order on the physical device list is [dp, pp, sharding, mp] —
outermost-to-innermost, so mp (highest-bandwidth collectives, per-layer
all-reduces) lands on adjacent devices (ICI neighbors on a real slice) and dp
(one all-reduce per step, overlappable) spans the slowest links (DCN between
slices), matching how the reference lays out nccl rings hierarchically
(distributed_strategy.proto:292-293 hierarchical allreduce).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from ..framework.errors import InvalidArgumentError, enforce

__all__ = [
    "CommunicateTopology", "HybridCommunicateGroup",
    "get_hybrid_communicate_group", "set_hybrid_communicate_group",
    "get_mesh", "axis_size", "axis_index",
]


class CommunicateTopology:
    """Axis-name → degree bookkeeping (reference topology.py:52)."""

    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe", "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        enforce(len(hybrid_group_names) == len(dims),
                "names and dims must align")
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self._world_size = int(np.prod(self._dims)) if self._dims else 1
        self._coord_array = np.arange(self._world_size).reshape(self._dims)

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world_size

    def get_rank(self, **kwargs) -> int:
        """Coordinate dict → linear rank."""
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return int(self._coord_array[coord])

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return tuple(int(c) for c in
                     np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        ax = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[ax] = index
        return [int(r) for r in self._coord_array[tuple(sl)].ravel()]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Groups of ranks that communicate along ``axis_name`` (every other
        coordinate fixed) — the reference's per-axis communicator lists."""
        ax = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._coord_array, ax, -1)
        return [list(map(int, row)) for row in moved.reshape(-1, self._dims[ax])]


# canonical mesh-axis names for the jax Mesh (short forms used in
# PartitionSpecs throughout the framework)
_AXIS_SHORT = {"data": "dp", "pipe": "pp", "sharding": "sharding",
               "model": "mp", "expert": "ep", "sequence": "sp"}


class HybridCommunicateGroup:
    """Builds the jax Mesh and answers per-axis rank/size queries
    (reference topology.py:133 HybridCommunicateGroup).

    The reference creates one NCCL group per axis per coordinate-slice; here
    the single Mesh carries all axes and XLA derives every "group" from the
    PartitionSpec/psum axis names at compile time.
    """

    def __init__(self, topology: CommunicateTopology,
                 devices: Optional[Sequence] = None,
                 dcn_dims: Optional[Dict[str, int]] = None):
        self._topo = topology
        if devices is None:
            devices = jax.devices()
        n = topology.world_size()
        enforce(len(devices) >= n,
                f"need {n} devices for topology, have {len(devices)}")
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(name) for name in names]
        axis_names = tuple(_AXIS_SHORT.get(name, name) for name in names)
        dev_array = self._device_array(list(devices[:n]), names, dims,
                                       dcn_dims)
        self.mesh = Mesh(dev_array, axis_names)
        self._axis_names = axis_names
        # the process this host drives; under single-controller SPMD every
        # device is visible, so "my rank" is only meaningful per-device —
        # keep rank 0 semantics for host-side code paths (logging, saving)
        self.global_rank = 0

    @staticmethod
    def _device_array(devices, names, dims, dcn_dims):
        """Device placement for the mesh, DCN-aware on multi-slice pods.

        Single slice (or CPU mesh): plain row-major reshape — every axis
        rides ICI.  Multi-slice (devices carry distinct ``slice_index``,
        i.e. slices joined by the data-center network): the axes named in
        ``dcn_dims`` (degree per axis; typically dp and/or pp — the
        low-volume, overlappable collectives per the scaling-book recipe)
        span slices and everything else stays inside a slice, via
        mesh_utils.create_hybrid_device_mesh.  This is the comm-backend
        topology layer the reference builds as hierarchical allreduce
        (nccl_comm_num / hierarchical_allreduce strategy fields) and
        multi-slice DCN pipelines (fleet_executor, SURVEY A5).
        """
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        if len(slice_ids) <= 1 or not dcn_dims:
            return np.asarray(devices).reshape(dims)
        from jax.experimental import mesh_utils
        num_slices = len(slice_ids)
        dcn_shape = []
        ici_shape = []
        for name, dim in zip(names, dims):
            d = int(dcn_dims.get(name, 1))
            enforce(dim % d == 0,
                    f"axis {name} degree {dim} not divisible by its DCN "
                    f"factor {d}")
            dcn_shape.append(d)
            ici_shape.append(dim // d)
        total_dcn = int(np.prod(dcn_shape))
        enforce(total_dcn == num_slices,
                f"DCN factors {dcn_shape} product {total_dcn} != "
                f"{num_slices} slices")
        return mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices,
            allow_split_physical_axes=True)

    # -- paddle-parity query API ------------------------------------------
    def topology(self) -> CommunicateTopology:
        return self._topo

    def get_parallel_mode(self) -> str:
        if self.get_model_parallel_world_size() > 1:
            return "tensor"
        if self.get_pipe_parallel_world_size() > 1:
            return "pipeline"
        if self.get_sharding_parallel_world_size() > 1:
            return "sharding"
        return "data"

    def _dim(self, long_name: str) -> int:
        try:
            return self._topo.get_dim(long_name)
        except ValueError:
            return 1

    def get_data_parallel_world_size(self) -> int:
        return self._dim("data")

    def get_model_parallel_world_size(self) -> int:
        return self._dim("model")

    def get_pipe_parallel_world_size(self) -> int:
        return self._dim("pipe")

    def get_sharding_parallel_world_size(self) -> int:
        return self._dim("sharding")

    def get_expert_parallel_world_size(self) -> int:
        return self._dim("expert")

    # ranks are per-device under SPMD; expose axis_index helpers for use
    # inside shard_map'ped code
    @staticmethod
    def get_data_parallel_rank():
        return jax.lax.axis_index("dp")

    @staticmethod
    def get_model_parallel_rank():
        return jax.lax.axis_index("mp")

    @staticmethod
    def get_stage_id():
        return jax.lax.axis_index("pp")

    def axis_names(self) -> Tuple[str, ...]:
        return self._axis_names


# ---------------------------------------------------------------------------
# Global registry (the analog of fleet's module-level _HYBRID_PARALLEL_GROUP)
# ---------------------------------------------------------------------------
_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg
    return hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def get_mesh() -> Optional[Mesh]:
    """The active hybrid mesh, or None before fleet.init()."""
    return _hcg.mesh if _hcg is not None else None


def axis_size(name: str) -> int:
    """Degree of a mesh axis (1 if the axis doesn't exist / no mesh)."""
    mesh = get_mesh()
    if mesh is None or name not in mesh.axis_names:
        return 1
    return mesh.shape[name]


def axis_index(name: str):
    """Per-device coordinate on a mesh axis — only valid inside shard_map."""
    return jax.lax.axis_index(name)
