"""Mixture-of-Experts with expert parallelism (BASELINE config #5).

Reference semantics:
- capacity-bucketed dispatch `global_scatter` / `global_gather`
  (operators/collective/global_scatter_op.cc:20, global_gather_op.cc) — an
  all-to-all that routes each token to the rank owning its assigned expert,
  bounded per-expert by a static capacity;
- `_limit_by_capacity` (distributed/models/moe/utils.py:131) — drop tokens
  beyond an expert's capacity;
- gate networks (incubate gshard/switch gates) with load-balancing aux loss.

TPU-native design: the ragged send/recv of global_scatter maps badly onto
XLA's static shapes, but its *semantics* — at most C tokens per expert,
overflow dropped — are exactly the GShard dispatch formulation: one-hot
(token, expert, slot) masks turned into einsums.  The MoE layer is therefore
pure SPMD: tokens stay sharded over dp, the stacked expert weights are
sharded over the ``ep`` mesh axis, and GSPMD inserts the all-to-alls that
global_scatter/global_gather perform by hand (they ride ICI).  The
shard_map-level ``global_scatter``/``global_gather`` primitives are also
provided for API parity and for custom dispatch experiments.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..framework.errors import enforce
from .collective import bound_axis_size
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from .collective import all_to_all
from .mp_layers import shard_constraint

__all__ = ["switch_gating", "gshard_gating", "limit_by_capacity",
           "global_scatter", "global_gather", "MoELayer", "ExpertFFN",
           "collect_aux_losses"]


# ---------------------------------------------------------------------------
# Aux-loss collection: MoE gate losses arise deep inside the network but
# belong in the training loss.  A trace-safe collection scope (the analog of
# the reference gathering gate losses from every MoELayer before the loss
# is formed) — a plain thread-local list of traced scalars.
# ---------------------------------------------------------------------------
import contextlib  # noqa: E402
import threading  # noqa: E402

_aux_ctx = threading.local()


@contextlib.contextmanager
def collect_aux_losses():
    """``with collect_aux_losses() as aux: ...`` — every MoELayer forward
    inside appends its load-balance loss to ``aux`` (a list of scalars)."""
    prev = getattr(_aux_ctx, "items", None)
    _aux_ctx.items = []
    try:
        yield _aux_ctx.items
    finally:
        _aux_ctx.items = prev


def _record_aux(value) -> bool:
    items = getattr(_aux_ctx, "items", None)
    if items is None:
        return False
    items.append(value)
    return True


# ---------------------------------------------------------------------------
# Gating
# ---------------------------------------------------------------------------
def limit_by_capacity(mask, capacity: int):
    """Zero out tokens beyond each expert's capacity and return their slot
    positions (first-come order along the token axis) — semantics of
    _limit_by_capacity (moe/utils.py:131) + prune_gate_by_capacity.

    mask: (T, E) one-hot-ish {0,1}.  Returns (kept_mask, positions) with
    positions ∈ [0, capacity) valid only where kept_mask is 1.
    """
    positions = jnp.cumsum(mask, axis=0) * mask - mask  # 0-based slot
    kept = mask * (positions < capacity)
    return kept, (positions * kept).astype(jnp.int32)


def _one_hot_dispatch(mask, positions, capacity: int):
    """(T, E) kept mask + slots → (T, E, C) dispatch tensor."""
    slot_oh = jax.nn.one_hot(positions, capacity, dtype=mask.dtype)
    return mask[:, :, None] * slot_oh


def switch_gating(logits, capacity: int):
    """Top-1 (Switch) gating with capacity.

    Returns (dispatch (T,E,C), combine (T,E,C), aux_loss scalar).
    aux = E * Σ_e frac_tokens_e · mean_prob_e (the Switch load-balance loss;
    ≙ the reference switch gate's balance term).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
    density = mask.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = E * jnp.sum(density * density_proxy)
    kept, pos = limit_by_capacity(mask, capacity)
    dispatch = _one_hot_dispatch(kept, pos, capacity)
    gate = jnp.sum(probs * mask, axis=-1)
    combine = gate[:, None, None] * dispatch
    return dispatch, combine, aux


def gshard_gating(logits, capacity: int):
    """Top-2 (GShard) gating with capacity; second choices queue behind all
    first choices (the reference gshard gate ordering)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)

    density = mask1.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = E * jnp.sum(density * density_proxy)

    kept1, pos1 = limit_by_capacity(mask1, capacity)
    # second choices are placed after every first choice of that expert
    first_counts = jnp.sum(kept1, axis=0, keepdims=True)      # (1, E)
    pos2_raw = jnp.cumsum(mask2, axis=0) * mask2 - mask2 + first_counts
    kept2 = mask2 * (pos2_raw < capacity)
    pos2 = (pos2_raw * kept2).astype(jnp.int32)

    gate1 = jnp.sum(probs * mask1, axis=-1)
    gate2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(gate1 + gate2, 1e-9)
    gate1, gate2 = gate1 / denom, gate2 / denom

    d1 = _one_hot_dispatch(kept1, pos1, capacity)
    d2 = _one_hot_dispatch(kept2, pos2, capacity)
    dispatch = d1 + d2
    combine = gate1[:, None, None] * d1 + gate2[:, None, None] * d2
    return dispatch, combine, aux


_GATES: Dict[str, Callable] = {"switch": switch_gating,
                               "gshard": gshard_gating}


# ---------------------------------------------------------------------------
# shard_map-level dispatch primitives (API parity with the reference ops)
# ---------------------------------------------------------------------------
def global_scatter(x, group: str = "ep"):
    """Capacity-bucketed expert dispatch across the ``group`` axis — the
    static-shape rendering of global_scatter_op.cc.  Call INSIDE shard_map.

    x: (E, C, ...) — this rank's tokens bucketed by destination expert
    (E = total experts).  Returns (E_local·world, C, ...) reshaped as
    (world, E_local, C, ...) → flattened to (world·C rows per local expert):
    concretely (E_local, world·C, ...) — every token now sits on the rank
    owning its expert, grouped by source rank.
    """
    world = bound_axis_size(group)
    e = x.shape[0]
    enforce(e % world == 0, f"experts {e} not divisible by ep world {world}")
    y = all_to_all(x, group, split_axis=0, concat_axis=0)
    # (world * e_local, C, ...) with source-rank major order
    e_local = e // world
    y = y.reshape(world, e_local, *y.shape[1:])
    y = jnp.moveaxis(y, 0, 1)                 # (e_local, world, C, ...)
    return y.reshape(e_local, world * y.shape[2], *y.shape[3:])


def global_gather(x, group: str = "ep"):
    """Inverse of global_scatter (≙ global_gather_op.cc): return expert
    outputs to the token's source rank.  Call INSIDE shard_map."""
    world = bound_axis_size(group)
    e_local = x.shape[0]
    c = x.shape[1] // world
    y = x.reshape(e_local, world, c, *x.shape[2:])
    y = jnp.moveaxis(y, 1, 0)                 # (world, e_local, C, ...)
    y = y.reshape(world * e_local, c, *y.shape[3:])
    return all_to_all(y, group, split_axis=0, concat_axis=0)


# ---------------------------------------------------------------------------
# Expert + layer
# ---------------------------------------------------------------------------
class ExpertFFN(Layer):
    """E stacked FFN experts, weights sharded over the ``ep`` mesh axis.
    ≙ the reference's per-rank expert list (moe/moe_layer.py experts), laid
    out as one (E, ...) tensor so a single einsum feeds every expert."""

    def __init__(self, num_experts: int, hidden_size: int, ffn_size: int,
                 ep_axis: str = "ep", weight_attr=None,
                 out_weight_attr=None, act=F.gelu):
        super().__init__()
        self.num_experts = num_experts
        self.act = act
        # separate in/out initializers: GPT-style residual scaling applies
        # only to the output projection (matches the dense GPTMLP fc_in /
        # fc_out split)
        out_weight_attr = out_weight_attr or weight_attr
        init1 = getattr(weight_attr, "initializer", None) or I.Normal(std=0.02)
        init2 = (getattr(out_weight_attr, "initializer", None)
                 or I.Normal(std=0.02))
        self.w1 = self.create_parameter(
            (num_experts, hidden_size, ffn_size),
            attr=weight_attr, default_initializer=init1)
        self.w1.pspec = P(ep_axis, None, None)
        self.b1 = self.create_parameter((num_experts, 1, ffn_size),
                                        is_bias=True)
        self.b1.pspec = P(ep_axis, None, None)
        self.w2 = self.create_parameter(
            (num_experts, ffn_size, hidden_size),
            attr=out_weight_attr, default_initializer=init2)
        self.w2.pspec = P(ep_axis, None, None)
        self.b2 = self.create_parameter((num_experts, 1, hidden_size),
                                        is_bias=True)
        self.b2.pspec = P(ep_axis, None, None)

    def forward(self, x):
        """x: (E, C, H) expert inputs → (E, C, H)."""
        w1 = self.w1.value.astype(x.dtype)
        w2 = self.w2.value.astype(x.dtype)
        h = jnp.einsum("ech,ehf->ecf", x, w1) + self.b1.value.astype(x.dtype)
        h = self.act(h)
        return (jnp.einsum("ecf,efh->ech", h, w2)
                + self.b2.value.astype(x.dtype))


class MoELayer(Layer):
    """Mixture-of-experts layer (≙ incubate.distributed.models.moe.MoELayer).

    Forward: gate → capacity-limited dispatch einsum → expert FFN (ep-sharded)
    → combine einsum.  The dispatched activations are shard-constrained
    P('ep', None, None) so GSPMD emits the global_scatter/global_gather
    all-to-alls between the token-sharded and expert-sharded layouts.

    The load-balancing aux loss reaches the training loss via an enclosing
    :func:`collect_aux_losses` scope (what GPTForCausalLM does), or via the
    second output of :meth:`forward_with_aux` — both stay inside the trace.
    """

    def __init__(self, hidden_size: int, ffn_size: int, num_experts: int,
                 *, gate: str = "gshard", capacity_factor: float = 2.0,
                 ep_axis: str = "ep", weight_attr=None,
                 out_weight_attr=None, gate_weight_attr=None,
                 dropout_p: float = 0.0):
        super().__init__()
        enforce(gate in _GATES, f"unknown gate {gate!r}; use {list(_GATES)}")
        self.num_experts = num_experts
        self.capacity_factor = float(capacity_factor)
        self.gate_type = gate
        self.ep_axis = ep_axis
        self.dropout_p = float(dropout_p)
        ginit = (getattr(gate_weight_attr, "initializer", None)
                 or I.Normal(std=0.02))
        self.gate_weight = self.create_parameter(
            (hidden_size, num_experts), attr=gate_weight_attr,
            default_initializer=ginit)
        self.gate_weight.pspec = P(None, None)
        self.experts = ExpertFFN(num_experts, hidden_size, ffn_size,
                                 ep_axis=ep_axis, weight_attr=weight_attr,
                                 out_weight_attr=out_weight_attr)

    def capacity(self, tokens: int) -> int:
        k = 2 if self.gate_type == "gshard" else 1
        return max(1, int(math.ceil(
            tokens * self.capacity_factor * k / self.num_experts)))

    def forward_with_aux(self, x) -> Tuple[Any, Any]:
        """x: (B, S, H) → (out (B, S, H), aux_loss scalar)."""
        b, s, h = x.shape
        tokens = b * s
        xt = x.reshape(tokens, h)
        cap = self.capacity(tokens)
        logits = xt.astype(jnp.float32) @ self.gate_weight.value.astype(
            jnp.float32)
        dispatch, combine, aux = _GATES[self.gate_type](logits, cap)
        dispatch = dispatch.astype(x.dtype)
        expert_in = jnp.einsum("tec,th->ech", dispatch, xt)
        expert_in = shard_constraint(expert_in, self.ep_axis, None, None)
        expert_out = self.experts(expert_in)
        expert_out = shard_constraint(expert_out, self.ep_axis, None, None)
        out = jnp.einsum("ech,tec->th", expert_out, combine.astype(x.dtype))
        out = out.reshape(b, s, h)
        if self.dropout_p > 0.0:
            # residual dropout, matching the dense FFN's trailing dropout
            out = F.dropout(out, p=self.dropout_p, training=self.training)
        return out, aux

    def forward(self, x):
        out, aux = self.forward_with_aux(x)
        _record_aux(aux)
        return out
