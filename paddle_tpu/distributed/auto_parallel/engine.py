"""auto_parallel Engine — the annotate-then-run driver.

Reference: python/paddle/distributed/auto_parallel/engine.py:50 (class
Engine; prepare/fit/evaluate/predict/save/load).  There the engine takes a
*serial* model plus shard annotations and runs the planner pipeline
(Completer -> Partitioner -> Resharder) to produce per-rank programs.  Here
GSPMD is the planner: `prepare()` compiles ONE jitted SPMD step over the
`ProcessMesh`, parameters are placed per their `pspec` annotations
(replicated by default), the batch is sharded along the mesh's first axis
(the reference's dp-leading convention, topology.py:52), and XLA's sharding
propagation completes every intermediate the user did not annotate.

The data contract matches hapi: `fit(data)` iterates (inputs, label)
batches (a `paddle_tpu.io.DataLoader` works as-is); `loss_fn(out, label)`
maps model output to a scalar.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework import random as fw_random
from ...framework.errors import enforce
from ...nn.layer import Layer
from . import ProcessMesh, get_default_mesh

__all__ = ["Engine"]


def _tuplify(x):
    if x is None:
        return ()
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


class Engine:
    """Annotate-then-run training driver over a ProcessMesh.

    Example::

        mesh = ProcessMesh(np.arange(8).reshape(2, 4).tolist(), ["dp", "mp"])
        engine = Engine(model, loss_fn=nn.functional.cross_entropy,
                        optimizer=optimizer.AdamW(1e-3), process_mesh=mesh)
        engine.prepare()
        history = engine.fit(loader, epochs=2)
    """

    def __init__(self, model: Layer, loss_fn: Optional[Callable] = None,
                 optimizer=None, metrics=None,
                 process_mesh: Optional[ProcessMesh] = None, strategy=None):
        enforce(isinstance(model, Layer),
                "Engine expects a paddle_tpu.nn.Layer model")
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.metrics = list(_tuplify(metrics))
        self.strategy = strategy
        self.process_mesh = process_mesh or get_default_mesh()
        self._mesh = (self.process_mesh.jax_mesh
                      if self.process_mesh is not None else None)
        self._prepared = False
        self._opt_state = None
        self._history: List[Dict[str, float]] = []

    # -- mesh placement ----------------------------------------------------
    def _batch_axis(self) -> Optional[str]:
        if self._mesh is None:
            return None
        return self._mesh.axis_names[0]

    def _shard_batch(self, x):
        if not isinstance(x, jax.Array):
            x = jnp.asarray(np.asarray(x))
        if self._mesh is None:
            return x
        spec = P(self._batch_axis())
        return jax.device_put(x, NamedSharding(self._mesh, spec))

    def _place_params(self):
        """Place every parameter per its pspec annotation (mp_layers and
        shard_tensor attach these); unannotated params replicate — the
        Completer role, done by placement + GSPMD propagation."""
        if self._mesh is None:
            return
        from ..mp_layers import param_sharding
        for _, p in self.model.named_parameters():
            p.value = jax.device_put(p.value, param_sharding(p, self._mesh))
        for _, sub in self.model.named_sublayers(include_self=True):
            for bname, b in list(sub._buffers.items()):
                sub._buffers[bname] = jax.device_put(
                    b, NamedSharding(self._mesh, P()))

    # -- compilation -------------------------------------------------------
    def prepare(self, mode: str = "train") -> "Engine":
        """Compile the SPMD train/eval steps (reference Engine.prepare).

        One XLA compilation replaces the reference's Completer/Partitioner/
        Resharder pipeline (SURVEY A4): annotations are placements, GSPMD
        completes the rest.
        """
        enforce(mode in ("train", "eval", "predict"), f"bad mode {mode!r}")
        if mode == "train":
            enforce(self.optimizer is not None,
                    "Engine(optimizer=...) is required for mode='train'")
        model, opt, loss_fn = self.model, self.optimizer, self.loss_fn

        def train_step(trainable, rest, opt_state, key, *data):
            *inputs, label = data

            def compute_loss(tp):
                variables = {**rest, **tp}
                with fw_random.key_scope(key):
                    out, newv = model.apply(variables, *inputs, mutable=True)
                loss = loss_fn(out, label) if loss_fn is not None else out
                return loss, (out, newv)

            (loss, (out, newv)), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(trainable)
            new_trainable, new_opt_state = opt.apply_gradients(
                grads, trainable, opt_state)
            merged = dict(newv)
            merged.update(new_trainable)
            return loss, out, merged, new_opt_state

        def eval_step(variables, *data):
            *inputs, label = data
            out = model.apply(variables, *inputs)
            loss = loss_fn(out, label) if loss_fn is not None else 0.0
            return loss, out

        def predict_step(variables, *inputs):
            return model.apply(variables, *inputs)

        from ...observability.compilation import track_jit
        self._train_step = track_jit(jax.jit(train_step),
                                     name="engine.train_step")
        self._eval_step = track_jit(jax.jit(eval_step),
                                    name="engine.eval_step")
        self._predict_step = track_jit(jax.jit(predict_step),
                                       name="engine.predict_step")
        self._place_params()
        self._prepared = True
        return self

    # -- loops -------------------------------------------------------------
    def _train_batch(self, inputs, label) -> float:
        self.model.train()
        trainable = self.model.trainable_variables()
        rest = {k: v for k, v in self.model.state_dict().items()
                if k not in trainable}
        if self._opt_state is None:
            self._opt_state = self.optimizer.init(trainable)
        data = [self._shard_batch(x) for x in (*_tuplify(inputs), label)]
        key = fw_random.next_key()
        loss, out, merged, self._opt_state = self._train_step(
            trainable, rest, self._opt_state, key, *data)
        self.model.set_state_dict(merged, strict=False)
        for m in self.metrics:
            r = m.compute(np.asarray(out), np.asarray(data[-1]))
            m.update(*(r if isinstance(r, tuple) else (r,)))
        return float(loss)

    def fit(self, train_data, epochs: int = 1,
            steps_per_epoch: Optional[int] = None,
            log_freq: int = 10, verbose: int = 1) -> List[Dict[str, float]]:
        """Reference Engine.fit: iterate (inputs, label) batches, run the
        compiled SPMD step, collect loss/metric history per epoch.

        Returns THIS call's epoch rows (epoch numbering is absolute across
        repeated fit calls; the accumulated record lives on
        ``self._history``)."""
        enforce(self.optimizer is not None,
                "Engine(optimizer=...) is required for fit()")
        if not self._prepared:
            self.prepare()
        from ...framework.log import vlog
        run_rows: List[Dict[str, float]] = []
        for _ in range(epochs):
            epoch = len(self._history)
            for m in self.metrics:
                m.reset()
            losses = []
            for step, batch in enumerate(train_data):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                *inputs, label = batch
                losses.append(self._train_batch(inputs, label))
                if verbose and log_freq and step % log_freq == 0:
                    vlog(1, f"engine.fit epoch {epoch} step {step} "
                            f"loss {losses[-1]:.4f}")
            row = {"epoch": epoch,
                   "loss": float(np.mean(losses)) if losses else 0.0}
            for m in self.metrics:
                row[m.name()] = m.accumulate()
            self._history.append(row)
            run_rows.append(row)
        return run_rows

    def evaluate(self, eval_data, steps: Optional[int] = None
                 ) -> Dict[str, float]:
        if not self._prepared:
            self.prepare(mode="eval")
        self.model.eval()
        variables = self.model.state_dict()
        for m in self.metrics:
            m.reset()
        losses = []
        for i, batch in enumerate(eval_data):
            if steps is not None and i >= steps:
                break
            *inputs, label = batch
            data = [self._shard_batch(x) for x in (*inputs, label)]
            loss, out = self._eval_step(variables, *data)
            losses.append(float(loss))
            for m in self.metrics:
                r = m.compute(np.asarray(out), np.asarray(data[-1]))
                m.update(*(r if isinstance(r, tuple) else (r,)))
        row = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self.metrics:
            row[m.name()] = m.accumulate()
        return row

    def predict(self, data, steps: Optional[int] = None) -> List[Any]:
        if not self._prepared:
            self.prepare(mode="predict")
        self.model.eval()
        variables = self.model.state_dict()
        outs = []
        for i, batch in enumerate(data):
            if steps is not None and i >= steps:
                break
            inputs = _tuplify(batch)
            outs.append(self._predict_step(
                variables, *[self._shard_batch(x) for x in inputs]))
        return outs

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        """Save model + optimizer state (per-rank shard semantics come from
        distributed.checkpoint when used under a real multi-host mesh)."""
        from ...framework import io as fio
        fio.save(self.model.state_dict(), path + ".pdparams")
        if self._opt_state is not None:
            fio.save(self._opt_state, path + ".pdopt")

    def load(self, path: str) -> None:
        from ...framework import io as fio
        self.model.set_state_dict(fio.load(path + ".pdparams"))
        try:
            self._opt_state = fio.load(path + ".pdopt")
        except (FileNotFoundError, OSError):
            pass
