"""auto_parallel: annotate-then-run sharding.

Reference: python/paddle/distributed/auto_parallel/process_mesh.py:39
(``ProcessMesh``), interface.py:34 (``shard_tensor``), interface.py:73
(``shard_op``).  There, annotations are recorded into a
DistributedContext and a planner completes/partitions the program.

TPU-native: GSPMD *is* the planner.  ``dims_mapping`` (dim i of the
tensor is split over mesh dim ``dims_mapping[i]``; -1 = not split)
translates directly to a ``PartitionSpec``; annotating is
``jax.device_put`` on concrete arrays and
``jax.lax.with_sharding_constraint`` under a trace, and XLA's SPMD
propagation pass fills in every unannotated intermediate — the role of
the reference's completion algorithm (auto_parallel/completion.py).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...framework.errors import enforce
from ..topology import get_mesh

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "get_default_mesh",
           "set_default_mesh"]


class ProcessMesh:
    """N-d array of logical process ids (reference process_mesh.py:39).

    ``topology``/``processes`` keep the reference's accessors; ``jax_mesh``
    is the TPU-native payload: a ``jax.sharding.Mesh`` over the same
    devices in the same topology, with ``dim_names`` as the axis names
    (auto-named ``d0, d1, ...`` when not given).
    """

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None):
        enforce(isinstance(mesh, (list, tuple, np.ndarray)),
                "mesh must be a (nested) list of process ids")
        self._ids = np.asarray(mesh, dtype=np.int64)
        self._dim_names = (list(dim_names) if dim_names is not None
                           else [f"d{i}" for i in range(self._ids.ndim)])
        enforce(len(self._dim_names) == self._ids.ndim,
                f"dim_names has {len(self._dim_names)} entries for a "
                f"{self._ids.ndim}-d mesh")
        self._jax_mesh: Optional[Mesh] = None

    @property
    def topology(self) -> List[int]:
        return list(self._ids.shape)

    shape = topology

    @property
    def processes(self) -> List[int]:
        return [int(i) for i in self._ids.reshape(-1)]

    process_ids = processes

    @property
    def ndim(self) -> int:
        return self._ids.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = jax.devices()
            enforce(int(self._ids.max()) < len(devices),
                    f"process id {int(self._ids.max())} out of range for "
                    f"{len(devices)} devices")
            dev_arr = np.asarray(devices, dtype=object)[self._ids]
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self._dim_names == other._dim_names)

    def __repr__(self):
        return (f"ProcessMesh(shape={self.topology}, "
                f"dim_names={self._dim_names})")


_default_mesh: Optional[ProcessMesh] = None


def set_default_mesh(mesh: Optional[ProcessMesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[ProcessMesh]:
    return _default_mesh


def _resolve_mesh(process_mesh) -> Mesh:
    """dist_attr process_mesh → jax Mesh: a ProcessMesh, a nested list
    (reference style), or None → the default ProcessMesh, else the fleet
    hybrid mesh."""
    if isinstance(process_mesh, ProcessMesh):
        return process_mesh.jax_mesh
    if process_mesh is not None:
        return ProcessMesh(process_mesh).jax_mesh
    if _default_mesh is not None:
        return _default_mesh.jax_mesh
    mesh = get_mesh()
    enforce(mesh is not None,
            "no process_mesh given and neither auto_parallel's default "
            "mesh nor the fleet mesh is initialized")
    return mesh


def _spec_from_dims_mapping(mesh: Mesh, dims_mapping: Sequence[int]) -> P:
    """dims_mapping[i] = j means tensor dim i is split over mesh dim j
    (-1 = replicated on that dim) — the reference's encoding, interface
    docstring at interface.py:40-44."""
    names = mesh.axis_names
    entries = []
    for j in dims_mapping:
        if j == -1:
            entries.append(None)
        else:
            enforce(0 <= j < len(names),
                    f"dims_mapping entry {j} out of range for mesh dims "
                    f"{names}")
            entries.append(names[j])
    used = [e for e in entries if e is not None]
    enforce(len(used) == len(set(used)),
            f"dims_mapping {list(dims_mapping)} maps one mesh dim to "
            "multiple tensor dims")
    return P(*entries)


def _annotate(x, mesh: Mesh, spec: P):
    arr = x.__jax_array__() if hasattr(x, "__jax_array__") else x
    sharding = NamedSharding(mesh, spec)
    if isinstance(arr, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(arr, sharding)
    return jax.device_put(arr, sharding)


def shard_tensor(x, dist_attr: Optional[Dict[str, Any]] = None, **kw):
    """Annotate ``x`` with a sharding (reference interface.py:34).

    ``dist_attr = {"process_mesh": ..., "dims_mapping": [0, -1]}``.
    Returns the annotated tensor: placed (eager) or constrained (traced);
    unlike the reference the annotation is carried by the array itself,
    not a side context."""
    attr = dict(dist_attr or {})
    attr.update(kw)
    mesh = _resolve_mesh(attr.get("process_mesh"))
    arr = x.__jax_array__() if hasattr(x, "__jax_array__") else jnp.asarray(x)
    dm = attr.get("dims_mapping", [-1] * arr.ndim)
    enforce(len(dm) == arr.ndim,
            f"dims_mapping has {len(dm)} entries for a {arr.ndim}-d tensor")
    return _annotate(arr, mesh, _spec_from_dims_mapping(mesh, dm))


def shard_op(op_fn: Callable, dist_attr: Optional[Dict[Any, Any]] = None):
    """Wrap ``op_fn`` so its inputs (and optionally outputs) are annotated
    before/after the call (reference interface.py:73).

    ``dist_attr`` keys: ``"process_mesh"``; per-input entries keyed by the
    tensor object itself (reference style) or by positional index; and an
    optional ``"out_dims_mappings": [ ... ]`` list for outputs.
    """
    attr = dict(dist_attr or {})
    mesh = _resolve_mesh(attr.get("process_mesh"))
    out_maps = attr.pop("out_dims_mappings", None)

    def _lookup(i, a):
        if i in attr:
            return attr[i]
        for k, v in attr.items():
            if k is a or (hasattr(k, "__jax_array__")
                          and k.__jax_array__() is a):
                return v
        return None

    def wrapper(*args, **kwargs):
        new_args = []
        for i, a in enumerate(args):
            cfg = _lookup(i, a)
            if cfg is not None and "dims_mapping" in cfg:
                a = _annotate(a, mesh,
                              _spec_from_dims_mapping(
                                  mesh, cfg["dims_mapping"]))
            new_args.append(a)
        out = op_fn(*new_args, **kwargs)
        if out_maps is not None:
            flat, tree = jax.tree_util.tree_flatten(out)
            enforce(len(flat) == len(out_maps),
                    f"out_dims_mappings has {len(out_maps)} entries for "
                    f"{len(flat)} outputs")
            flat = [o if m is None
                    else _annotate(o, mesh, _spec_from_dims_mapping(mesh, m))
                    for o, m in zip(flat, out_maps)]
            out = jax.tree_util.tree_unflatten(tree, flat)
        return out

    return wrapper


from .engine import Engine  # noqa: E402,F401

__all__.append("Engine")
