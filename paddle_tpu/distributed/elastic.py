"""Elastic / preemption handling (component D14).

Reference: fleet/elastic/manager.py ``ElasticManager``:130 — etcd node
registry with watch callbacks (:245) and lease heartbeats; on membership
change it tears down and relaunches training with rewritten endpoints.
Companion: automatic checkpointing for recovery
(fluid/incubate/checkpoint/auto_checkpoint.py).

TPU-native rendering: cluster membership is the TPU runtime's problem (a
preempted pod slice just goes away); what the framework owes the user is
**surviving preemption** — periodic async sharded checkpoints, a SIGTERM
hook that flushes one final checkpoint inside the grace window, and a
restore-on-restart that reshards into whatever topology the job came back
with (which checkpoint.load_sharded already does).  That is the whole
teardown/relaunch loop of the reference with the etcd machinery replaced
by the platform's own scheduler.

Atomic commit protocol (ISSUE 1): every save is staged into
``step-N.tmp/`` (shards + manifest fsync'd there by ``save_sharded``),
then ``os.replace``d to ``step-N/``, then the COMMITTED marker is written
and the parent directory fsync'd.  A crash at ANY point leaves either a
``.tmp`` staging dir (never eligible for restore) or a fully durable
committed step — restore can never observe a torn checkpoint.  On
restore, ``restore_or`` walks committed steps newest→oldest, quarantining
(``step-N/`` → ``step-N.corrupt/``) any that fail manifest/checksum
validation, and only falls back to a fresh init when none survive.
"""
from __future__ import annotations

import os
import shutil
import signal
import threading
from typing import Any, Callable, List, Optional

import jax

from ..framework.log import vlog
from ..utils import fsio
from .checkpoint import (AsyncSaveHandle, CheckpointCorruption, load_sharded,
                         save_sharded)

__all__ = ["ElasticTrainState", "latest_checkpoint", "committed_checkpoints"]

_STEP_PREFIX = "step-"
_TMP_SUFFIX = ".tmp"
_CORRUPT_SUFFIX = ".corrupt"


def _step_of(name: str) -> Optional[int]:
    """Step number of a ``step-N[.seq][.tmp|.corrupt]`` entry name (else
    None)."""
    if not name.startswith(_STEP_PREFIX):
        return None
    stem = name[len(_STEP_PREFIX):]
    for suffix in (_TMP_SUFFIX, _CORRUPT_SUFFIX):
        if stem.endswith(suffix):
            stem = stem[: -len(suffix)]
    stem = stem.split(".")[0]  # drop the per-save staging token
    try:
        return int(stem)
    except ValueError:
        return None


def committed_checkpoints(directory: str) -> List[str]:
    """Every committed checkpoint path under ``directory``, newest first."""
    if not os.path.isdir(directory):
        return []
    found = []
    for name in os.listdir(directory):
        if not name.startswith(_STEP_PREFIX) or name.endswith(
                (_TMP_SUFFIX, _CORRUPT_SUFFIX)):
            continue
        full = os.path.join(directory, name)
        if not os.path.exists(os.path.join(full, "COMMITTED")):
            continue  # partial write (crashed mid-save)
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        found.append((step, full))
    return [path for _, path in sorted(found, reverse=True)]


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest complete checkpoint path under ``directory`` (or None)."""
    done = committed_checkpoints(directory)
    return done[0] if done else None


class ElasticTrainState:
    """Preemption-aware checkpoint manager.

    >>> mgr = ElasticTrainState("ckpts", save_interval_steps=100)
    >>> state, start = mgr.restore_or(init_state, template_fn)
    >>> for step in range(start, total):
    ...     state = train_step(state)
    ...     mgr.maybe_save(step, state)     # async, every interval
    >>> mgr.finalize(step, state)

    On SIGTERM (the TPU preemption notice) the handler saves one final
    checkpoint synchronously before re-raising the default handler —
    restart then resumes from it, under the SAME or a DIFFERENT mesh
    (resharding-on-load).  ≙ ElasticManager's watch→checkpoint→relaunch
    cycle with the relaunch owned by the cluster scheduler.
    """

    def __init__(self, directory: str, save_interval_steps: int = 1000,
                 keep: int = 2, install_sigterm_handler: bool = True,
                 event_sink: Optional[Callable] = None):
        self.directory = directory
        self._event_sink = event_sink
        self.save_interval_steps = int(save_interval_steps)
        self.keep = keep
        self._pending: Optional[AsyncSaveHandle] = None
        self._save_seq = 0
        self._latest_state: Any = None
        self._latest_step: int = -1
        self._lock = threading.Lock()
        self._prev_handler = None
        if install_sigterm_handler:
            try:
                self._prev_handler = signal.signal(
                    signal.SIGTERM, self._on_sigterm)
            except ValueError:  # not the main thread
                self._prev_handler = None

    # -- supervision hookup (ISSUE 2) --------------------------------------
    def set_event_sink(self, sink: Optional[Callable]) -> None:
        """``sink(kind, **fields)`` — the run supervisor's report; every
        quarantine/restore decision becomes a recorded event so rollback
        can target (and post-mortems can explain) the right step."""
        self._event_sink = sink

    def _emit(self, kind: str, **fields) -> None:
        if self._event_sink is not None:
            try:
                self._event_sink(kind, **fields)
            except Exception as e:
                vlog(0, "elastic: event sink failed for %s: %s", kind, e)

    def last_good_step(self) -> int:
        """Newest committed (restorable) step number, -1 when none exist —
        the step auto-rollback will land on."""
        done = committed_checkpoints(self.directory)
        if not done:
            return -1
        return int(os.path.basename(done[0])[len(_STEP_PREFIX):])

    # -- save --------------------------------------------------------------
    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _commit(self, step: int, stage: str) -> None:
        """Promote the staging dir to a durable committed ``step-N/``."""
        final = self._path(step)
        if stage != final:
            if os.path.isdir(final):
                # leftover from an earlier crashed/uncommitted save of the
                # same step — the fresh staging dir supersedes it
                shutil.rmtree(final)
            os.replace(stage, final)
        # multi-host: every process wrote its own shards straight into
        # ``final`` (no per-process rename possible over a shared dir);
        # the COMMITTED marker below is still the only eligibility gate
        fsio.write_bytes(os.path.join(final, "COMMITTED"), b"")
        fsio.fsync_dir(self.directory)
        self._gc()

    def _stage_path(self, step: int) -> str:
        # single-host saves stage into step-N.<seq>.tmp then os.replace
        # into place; the per-manager sequence number makes the staging dir
        # unique per save attempt, so a SIGTERM handler re-entering save()
        # mid-write can never clobber the interrupted save's staging area.
        # Multi-host processes share one directory and rely on the
        # COMMITTED marker alone.
        if jax.process_count() == 1:
            self._save_seq += 1
            return f"{self._path(step)}.{self._save_seq}{_TMP_SUFFIX}"
        return self._path(step)

    def save(self, step: int, state, *, use_async: bool = True) -> None:
        self.wait()
        stage = self._stage_path(step)
        if stage.endswith(_TMP_SUFFIX) and os.path.isdir(stage):
            shutil.rmtree(stage)  # stale staging dir from a crashed save
        vlog(1, "elastic: saving checkpoint %s", self._path(step))
        if use_async:
            handle = save_sharded(state, stage, use_async=True)
            mgr = self
            errors: list = []

            def _finish(h=handle, s=step, st=stage):
                try:
                    h.wait()
                    mgr._commit(s, st)
                except Exception as e:  # surfaced by self.wait()
                    errors.append(e)

            t = threading.Thread(target=_finish, daemon=True)
            t.start()
            self._pending = AsyncSaveHandle(t, errors)
        else:
            save_sharded(state, stage)
            self._commit(step, stage)

    def maybe_save(self, step: int, state) -> bool:
        """Track the live state; checkpoint every save_interval_steps."""
        with self._lock:
            self._latest_state = state
            self._latest_step = step
        if step > 0 and step % self.save_interval_steps == 0:
            self.save(step, state)
            return True
        return False

    def finalize(self, step: int, state) -> None:
        self.save(step, state, use_async=False)
        self.wait()

    def wait(self) -> None:
        if self._pending is not None:
            self._pending.wait()
            self._pending = None

    # -- restore -----------------------------------------------------------
    def restore_or(self, init_fn: Callable[[], Any],
                   template_fn: Callable[[], Any]):
        """(state, start_step): restore the newest VALID committed
        checkpoint into ``template_fn()``'s placement, else
        ``(init_fn(), 0)``.

        Fallback chain: committed steps are tried newest→oldest; any that
        fail manifest/checksum validation (or raise during load) are
        quarantined to ``step-N.corrupt/`` and the next one is tried.  A
        single flipped bit therefore costs one checkpoint interval, not
        the run.
        """
        for path in committed_checkpoints(self.directory):
            step = int(os.path.basename(path)[len(_STEP_PREFIX):])
            vlog(1, "elastic: restoring %s", path)
            try:
                return load_sharded(path, template_fn()), step + 1
            except Exception as e:
                kind = ("corruption" if isinstance(e, CheckpointCorruption)
                        else "load failure")
                vlog(0, "elastic: %s restoring %s (%s) — quarantining and "
                     "falling back to the previous committed step",
                     kind, path, e)
                self._quarantine(path, reason=kind, error=str(e))
        return init_fn(), 0

    def _quarantine(self, path: str, reason: str = "corruption",
                    error: str = "") -> None:
        dst = path + _CORRUPT_SUFFIX
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.replace(path, dst)
        fsio.fsync_dir(self.directory)
        self._emit("checkpoint_quarantined", path=path, step=_step_of(
            os.path.basename(path)), reason=reason, error=error,
            next_good_step=self.last_good_step())

    # -- preemption --------------------------------------------------------
    def _on_sigterm(self, signum, frame) -> None:
        with self._lock:
            state, step = self._latest_state, self._latest_step
        if state is not None:
            vlog(0, "elastic: SIGTERM — flushing checkpoint at step %d", step)
            # a pending async save may be mid-flight (or mid-failure): its
            # _finish thread can surface an exception out of save()'s
            # wait() INSIDE this signal handler — absorb it and still
            # write the final synchronous checkpoint, which is the one
            # restart depends on
            try:
                self.wait()
            except Exception as e:
                vlog(0, "elastic: pending async save failed during SIGTERM "
                     "(%s) — writing final checkpoint anyway", e)
                self._pending = None
            try:
                self.save(step, state, use_async=False)
            except Exception as e:
                vlog(0, "elastic: final checkpoint flush failed: %s", e)
        if callable(self._prev_handler):
            self._prev_handler(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _gc(self) -> None:
        """Prune old committed steps (keep newest ``self.keep``) and sweep
        stale debris — uncommitted ``step-*`` dirs, ``.tmp`` staging dirs
        and ``.corrupt`` quarantines STRICTLY OLDER than the newest
        committed step (crashed async saves must not leak disk forever;
        newer-or-equal debris is left alone: it may be another process's
        in-flight save or evidence worth keeping)."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        committed = sorted(
            (int(n[len(_STEP_PREFIX):]) for n in entries
             if n.startswith(_STEP_PREFIX)
             and not n.endswith((_TMP_SUFFIX, _CORRUPT_SUFFIX))
             and os.path.exists(
                 os.path.join(self.directory, n, "COMMITTED"))),
            reverse=True)
        if not committed:
            return
        if self.keep:
            for step in committed[self.keep:]:
                shutil.rmtree(self._path(step), ignore_errors=True)
        newest = committed[0]
        for name in entries:
            step = _step_of(name)
            if step is None or step >= newest:
                continue
            full = os.path.join(self.directory, name)
            is_stale = (name.endswith((_TMP_SUFFIX, _CORRUPT_SUFFIX))
                        or not os.path.exists(
                            os.path.join(full, "COMMITTED")))
            if is_stale:
                vlog(1, "elastic: gc removing stale %s", full)
                shutil.rmtree(full, ignore_errors=True)
