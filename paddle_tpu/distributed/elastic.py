"""Elastic / preemption handling (component D14).

Reference: fleet/elastic/manager.py ``ElasticManager``:130 — etcd node
registry with watch callbacks (:245) and lease heartbeats; on membership
change it tears down and relaunches training with rewritten endpoints.
Companion: automatic checkpointing for recovery
(fluid/incubate/checkpoint/auto_checkpoint.py).

TPU-native rendering: cluster membership is the TPU runtime's problem (a
preempted pod slice just goes away); what the framework owes the user is
**surviving preemption** — periodic async sharded checkpoints, a SIGTERM
hook that flushes one final checkpoint inside the grace window, and a
restore-on-restart that reshards into whatever topology the job came back
with (which checkpoint.load_sharded already does).  That is the whole
teardown/relaunch loop of the reference with the etcd machinery replaced
by the platform's own scheduler.

Atomic commit protocol (ISSUE 1): every save is staged into
``step-N.tmp/`` (shards + manifest fsync'd there by ``save_sharded``),
then ``os.replace``d to ``step-N/``, then the COMMITTED marker is written
and the parent directory fsync'd.  A crash at ANY point leaves either a
``.tmp`` staging dir (never eligible for restore) or a fully durable
committed step — restore can never observe a torn checkpoint.  On
restore, ``restore_or`` walks committed steps newest→oldest, quarantining
(``step-N/`` → ``step-N.corrupt/``) any that fail manifest/checksum
validation, and only falls back to a fresh init when none survive.

Elastic fleet (ISSUE 9): surviving preemption is only half of the
reference ``ElasticManager``'s contract — the other half is *resizing*
the job when membership changes instead of dying or rolling back at a
fixed width.  Two pieces render that here:

- a **world descriptor** (``<run_dir>/world.json``): the generation-
  stamped membership record the launcher's reconciliation loop owns.
  Every membership change bumps ``generation``; a worker holding a
  stale generation is *fenced* — its checkpoint commits are refused
  (:class:`StaleGeneration`), so a zombie preempted worker that comes
  back from a long GC pause can never clobber the new world's chain.
- an :class:`ElasticCoordinator`: the worker-side resize state machine
  — on lost-worker / scale-signal it quiesces pending saves, re-forms
  the device mesh at the new dp width (mp×pp stay fixed: resizing them
  changes per-device tensor shapes, which is a relaunch, not a
  resize), re-shards the last committed state onto the new mesh
  through the manifest-v2 window reader (``load_sharded``'s
  ``mismatch`` hook re-packs the ZeRO-1 flat master when the padded
  length changes; rank-private error-feedback residuals are dropped
  with an ``elastic.ef_reset`` event — they are not relayout-able),
  rewinds to ``last_good_step()``, and reseeds the data pipeline.
  Preemption costs one checkpoint interval, not the job.
"""
from __future__ import annotations

import json
import os
import shutil
import signal
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

import jax

from ..framework.errors import enforce
from ..framework.log import vlog
from ..utils import fsio
from .checkpoint import (AsyncSaveHandle, CheckpointCorruption,
                         DigestMismatch, load_sharded, save_sharded)

__all__ = ["ElasticTrainState", "ElasticCoordinator", "StaleGeneration",
           "latest_checkpoint", "committed_checkpoints", "read_world",
           "write_world", "world_path"]

_STEP_PREFIX = "step-"
_TMP_SUFFIX = ".tmp"
_CORRUPT_SUFFIX = ".corrupt"

#: newest quarantined ``step-N.corrupt/`` dirs kept by gc (forensics);
#: older ones are swept so a corrupt-prone disk can't fill itself.
CORRUPT_KEEP_ENV = "PTPU_CORRUPT_KEEP"
ELASTIC_MIN_ENV = "PTPU_ELASTIC_MIN"
ELASTIC_MAX_ENV = "PTPU_ELASTIC_MAX"

_WORLD_FILE = "world.json"


class StaleGeneration(RuntimeError):
    """This worker's world generation is older than the fleet's — it was
    declared lost (or retired) and must not commit checkpoints or act on
    the run; restart and rejoin at the current generation."""


# ---------------------------------------------------------------------------
# world descriptor (generation-stamped membership, owned by the launcher)
# ---------------------------------------------------------------------------
def world_path(run_dir: str) -> str:
    return os.path.join(run_dir, _WORLD_FILE)


def write_world(run_dir: str, *, generation: int, members: Iterable[int],
                min_size: int = 1, max_size: Optional[int] = None,
                reason: str = "init", clock=time.time) -> Dict[str, Any]:
    """Durably publish a new world descriptor.  The launcher (or a test
    driver) is the single writer; workers only read.  The atomic write
    means a reader never observes a torn descriptor."""
    members = sorted(int(m) for m in members)
    desc = {"generation": int(generation), "members": members,
            "world_size": len(members), "min_size": int(min_size),
            "max_size": (len(members) if max_size is None
                         else int(max_size)),
            "reason": str(reason), "updated": float(clock())}
    os.makedirs(run_dir, exist_ok=True)
    fsio.atomic_write_bytes(world_path(run_dir),
                            json.dumps(desc, indent=1).encode("utf-8"))
    return desc


def read_world(run_dir: str) -> Optional[Dict[str, Any]]:
    """The current world descriptor, or None when absent/unreadable (a
    torn read is indistinguishable from "not published yet" — callers
    poll)."""
    try:
        return json.loads(fsio.read_bytes(world_path(run_dir)))
    except (OSError, ValueError):
        return None


def _step_of(name: str) -> Optional[int]:
    """Step number of a ``step-N[.seq][.tmp|.corrupt]`` entry name (else
    None)."""
    if not name.startswith(_STEP_PREFIX):
        return None
    stem = name[len(_STEP_PREFIX):]
    for suffix in (_TMP_SUFFIX, _CORRUPT_SUFFIX):
        if stem.endswith(suffix):
            stem = stem[: -len(suffix)]
    stem = stem.split(".")[0]  # drop the per-save staging token
    try:
        return int(stem)
    except ValueError:
        return None


def committed_checkpoints(directory: str) -> List[str]:
    """Every committed checkpoint path under ``directory``, newest first."""
    if not os.path.isdir(directory):
        return []
    found = []
    for name in os.listdir(directory):
        if not name.startswith(_STEP_PREFIX) or name.endswith(
                (_TMP_SUFFIX, _CORRUPT_SUFFIX)):
            continue
        full = os.path.join(directory, name)
        if not os.path.exists(os.path.join(full, "COMMITTED")):
            continue  # partial write (crashed mid-save)
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        found.append((step, full))
    return [path for _, path in sorted(found, reverse=True)]


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest complete checkpoint path under ``directory`` (or None)."""
    done = committed_checkpoints(directory)
    return done[0] if done else None


class ElasticTrainState:
    """Preemption-aware checkpoint manager.

    >>> mgr = ElasticTrainState("ckpts", save_interval_steps=100)
    >>> state, start = mgr.restore_or(init_state, template_fn)
    >>> for step in range(start, total):
    ...     state = train_step(state)
    ...     mgr.maybe_save(step, state)     # async, every interval
    >>> mgr.finalize(step, state)

    On SIGTERM (the TPU preemption notice) the handler saves one final
    checkpoint synchronously before re-raising the default handler —
    restart then resumes from it, under the SAME or a DIFFERENT mesh
    (resharding-on-load).  ≙ ElasticManager's watch→checkpoint→relaunch
    cycle with the relaunch owned by the cluster scheduler.
    """

    def __init__(self, directory: str, save_interval_steps: int = 1000,
                 keep: int = 2, install_sigterm_handler: bool = True,
                 event_sink: Optional[Callable] = None,
                 corrupt_keep: Optional[int] = None,
                 fingerprint=None):
        self.directory = directory
        self._event_sink = event_sink
        #: optional TreeFingerprint (ISSUE 11): when set, every save
        #: stamps the live tree digest into the manifest and every
        #: restore re-verifies it (load_sharded's round-trip check) —
        #: the supervisor's IntegrityGuard shares the instance so the
        #: checkpoint stamp and the cross-worker compare use one digest
        self.fingerprint = fingerprint
        self.save_interval_steps = int(save_interval_steps)
        self.keep = keep
        self.corrupt_keep = (int(os.environ.get(CORRUPT_KEEP_ENV, "2"))
                             if corrupt_keep is None else int(corrupt_keep))
        #: generation fencing (ISSUE 9): when bound to a world descriptor
        #: (or an explicit fence callable), a commit whose generation is
        #: older than the fleet's is refused with StaleGeneration
        self.generation: Optional[int] = None
        self._fence: Optional[Callable[[], Optional[int]]] = None
        self._pending: Optional[AsyncSaveHandle] = None
        self._save_seq = 0
        self._latest_state: Any = None
        self._latest_step: int = -1
        self._lock = threading.Lock()
        self._prev_handler = None
        if install_sigterm_handler:
            try:
                self._prev_handler = signal.signal(
                    signal.SIGTERM, self._on_sigterm)
            except ValueError:  # not the main thread
                self._prev_handler = None

    # -- supervision hookup (ISSUE 2) --------------------------------------
    def set_event_sink(self, sink: Optional[Callable]) -> None:
        """``sink(kind, **fields)`` — the run supervisor's report; every
        quarantine/restore decision becomes a recorded event so rollback
        can target (and post-mortems can explain) the right step."""
        self._event_sink = sink

    def _emit(self, kind: str, **fields) -> None:
        if self._event_sink is not None:
            try:
                self._event_sink(kind, **fields)
            except Exception as e:
                vlog(0, "elastic: event sink failed for %s: %s", kind, e)

    # -- generation fencing (ISSUE 9) --------------------------------------
    def set_generation(self, generation: Optional[int],
                       fence: Optional[Callable[[], Optional[int]]] = None
                       ) -> None:
        """Stamp this worker's world generation; ``fence()`` (when given)
        returns the fleet's CURRENT generation at commit time."""
        self.generation = None if generation is None else int(generation)
        if fence is not None:
            self._fence = fence

    def bind_world(self, run_dir: str,
                   generation: Optional[int] = None,
                   worker_id: Optional[int] = None) -> None:
        """Fence commits against ``<run_dir>/world.json``: reads the
        live descriptor's generation at every commit.  ``generation``
        defaults to the descriptor's current value (joining worker).

        With ``worker_id`` given, a worker that is STILL A MEMBER of a
        newer world may commit before it has polled the bump (it will
        rewind at its next poll); only a worker the fleet retired — the
        actual zombie — is fenced.  Without it, any newer generation
        fences (strict mode)."""
        if generation is None:
            desc = read_world(run_dir)
            generation = desc["generation"] if desc else 0

        def fence() -> Optional[int]:
            desc = read_world(run_dir)
            if not desc:
                return None
            if worker_id is not None and int(worker_id) in desc.get(
                    "members", []):
                return None   # still a member: no objection
            return desc.get("generation")

        self.set_generation(generation, fence=fence)

    def _check_fence(self, step: int) -> None:
        if self.generation is None or self._fence is None:
            return
        current = self._fence()
        if current is None or int(current) <= self.generation:
            return
        self._emit("elastic.fence_rejected", step=step,
                   generation=self.generation, current_generation=current)
        raise StaleGeneration(
            f"refusing to commit step {step}: this worker holds world "
            f"generation {self.generation} but the fleet is at "
            f"{current} — the run moved on without it")

    def last_good_step(self) -> int:
        """Newest committed (restorable) step number, -1 when none exist —
        the step auto-rollback will land on."""
        done = committed_checkpoints(self.directory)
        if not done:
            return -1
        return int(os.path.basename(done[0])[len(_STEP_PREFIX):])

    # -- save --------------------------------------------------------------
    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _commit(self, step: int, stage: str) -> None:
        """Promote the staging dir to a durable committed ``step-N/``.

        Fenced (ISSUE 9): a worker whose world generation went stale
        between save() and commit must NOT publish — the staging dir is
        dropped and :class:`StaleGeneration` surfaces out of ``wait()``
        (or synchronously for ``use_async=False`` saves)."""
        final = self._path(step)
        try:
            self._check_fence(step)
        except StaleGeneration:
            if stage != final and os.path.isdir(stage):
                shutil.rmtree(stage, ignore_errors=True)
            raise
        if stage != final:
            if os.path.isdir(final):
                # leftover from an earlier crashed/uncommitted save of the
                # same step — the fresh staging dir supersedes it
                shutil.rmtree(final)
            os.replace(stage, final)  # noqa: fsio — dir rename; parent fsync'd below
        # multi-host: every process wrote its own shards straight into
        # ``final`` (no per-process rename possible over a shared dir);
        # the COMMITTED marker below is still the only eligibility gate
        fsio.write_bytes(os.path.join(final, "COMMITTED"), b"")
        fsio.fsync_dir(self.directory)
        self._gc()

    def _stage_path(self, step: int) -> str:
        # single-host saves stage into step-N.<seq>.tmp then os.replace
        # into place; the per-manager sequence number makes the staging dir
        # unique per save attempt, so a SIGTERM handler re-entering save()
        # mid-write can never clobber the interrupted save's staging area.
        # Multi-host processes share one directory and rely on the
        # COMMITTED marker alone.
        if jax.process_count() == 1:
            self._save_seq += 1
            return f"{self._path(step)}.{self._save_seq}{_TMP_SUFFIX}"
        return self._path(step)

    def _integrity_meta(self, step: int, state) -> Optional[Dict[str, Any]]:
        """Manifest fingerprint stamp for ``state`` (None when digesting
        is off).  Computed synchronously BEFORE the save serializes
        anything — the whole point is that the digest describes the live
        tree, so corruption between here and the shard writes is caught
        at restore even though every CRC passes."""
        if self.fingerprint is None:
            return None
        fpr = self.fingerprint.digest(state)
        meta = fpr.meta()
        meta["exclude"] = list(self.fingerprint.exclude)
        self._emit("checkpoint_digest", step=step, digest=fpr.hex(),
                   excluded=len(fpr.excluded))
        return meta

    def save(self, step: int, state, *, use_async: bool = True) -> None:
        self.wait()
        stage = self._stage_path(step)
        if stage.endswith(_TMP_SUFFIX) and os.path.isdir(stage):
            shutil.rmtree(stage)  # stale staging dir from a crashed save
        vlog(1, "elastic: saving checkpoint %s", self._path(step))
        integrity = self._integrity_meta(step, state)
        if use_async:
            handle = save_sharded(state, stage, use_async=True,
                                  integrity=integrity)
            mgr = self
            errors: list = []

            def _finish(h=handle, s=step, st=stage):
                try:
                    h.wait()
                    mgr._commit(s, st)
                except Exception as e:  # surfaced by self.wait()
                    errors.append(e)

            t = threading.Thread(target=_finish, daemon=True)
            t.start()
            self._pending = AsyncSaveHandle(t, errors)
        else:
            save_sharded(state, stage, integrity=integrity)
            self._commit(step, stage)

    def maybe_save(self, step: int, state) -> bool:
        """Track the live state; checkpoint every save_interval_steps."""
        with self._lock:
            self._latest_state = state
            self._latest_step = step
        if step > 0 and step % self.save_interval_steps == 0:
            self.save(step, state)
            return True
        return False

    def finalize(self, step: int, state) -> None:
        self.save(step, state, use_async=False)
        self.wait()

    def wait(self) -> None:
        if self._pending is not None:
            self._pending.wait()
            self._pending = None

    # -- restore -----------------------------------------------------------
    def _fallback_kind(self, e: Exception) -> str:
        if isinstance(e, DigestMismatch):
            return "digest mismatch"
        if isinstance(e, CheckpointCorruption):
            return "corruption"
        return "load failure"

    def _note_fallback(self, step: Optional[int], path: str, reason: str,
                       error: str = "") -> None:
        """ISSUE 11: every step the restore chain skips gets a named
        ``restore.fallback`` event + counter — older-step fallback used
        to be silent in the timeline, which hid exactly the evidence an
        SDC post-mortem needs (which steps were skipped and why)."""
        self._emit("restore.fallback", step=step, path=path,
                   reason=reason, error=error)
        try:
            from ..observability import get_registry
            reg = get_registry()
            reg.counter("restore.fallbacks").inc()
            reg.emit("restore.fallback", step=step, reason=reason,
                     path=path)
        except Exception as e:
            vlog(1, "elastic: fallback metrics failed: %r", e)

    def _note_uncommitted(self) -> None:
        """Fallback events for step dirs that never got a COMMITTED
        marker (crashed mid-save): the restore walk silently ignores
        them, the timeline should not."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for name in sorted(entries, reverse=True):
            if (not name.startswith(_STEP_PREFIX)
                    or name.endswith((_TMP_SUFFIX, _CORRUPT_SUFFIX))):
                continue
            full = os.path.join(self.directory, name)
            if not os.path.exists(os.path.join(full, "COMMITTED")):
                self._note_fallback(_step_of(name), full,
                                    "missing COMMITTED")

    def restore_or(self, init_fn: Callable[[], Any],
                   template_fn: Callable[[], Any]):
        """(state, start_step): restore the newest VALID committed
        checkpoint into ``template_fn()``'s placement, else
        ``(init_fn(), 0)``.

        Fallback chain: committed steps are tried newest→oldest; any that
        fail manifest/checksum validation, tree-digest re-verification,
        or raise during load are quarantined to ``step-N.corrupt/`` and
        the next one is tried — each skip named by a ``restore.fallback``
        event (corrupt / digest mismatch / missing COMMITTED).  A single
        flipped bit therefore costs one checkpoint interval, not the run.
        """
        self._note_uncommitted()
        for path in committed_checkpoints(self.directory):
            step = int(os.path.basename(path)[len(_STEP_PREFIX):])
            vlog(1, "elastic: restoring %s", path)
            try:
                return load_sharded(path, template_fn()), step + 1
            except Exception as e:
                kind = self._fallback_kind(e)
                vlog(0, "elastic: %s restoring %s (%s) — quarantining and "
                     "falling back to the previous committed step",
                     kind, path, e)
                self._note_fallback(step, path, kind, error=str(e))
                self._quarantine(path, reason=kind, error=str(e))
        return init_fn(), 0

    def _quarantine(self, path: str, reason: str = "corruption",
                    error: str = "") -> None:
        dst = path + _CORRUPT_SUFFIX
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.replace(path, dst)  # noqa: fsio — dir rename; parent fsync'd below
        fsio.fsync_dir(self.directory)
        self._emit("checkpoint_quarantined", path=path, step=_step_of(
            os.path.basename(path)), reason=reason, error=error,
            next_good_step=self.last_good_step())

    # -- preemption --------------------------------------------------------
    def _on_sigterm(self, signum, frame) -> None:
        with self._lock:
            state, step = self._latest_state, self._latest_step
        if state is not None:
            vlog(0, "elastic: SIGTERM — flushing checkpoint at step %d", step)
            # a pending async save may be mid-flight (or mid-failure): its
            # _finish thread can surface an exception out of save()'s
            # wait() INSIDE this signal handler — absorb it and still
            # write the final synchronous checkpoint, which is the one
            # restart depends on
            try:
                self.wait()
            except Exception as e:
                vlog(0, "elastic: pending async save failed during SIGTERM "
                     "(%s) — writing final checkpoint anyway", e)
                self._pending = None
            try:
                self.save(step, state, use_async=False)
            except Exception as e:
                vlog(0, "elastic: final checkpoint flush failed: %s", e)
        if callable(self._prev_handler):
            self._prev_handler(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _gc(self) -> None:
        """Prune old committed steps (keep newest ``self.keep``) and sweep
        stale debris — uncommitted ``step-*`` dirs, ``.tmp`` staging dirs
        and ``.corrupt`` quarantines STRICTLY OLDER than the newest
        committed step (crashed async saves must not leak disk forever;
        newer-or-equal debris is left alone: it may be another process's
        in-flight save or evidence worth keeping).  Quarantines are
        additionally bounded to the newest ``corrupt_keep``
        (``PTPU_CORRUPT_KEEP``, default 2) REGARDLESS of age — a
        corrupt-prone volume otherwise accumulates evidence forever."""
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        committed = sorted(
            (int(n[len(_STEP_PREFIX):]) for n in entries
             if n.startswith(_STEP_PREFIX)
             and not n.endswith((_TMP_SUFFIX, _CORRUPT_SUFFIX))
             and os.path.exists(
                 os.path.join(self.directory, n, "COMMITTED"))),
            reverse=True)
        corrupt = sorted(
            ((_step_of(n), n) for n in entries
             if n.endswith(_CORRUPT_SUFFIX) and _step_of(n) is not None),
            reverse=True)
        kept_corrupt = {n for _s, n in corrupt[:max(0, self.corrupt_keep)]}
        for _step, name in corrupt[max(0, self.corrupt_keep):]:
            vlog(1, "elastic: gc bounding quarantine %s", name)
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)
        if not committed:
            return
        if self.keep:
            for step in committed[self.keep:]:
                shutil.rmtree(self._path(step), ignore_errors=True)
        newest = committed[0]
        for name in entries:
            step = _step_of(name)
            if step is None or step >= newest or name in kept_corrupt:
                continue
            full = os.path.join(self.directory, name)
            is_stale = (name.endswith((_TMP_SUFFIX, _CORRUPT_SUFFIX))
                        or not os.path.exists(
                            os.path.join(full, "COMMITTED")))
            if is_stale:
                vlog(1, "elastic: gc removing stale %s", full)
                shutil.rmtree(full, ignore_errors=True)


# ---------------------------------------------------------------------------
# elastic coordinator (ISSUE 9): resize a live run instead of rolling back
# ---------------------------------------------------------------------------
def _env_int(name: str, default: Optional[int]) -> Optional[int]:
    v = os.environ.get(name)
    return default if not v else int(v)


class ElasticCoordinator:
    """Worker-side resize state machine.

    ::

        RUNNING --lost-worker / scale-signal--> QUIESCE (drain async save)
          --> FENCE   (generation += 1; stale workers can't commit)
          --> REMESH  (dp axis resized over the surviving devices;
                       mp×pp fixed)
          --> RESHARD (last committed state stitched onto the new mesh by
                       the manifest-v2 window reader; ZeRO-1 flat master
                       re-packed when the padded length changes; EF
                       residuals dropped — rank-private state has no
                       cross-width meaning)
          --> REWIND  (back to last_good_step(); one interval lost)
          --> RESEED  (data pipeline told the new start step + width)
          --> RUNNING (new generation)

    The coordinator owns the *in-process* half of elasticity; process
    membership (spawning/retiring workers, publishing ``world.json``)
    belongs to the launcher's reconciliation loop (``launch --elastic``).

    >>> coord = ElasticCoordinator(mgr, mp=1, pp=1, min_dp=1)
    >>> coord.form_mesh(8)                       # initial world
    >>> ...                                      # train, maybe_save(...)
    >>> state, start = coord.resize(4, template_fn,
    ...                             reason="lost-worker:3")

    ``template_fn`` is called AFTER the new mesh is installed and must
    build the restore placement against it (``ShapeDtypeStruct``s with
    NamedShardings, or host-placed arrays).  Leaves whose saved global
    shape differs from the template's are re-packed by
    :meth:`_relayout_leaf` — 1-D zero-padded flat leaves (the ZeRO-1
    master and its slots) are re-padded bitwise; leaves under an
    ``ef_keys`` subtree are reset to zeros with an ``elastic.ef_reset``
    event.
    """

    def __init__(self, elastic: ElasticTrainState, *, mp: int = 1,
                 pp: int = 1, min_dp: Optional[int] = None,
                 max_dp: Optional[int] = None, devices=None,
                 event_sink: Optional[Callable] = None,
                 reseed: Optional[Callable[[int, int], None]] = None,
                 ef_keys: Tuple[str, ...] = ("resid", "ef_residual"),
                 world_dir: Optional[str] = None):
        self.elastic = elastic
        self.mp, self.pp = int(mp), int(pp)
        self.devices = list(devices) if devices is not None else list(
            jax.devices())
        per_dp = self.mp * self.pp
        hw_max = len(self.devices) // per_dp
        self.min_dp = max(1, _env_int(ELASTIC_MIN_ENV, min_dp) or 1)
        self.max_dp = min(hw_max, _env_int(ELASTIC_MAX_ENV, max_dp)
                          or hw_max)
        enforce(self.min_dp <= self.max_dp,
                f"elastic bounds empty: min_dp {self.min_dp} > max_dp "
                f"{self.max_dp} ({len(self.devices)} devices / mp={self.mp}"
                f" pp={self.pp})")
        self.event_sink = event_sink
        self.reseed = reseed
        self.ef_keys = tuple(ef_keys)
        self.world_dir = world_dir
        self.generation = 0
        self.dp: Optional[int] = None
        self.resizes = 0
        self.last_resize: Optional[Dict[str, Any]] = None
        self._ef_reset: List[str] = []
        if world_dir is not None:
            desc = read_world(world_dir)
            if desc:
                self.generation = int(desc["generation"])
            self.elastic.bind_world(world_dir, generation=self.generation)

    # -- events / metrics ---------------------------------------------------
    def _emit(self, kind: str, **fields) -> None:
        if self.event_sink is not None:
            try:
                self.event_sink(kind, **fields)
            except Exception as e:
                vlog(0, "elastic: event sink failed for %s: %s", kind, e)

    def _metrics(self, **gauges) -> None:
        try:
            from ..observability import get_registry
        except ImportError:  # pragma: no cover - package always present
            return
        reg = get_registry()
        for name, value in gauges.items():
            reg.gauge(f"elastic.{name}").set(float(value))

    # -- mesh ---------------------------------------------------------------
    @property
    def world_size(self) -> Optional[int]:
        return None if self.dp is None else self.dp * self.mp * self.pp

    def form_mesh(self, dp: int):
        """(Re)install the hybrid mesh at width ``dp`` over the leading
        ``dp·mp·pp`` devices; returns the new ``jax.sharding.Mesh``."""
        from .topology import (CommunicateTopology, HybridCommunicateGroup,
                               set_hybrid_communicate_group)
        dp = int(dp)
        enforce(self.min_dp <= dp <= self.max_dp,
                f"dp={dp} outside the elastic range "
                f"[{self.min_dp}, {self.max_dp}]")
        need = dp * self.mp * self.pp
        enforce(need <= len(self.devices),
                f"world size {need} exceeds the {len(self.devices)} "
                f"available devices")
        topo = CommunicateTopology(("data", "pipe", "model"),
                                   (dp, self.pp, self.mp))
        hcg = HybridCommunicateGroup(topo, devices=self.devices[:need])
        set_hybrid_communicate_group(hcg)
        self.dp = dp
        self._metrics(generation=self.generation, world_size=need, dp=dp)
        return hcg.mesh

    # -- membership polling (worker side of the launcher protocol) ---------
    def poll_world(self) -> Optional[Dict[str, Any]]:
        """The new world descriptor when the fleet moved past this
        worker's generation, else None.  The caller decides: resize and
        continue (still a member) or exit (retired)."""
        if self.world_dir is None:
            return None
        desc = read_world(self.world_dir)
        if desc and int(desc["generation"]) > self.generation:
            return desc
        return None

    def adopt_world(self, desc: Dict[str, Any]) -> None:
        """Take on a descriptor published by the launcher (instead of
        bumping the generation locally): fences re-arm at the fleet's
        generation."""
        self.generation = int(desc["generation"])
        self.elastic.set_generation(self.generation)
        self._metrics(generation=self.generation,
                      world_size=desc.get("world_size", 0))

    # -- the resize itself --------------------------------------------------
    def clamp(self, dp: int) -> int:
        return max(self.min_dp, min(self.max_dp, int(dp)))

    def resize(self, new_dp: int, template_fn: Callable[[], Any],
               init_fn: Optional[Callable[[], Any]] = None, *,
               reason: str = "scale-signal",
               bump_generation: bool = True) -> Tuple[Any, int]:
        """Execute the full quiesce→fence→remesh→reshard→rewind→reseed
        arc; returns ``(state, start_step)``.

        ``init_fn`` is the from-scratch fallback when no committed
        checkpoint survives (same contract as ``restore_or``).
        ``bump_generation=False`` is the launcher-driven path: the
        descriptor already carries the new generation (``adopt_world``).
        """
        old_dp = self.dp
        new_dp = self.clamp(new_dp)
        # 1. quiesce — drain (or absorb the failure of) an in-flight save
        try:
            self.elastic.wait()
        except Exception as e:
            vlog(0, "elastic: pending async save failed during resize "
                 "(%s) — restoring from the last committed step", e)
        # 2. fence — everyone still holding the old generation is stale
        if bump_generation:
            self.generation += 1
            if self.world_dir is not None:
                write_world(self.world_dir, generation=self.generation,
                            members=list(range(new_dp)),
                            min_size=self.min_dp, max_size=self.max_dp,
                            reason=reason)
            self.elastic.set_generation(self.generation)
        # 3. remesh
        self.form_mesh(new_dp)
        # 4+5. reshard + rewind
        width_changed = old_dp is not None and new_dp != old_dp
        self._ef_reset = []
        state, start = self._restore_resharded(template_fn, init_fn,
                                               width_changed)
        if self._ef_reset:
            self._emit("elastic.ef_reset", step=start,
                       leaves=list(self._ef_reset),
                       old_dp=old_dp, new_dp=new_dp)
        self.resizes += 1
        self.last_resize = {"old_dp": old_dp, "new_dp": new_dp,
                            "generation": self.generation,
                            "reason": reason, "start_step": start}
        self._emit("elastic.resize", **self.last_resize)
        try:
            from ..observability import get_registry
            reg = get_registry()
            reg.counter("elastic.resizes").inc()
            reg.emit("elastic.resize", **self.last_resize)
        except Exception as e:
            vlog(1, "elastic: resize metrics failed: %r", e)
        # 6. reseed — the data pipeline needs the new start step + width
        if self.reseed is not None:
            self.reseed(start, new_dp)
        vlog(0, "elastic: resized dp %s → %d (generation %d, %s); "
             "resuming at step %d", old_dp, new_dp, self.generation,
             reason, start)
        return state, start

    # -- state relayout -----------------------------------------------------
    def _is_rank_private(self, name: str) -> bool:
        parts = name.split("/")
        return any(k in parts for k in self.ef_keys)

    def _relayout_leaf(self, name: str, saved: np.ndarray, tpl):
        """Shape-mismatch hook for ``load_sharded``: called for every
        leaf whose saved global shape differs from the template's —
        exactly the leaves whose layout depends on the dp width."""
        tshape = tuple(getattr(tpl, "shape", ()))
        if self._is_rank_private(name):
            # stacked per-rank state (error-feedback residuals): a rank's
            # residual describes ITS last quantization error — after a
            # width change there is no rank to return it to.  Reset to
            # zeros; EF re-converges within a few steps (PR 8 drill).
            self._ef_reset.append(name)
            return self._place_like(np.zeros(tshape, np.float32), tpl)
        if saved.ndim == 1 and len(tshape) == 1:
            # zero-padded flat pack (ZeRO-1 master / slots): only padding
            # may be dropped or added — bitwise on the real elements
            from .comm.zero import repack_flat
            return self._place_like(repack_flat(saved, tshape[0]), tpl)
        raise CheckpointCorruption(
            f"{name}: saved shape {tuple(saved.shape)} cannot be "
            f"re-laid-out onto template shape {tshape} (only 1-D "
            f"flat-packed and rank-private leaves resize)")

    @staticmethod
    def _place_like(arr: np.ndarray, tpl):
        import jax.numpy as jnp
        sharding = getattr(tpl, "sharding", None)
        dtype = getattr(tpl, "dtype", arr.dtype)
        arr = np.asarray(arr, dtype=dtype)
        if sharding is not None and not isinstance(
                sharding, jax.sharding.SingleDeviceSharding):
            return jax.device_put(arr, sharding)
        return jnp.asarray(arr)

    def _restore_resharded(self, template_fn, init_fn, width_changed
                           ) -> Tuple[Any, int]:
        """``restore_or`` with the relayout hook threaded through: walk
        committed steps newest→oldest, quarantining failures."""
        directory = self.elastic.directory
        self.elastic._note_uncommitted()
        for path in committed_checkpoints(directory):
            step = int(os.path.basename(path)[len(_STEP_PREFIX):])
            vlog(1, "elastic: resharding %s onto dp=%s", path, self.dp)
            try:
                state = load_sharded(path, template_fn(),
                                     mismatch=self._relayout_leaf)
                return state, step + 1
            except Exception as e:
                kind = self.elastic._fallback_kind(e)
                vlog(0, "elastic: %s resharding %s (%s) — quarantining "
                     "and falling back", kind, path, e)
                self.elastic._note_fallback(step, path, kind,
                                            error=str(e))
                self.elastic._quarantine(path, reason=kind, error=str(e))
        enforce(init_fn is not None,
                "no committed checkpoint survives and no init_fn was "
                "given — cannot re-form the run")
        return init_fn(), 0
