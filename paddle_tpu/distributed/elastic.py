"""Elastic / preemption handling (component D14).

Reference: fleet/elastic/manager.py ``ElasticManager``:130 — etcd node
registry with watch callbacks (:245) and lease heartbeats; on membership
change it tears down and relaunches training with rewritten endpoints.
Companion: automatic checkpointing for recovery
(fluid/incubate/checkpoint/auto_checkpoint.py).

TPU-native rendering: cluster membership is the TPU runtime's problem (a
preempted pod slice just goes away); what the framework owes the user is
**surviving preemption** — periodic async sharded checkpoints, a SIGTERM
hook that flushes one final checkpoint inside the grace window, and a
restore-on-restart that reshards into whatever topology the job came back
with (which checkpoint.load_sharded already does).  That is the whole
teardown/relaunch loop of the reference with the etcd machinery replaced
by the platform's own scheduler.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Any, Callable, Dict, Optional

import jax

from ..framework.log import vlog
from .checkpoint import AsyncSaveHandle, load_sharded, save_sharded

__all__ = ["ElasticTrainState", "latest_checkpoint"]

_STEP_PREFIX = "step-"


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest complete checkpoint path under ``directory`` (or None)."""
    if not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        if not name.startswith(_STEP_PREFIX):
            continue
        full = os.path.join(directory, name)
        if not os.path.exists(os.path.join(full, "COMMITTED")):
            continue  # partial write (crashed mid-save)
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        if step > best_step:
            best, best_step = full, step
    return best


class ElasticTrainState:
    """Preemption-aware checkpoint manager.

    >>> mgr = ElasticTrainState("ckpts", save_interval_steps=100)
    >>> state, start = mgr.restore_or(init_state, template_fn)
    >>> for step in range(start, total):
    ...     state = train_step(state)
    ...     mgr.maybe_save(step, state)     # async, every interval
    >>> mgr.finalize(step, state)

    On SIGTERM (the TPU preemption notice) the handler saves one final
    checkpoint synchronously before re-raising the default handler —
    restart then resumes from it, under the SAME or a DIFFERENT mesh
    (resharding-on-load).  ≙ ElasticManager's watch→checkpoint→relaunch
    cycle with the relaunch owned by the cluster scheduler.
    """

    def __init__(self, directory: str, save_interval_steps: int = 1000,
                 keep: int = 2, install_sigterm_handler: bool = True):
        self.directory = directory
        self.save_interval_steps = int(save_interval_steps)
        self.keep = keep
        self._pending: Optional[AsyncSaveHandle] = None
        self._latest_state: Any = None
        self._latest_step: int = -1
        self._lock = threading.Lock()
        self._prev_handler = None
        if install_sigterm_handler:
            try:
                self._prev_handler = signal.signal(
                    signal.SIGTERM, self._on_sigterm)
            except ValueError:  # not the main thread
                self._prev_handler = None

    # -- save --------------------------------------------------------------
    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    def _commit(self, step: int) -> None:
        open(os.path.join(self._path(step), "COMMITTED"), "w").close()
        self._gc()

    def save(self, step: int, state, *, use_async: bool = True) -> None:
        self.wait()
        path = self._path(step)
        vlog(1, "elastic: saving checkpoint %s", path)
        if use_async:
            handle = save_sharded(state, path, use_async=True)
            mgr = self
            errors: list = []

            def _finish(h=handle, s=step):
                try:
                    h.wait()
                    mgr._commit(s)
                except Exception as e:  # surfaced by self.wait()
                    errors.append(e)

            t = threading.Thread(target=_finish, daemon=True)
            t.start()
            self._pending = AsyncSaveHandle(t, errors)
        else:
            save_sharded(state, path)
            self._commit(step)

    def maybe_save(self, step: int, state) -> bool:
        """Track the live state; checkpoint every save_interval_steps."""
        with self._lock:
            self._latest_state = state
            self._latest_step = step
        if step > 0 and step % self.save_interval_steps == 0:
            self.save(step, state)
            return True
        return False

    def finalize(self, step: int, state) -> None:
        self.save(step, state, use_async=False)
        self.wait()

    def wait(self) -> None:
        if self._pending is not None:
            self._pending.wait()
            self._pending = None

    # -- restore -----------------------------------------------------------
    def restore_or(self, init_fn: Callable[[], Any],
                   template_fn: Callable[[], Any]):
        """(state, start_step): restore the newest committed checkpoint into
        ``template_fn()``'s placement, else ``(init_fn(), 0)``."""
        path = latest_checkpoint(self.directory)
        if path is None:
            return init_fn(), 0
        step = int(os.path.basename(path)[len(_STEP_PREFIX):])
        vlog(1, "elastic: restoring %s", path)
        return load_sharded(path, template_fn()), step + 1

    # -- preemption --------------------------------------------------------
    def _on_sigterm(self, signum, frame) -> None:
        with self._lock:
            state, step = self._latest_state, self._latest_step
        if state is not None:
            vlog(0, "elastic: SIGTERM — flushing checkpoint at step %d", step)
            self.save(step, state, use_async=False)
        if callable(self._prev_handler):
            self._prev_handler(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _gc(self) -> None:
        if not self.keep:
            return
        done = sorted(
            (int(n[len(_STEP_PREFIX):]) for n in os.listdir(self.directory)
             if n.startswith(_STEP_PREFIX) and os.path.exists(
                 os.path.join(self.directory, n, "COMMITTED"))),
            reverse=True)
        import shutil
        for step in done[self.keep:]:
            shutil.rmtree(self._path(step), ignore_errors=True)
