"""Collective communication API.

Reference: paddle/fluid/operators/collective/ (143 op files — c_allreduce_*,
c_allgather, c_reducescatter, alltoall, c_broadcast, partial_send/recv...) and
the eager ``ProcessGroup`` API (distributed/collective/ProcessGroup.h:53).

TPU-native design: every byte-level transport (NCCL rings, ring_id registry,
gen_comm_id bootstrap) collapses into XLA collectives over ICI/DCN.  A
"process group" is a mesh axis name; these functions lower to ``jax.lax``
collectives and are valid inside ``shard_map``/``pjit``-parallelized code.
Called outside any mesh axis they are identity (world size 1) — the same
behavior paddle has when dist is not initialized.

The reference's eager tensor-in-place mutation API is reshaped functional:
``y = dist.all_reduce(x, group='mp')`` returns the result.
"""
from __future__ import annotations

import functools
import inspect
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.errors import enforce
from .topology import axis_size

__all__ = [
    "ReduceOp", "all_reduce", "all_reduce_quantized", "all_gather",
    "reduce_scatter", "broadcast", "all_to_all", "reduce", "scatter",
    "send_recv_permute", "barrier", "split", "p2p_push",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _observed(fn):
    """Record per-collective host latency into the telemetry registry
    (ISSUE 3): histogram ``collective.<op>.ms`` + counter
    ``collective.<op>.calls``.  For ops invoked inside a traced program
    this measures trace/dispatch cost (the wire time lives in the XLA
    schedule); for host-blocking ops — ``barrier`` above all — it is the
    real wait, which is exactly the number a wedged fleet shows first.

    ISSUE 20: when the call's ``group`` is a mesh-axis name, the
    instruments carry ``[axis=<group>,n=<participants>]`` labels
    (name-suffix convention; parse with
    :func:`~paddle_tpu.observability.registry.split_labels`) plus a
    ``collective.<op>.bytes[...]`` payload counter, so the interconnect
    microscope can attribute wire time per (op, axis).  Label
    extraction is strictly best-effort — any failure falls back to the
    legacy unlabeled names rather than raising out of a collective."""
    base = f"collective.{fn.__name__}"
    try:
        params = list(inspect.signature(fn).parameters.values())
        group_idx = next(i for i, p in enumerate(params)
                         if p.name == "group")
        group_default = params[group_idx].default
        if group_default is inspect.Parameter.empty:
            group_default = None
    except (StopIteration, TypeError, ValueError):
        group_idx, group_default = None, None

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            dt_ms = (time.perf_counter() - t0) * 1e3
            suffix = ""
            nbytes = 0
            try:
                group = kwargs.get("group", group_default)
                if ("group" not in kwargs and group_idx is not None
                        and len(args) > group_idx):
                    group = args[group_idx]
                if isinstance(group, str):
                    n = (bound_axis_size(group) if _in_axis(group)
                         else axis_size(group))
                    suffix = f"[axis={group},n={int(n)}]"
                x = args[0] if args else None
                if (x is not None and hasattr(x, "size")
                        and hasattr(x, "dtype")):
                    nbytes = int(x.size) * int(x.dtype.itemsize)
            except Exception:  # noqa: BLE001 — labels never break a call
                suffix, nbytes = "", 0
            from ..observability import get_registry
            reg = get_registry()
            reg.histogram(f"{base}.ms{suffix}").observe(dt_ms)
            reg.counter(f"{base}.calls{suffix}").inc()
            if nbytes and suffix:
                reg.counter(f"{base}.bytes{suffix}").inc(nbytes)
    return wrapped


def bound_axis_size(name: str):
    """Size of a bound (shard_map/pmap) axis — ``lax.axis_size`` on
    jax>=0.5, the constant-folded ``psum(1, axis)`` idiom before that."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(name)
    return lax.psum(1, name)


def _in_axis(group: Optional[str]) -> bool:
    """True when ``group`` names an axis bound in the current trace
    (inside shard_map over that axis)."""
    if group is None:
        return False
    try:
        bound_axis_size(group)
        return True
    except (NameError, KeyError, ValueError):
        return False


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else jnp.asarray(x)


@_observed
def all_reduce(x, op: str = ReduceOp.SUM, group: Optional[str] = "dp"):
    """c_allreduce_{sum,max,min,prod} (reference collective/c_allreduce_op.h).
    ``group`` is a mesh axis name or tuple of axis names."""
    x = _arr(x)
    if not _in_axis(group if isinstance(group, str) else (group or [None])[0]):
        return x
    if op == ReduceOp.SUM:
        return lax.psum(x, group)
    if op == ReduceOp.MAX:
        return lax.pmax(x, group)
    if op == ReduceOp.MIN:
        return lax.pmin(x, group)
    if op == ReduceOp.AVG:
        return lax.pmean(x, group)
    if op == ReduceOp.PROD:
        # gather-then-prod: exact for zeros/negatives/ints (an exp-of-
        # psum-of-logs trick would NaN on non-positive values)
        gathered = lax.all_gather(x, group, axis=0)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unknown reduce op {op!r}")


@_observed
def all_gather(x, group: Optional[str] = "dp", axis: int = 0,
               tiled: bool = True):
    """c_allgather (reference collective/c_allgather_op.cc): concatenate the
    per-device shards along ``axis``."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    return lax.all_gather(x, group, axis=axis, tiled=tiled)


@_observed
def reduce_scatter(x, op: str = ReduceOp.SUM, group: Optional[str] = "dp",
                   axis: int = 0):
    """c_reducescatter (reference collective/c_reducescatter_op.cc)."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    return lax.psum_scatter(x, group, scatter_dimension=axis, tiled=True)


@_observed
def broadcast(x, src: int = 0, group: Optional[str] = "dp"):
    """c_broadcast: every device gets src's value.  Implemented as a
    masked psum (XLA lowers single-source psum patterns to a broadcast)."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    idx = lax.axis_index(group)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, group)


@_observed
def reduce(x, dst: int = 0, op: str = ReduceOp.SUM,
           group: Optional[str] = "dp"):
    """c_reduce: full result lands on dst, zeros elsewhere (SPMD shape must
    be uniform; callers normally follow with work on dst only)."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    total = all_reduce(x, op, group)
    idx = lax.axis_index(group)
    return jnp.where(idx == dst, total, jnp.zeros_like(total))


@_observed
def scatter(x, src: int = 0, group: Optional[str] = "dp", axis: int = 0):
    """Each device keeps its slice of src's tensor."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    n = bound_axis_size(group)
    if x.shape[axis] % n:
        raise ValueError(
            f"scatter axis {axis} size {x.shape[axis]} not divisible by "
            f"group size {n}")
    x = broadcast(x, src, group)
    idx = lax.axis_index(group)
    size = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=axis)


@_observed
def all_to_all(x, group: Optional[str] = "ep", split_axis: int = 0,
               concat_axis: int = 0):
    """alltoall (reference collective/alltoall_op.cc; MoE dispatch backbone
    global_scatter_op.cc)."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    return lax.all_to_all(x, group, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


@_observed
def send_recv_permute(x, perm: Sequence[tuple], group: str = "pp"):
    """Point-to-point via collective_permute — the ICI-native replacement for
    the reference's NCCL send/recv pairs (partial_send/recv,
    pp_utils/p2p_communication.py).  ``perm`` is [(src, dst), ...]."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    return lax.ppermute(x, group, perm=list(perm))


@_observed
def p2p_push(x, offset: int = 1, group: str = "pp"):
    """Shift along a ring: stage i sends to stage i+offset (mod n) — the 1F1B
    forward/backward activation hand-off."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    n = bound_axis_size(group)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, group, perm=perm)


@_observed
def split(x, group: str = "mp", axis: int = -1):
    """c_split: keep this device's slice along ``axis``."""
    x = _arr(x)
    if not _in_axis(group):
        return x
    n = bound_axis_size(group)
    idx = lax.axis_index(group)
    ax = axis % x.ndim
    if x.shape[ax] % n:
        raise ValueError(
            f"split axis {ax} size {x.shape[ax]} not divisible by "
            f"group size {n}")
    size = x.shape[ax] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis=ax)


@_observed
def barrier(group: Optional[str] = None, timeout: Optional[float] = None):
    """Host-side rendezvous.  Inside a traced program this is a no-op
    (one program, one schedule — XLA's execution model is the barrier;
    reference collective/barrier_op.cc is an allreduce on a scalar).
    Called from host code on a multi-process run it blocks until every
    process arrives — and that wait is exactly where a dead or wedged
    peer hangs the fleet, so it runs under the run supervisor's watchdog
    when one is installed: instead of blocking forever the caller gets a
    ``StepTimeout`` (plus an all-thread stack dump in the supervisor
    report).  ``timeout`` overrides the watchdog's default deadline for
    this wait only."""
    from ..supervisor.watchdog import guarded
    if _in_axis(group):
        return None  # traced: SPMD already orders the program
    with guarded("collective.barrier", timeout=timeout):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("paddle_tpu.barrier")
    return None


def all_reduce_quantized(x, group: str = "dp", bits: int = 8,
                         block_size: int = 256):
    """DEPRECATED alias for the comm package's quantized all-reduce
    (ISSUE 8): ``comm.all_reduce(x, config=CommConfig(dtype="int8"))``.

    The historical stub here carried int16 payloads because a stock psum
    cannot sum int8 without cross-lane overflow; the comm package's
    two-phase schedule (quantize → all_to_all reduce-scatter → requantize
    → all_gather, EQuARX-style per PAPERS.md) really ships int8 + f32
    per-block scales — ~3.9× fewer wire bytes at block_size=256 instead
    of 2×.  This alias keeps the old call shape (sum semantics, no size
    threshold) and will be removed once callers migrate to
    ``paddle_tpu.distributed.comm``."""
    import warnings
    warnings.warn(
        "all_reduce_quantized is deprecated; use paddle_tpu.distributed"
        ".comm.all_reduce(x, config=CommConfig(dtype='int8')) instead",
        DeprecationWarning, stacklevel=2)
    enforce(2 <= bits <= 8,
            f"all_reduce_quantized supports 2..8 bits (int8 container), "
            f"got {bits}")
    from .comm import CommConfig
    from .comm import all_reduce as _comm_all_reduce
    cfg = CommConfig(dtype="int8", bits=bits, block_size=block_size,
                     min_size_to_compress=0)
    return _comm_all_reduce(x, op=ReduceOp.SUM, group=group, config=cfg)
