"""ZeRO-style parameter/gradient/optimizer-state sharding.

Reference: fleet/meta_parallel/sharding/sharding_stage2.py:43 (grad shard +
bucketed reduce), sharding_stage3.py:50 (param shard with pre/post forward
hooks), dygraph ZeRO-1 `DygraphShardingOptimizer`
(dygraph_optimizer/dygraph_sharding_optimizer.py:28), static
sharding_optimizer.py:45, and the public facade
`paddle.distributed.sharding.group_sharded_parallel`
(distributed/sharding/group_sharded.py).

TPU-native design (SURVEY A3; PAPERS.md "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" — the XLA-native form of this exact
component): sharding is a *placement decision*, not a runtime.  Optimizer
slots/master weights get a PartitionSpec with the ``sharding`` (or dp) axis
on their largest evenly-divisible unsharded dim; GSPMD then:

- reduce-scatters gradients into the sharded update (stage-2 semantics),
- runs the weight update on 1/N of the state per device (stage-1/ZeRO-1),
- all-gathers fresh params for the next forward when params are sharded too
  (stage-3 semantics).

The reference's bucketing, hooks, and offload logic have no analog to write:
the compiler schedules the collectives.

Two forms of ZeRO-1 live here (ISSUE 8):

- the *declarative* form below (:func:`shard_optimizer_state`): leave the
  optimizer untouched, PartitionSpec the slots over dp, and let GSPMD
  derive the reduce-scatter + sharded update;
- the *explicit* form, :class:`~paddle_tpu.distributed.comm.zero.
  ShardedOptimizer` (re-exported here): a wrapper that owns the flat
  fp32 master + 1/n slot shards, issues the reduce-scatter / all-gather
  itself (compressible via CommConfig), works inside ``shard_map``, and
  is what ``DistributedStrategy.sharding_configs["shard_weight_update"]``
  turns on.  Prefer it when the gradient sync itself must change (int8
  compression, explicit-collective drills); prefer the declarative form
  when placement alone is enough.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.errors import enforce
from .comm.zero import ShardedOptimizer  # noqa: F401  (public re-export)
from .mp_layers import _clean_spec, param_sharding
from .topology import get_mesh

__all__ = ["shard_spec_for_leaf", "shard_optimizer_state",
           "shard_params_stage3", "group_sharded_parallel",
           "ShardedOptimizer"]


def shard_spec_for_leaf(leaf, base_spec: Optional[P], axis: str, axis_size: int
                        ) -> Optional[P]:
    """Insert ``axis`` on the first dim that is (a) not already sharded in
    base_spec and (b) evenly divisible by axis_size.  None → leave
    replicated (small leaf, e.g. a scalar step counter or LN bias)."""
    if leaf is None or not hasattr(leaf, "shape") or leaf.ndim == 0:
        return None
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (leaf.ndim - len(base))
    for d in range(leaf.ndim):
        if base[d] is None and leaf.shape[d] % axis_size == 0 \
                and leaf.shape[d] >= axis_size:
            new = list(base)
            new[d] = axis
            return P(*new)
    return P(*base) if any(s is not None for s in base) else None


def _apply_specs(tree, spec_fn, mesh):
    def _place(path, leaf):
        if leaf is None:
            return None
        spec = spec_fn(path, leaf)
        if spec is None:
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, _clean_spec(mesh, spec)))
    return jax.tree_util.tree_map_with_path(_place, tree)


def shard_optimizer_state(opt_state, params_layer=None, mesh=None,
                          axis: str = "dp"):
    """ZeRO-1/2: place every slot/master leaf sharded over ``axis``
    (composing with the parameter's own TP spec when the param pytree is a
    state_dict of a Layer built from mp_layers).

    ≙ DygraphShardingOptimizer's param-to-rank assignment — here the
    "assignment" is a PartitionSpec and XLA emits the reduce-scatter +
    sharded update.
    """
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return opt_state
    n = mesh.shape[axis]

    # param name -> TP base spec (so slots inherit the mp split too)
    base_specs: Dict[str, P] = {}
    if params_layer is not None:
        for name, p in params_layer.named_parameters():
            if getattr(p, "pspec", None) is not None:
                base_specs[name] = p.pspec

    def _spec(path, leaf):
        # path like ('slots', '<param name>', 'moment1') or
        # ('master', '<param name>'); step stays replicated
        keys = [getattr(k, "key", None) for k in path]
        if keys and keys[0] == "step":
            return None
        pname = keys[1] if len(keys) > 1 else None
        base = base_specs.get(pname)
        return shard_spec_for_leaf(leaf, base, axis, n)

    return _apply_specs(opt_state, _spec, mesh)


def shard_params_stage3(layer, mesh=None, axis: str = "dp"):
    """Stage-3: parameters themselves sharded over the dp/sharding axis
    (≙ sharding_stage3.py:50).  GSPMD all-gathers just-in-time per layer in
    the forward — the reference's pre-forward hook, compiler-derived."""
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return layer
    n = mesh.shape[axis]
    for name, p in layer.named_parameters():
        spec = shard_spec_for_leaf(p.value, getattr(p, "pspec", None), axis, n)
        if spec is not None:
            p.pspec = spec
            p.value = jax.device_put(
                p.value, NamedSharding(mesh, _clean_spec(mesh, spec)))
    return layer


def group_sharded_parallel(model, optimizer, level: str = "os",
                           scaler=None, group=None, offload: bool = False,
                           sync_buffers: bool = False):
    """Public facade (≙ paddle.distributed.sharding.group_sharded_parallel):
    level 'os' = optimizer-state sharding (stage 1/2 — on TPU the grad
    reduce-scatter comes with it), 'os_g' same (alias), 'p_g_os' adds
    parameter sharding (stage 3).  Returns (model, optimizer, scaler)."""
    enforce(level in ("os", "os_g", "p_g_os"), f"unknown level {level!r}")
    mesh = get_mesh()
    if mesh is None:
        return model, optimizer, scaler
    axis = "sharding" if "sharding" in mesh.axis_names else "dp"
    if level == "p_g_os":
        shard_params_stage3(model, mesh, axis)

    # wrap the optimizer's init so freshly-built states come out sharded
    orig_init = optimizer.init

    def sharded_init(params):
        state = orig_init(params)
        return shard_optimizer_state(state, params_layer=model, mesh=mesh,
                                     axis=axis)

    optimizer.init = sharded_init
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Persist a group-sharded model (reference sharding
    save_group_sharded_model: gathers shards and writes whole weights —
    GSPMD arrays gather on host readback, so plain save does the job)."""
    import os

    from ..framework.io import save as _save
    os.makedirs(output, exist_ok=True)           # output is a directory
    base = os.path.join(output, "model")
    _save(model.state_dict(), base + ".pdparams")
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        _save(optimizer.state_dict(), base + ".pdopt")


__all__.append("save_group_sharded_model")
