"""Sequence/context parallelism for long sequences.

The reference has NO sequence-parallel machinery (verified in SURVEY §5:
no ring attention / context parallel / Ulysses anywhere in the snapshot) —
its long-sequence levers are recompute and micro-batching.  This module is
the additive TPU-native capability the north star calls for, designed as
two composable pieces:

1. **Ulysses-style all-to-all SP** (`ulysses_qkv_spec` /
   `ulysses_out_spec` + the ``sequence_parallel`` flag on GPTConfig):
   activations are sequence-sharded over the ``sp`` mesh axis everywhere
   EXCEPT inside attention, where a layout change to head-sharding (heads
   over mp×sp, full sequence per shard) lets every device run its heads on
   the whole sequence.  Under GSPMD the layout change IS the pair of
   all-to-alls — expressed as two sharding constraints, XLA inserts and
   schedules the collectives over ICI.

2. **Ring attention** (`ring_attention`): true context parallelism where no
   device ever holds the full sequence.  Called inside ``shard_map`` with
   seq-sharded q/k/v; KV chunks rotate around the ``sp`` ring via
   ``ppermute`` while each rank maintains the online-softmax running
   (max, denominator, accumulator) over arriving chunks — the blockwise/
   ring-attention recurrence, with the flash kernel's math at chunk
   granularity and jnp ops so the backward differentiates through the
   ring (remat per chunk bounds memory).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.errors import enforce
from .collective import bound_axis_size

__all__ = ["ring_attention", "ring_attention_sharded", "shard_map"]

try:
    shard_map = jax.shard_map
except AttributeError:  # jax<0.6 only exposes the experimental spelling
    from jax.experimental.shard_map import shard_map

_NEG_INF = -1e30


def _chunk_attn(q, k, v, row_off, col_off, *, scale, causal):
    """One (s_q, s_k) chunk's contribution: returns (m, l, acc) partials.

    q: (b, h, sq, d); k/v: (b, h, sk, d); offsets are the chunks' global
    sequence positions for causal masking."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = row_off + lax.broadcasted_iota(
            jnp.int32, s.shape, s.ndim - 2)
        cols = col_off + lax.broadcasted_iota(
            jnp.int32, s.shape, s.ndim - 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1)                       # (b, h, sq)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where((m <= _NEG_INF / 2)[..., None], 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m_safe, l, acc


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True,
                   scale: Optional[float] = None):
    """Context-parallel attention over a seq-sharded ring — call INSIDE
    shard_map with q, k, v of per-shard shape (b, h, s_local, d).

    Rank r owns query rows [r·s_local, (r+1)·s_local); KV chunks travel the
    ring so after n-1 rotations every rank has attended to the full
    sequence, holding only one chunk at a time (O(s_local) memory — the
    long-context property).  Communication is ``ppermute`` over ICI,
    overlappable with the chunk compute by XLA's scheduler.
    """
    n = bound_axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if scale is None:
        scale = d ** -0.5
    row_off = idx * s_local
    chunk = jax.checkpoint(
        functools.partial(_chunk_attn, scale=scale, causal=causal))

    def step(i, carry):
        m, l, acc, kc, vc = carry
        src = jnp.mod(idx - i, n)                 # whose chunk we hold now
        cm, cl, cacc = chunk(q, kc, vc, row_off, src * s_local)
        m_new = jnp.maximum(m, cm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(cm - m_new)
        l_new = alpha * l + beta * cl
        acc_new = (acc * alpha[..., None]
                   + cacc * beta[..., None].astype(cacc.dtype))
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m_new, l_new, acc_new, kc, vc

    m0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    carry = (m0, l0, acc0, k, v)
    # python loop, not fori_loop: n is small (the sp degree) and unrolling
    # lets XLA overlap each ppermute with the next chunk's compute
    for i in range(n):
        carry = step(i, carry)
    m, l, acc, _, _ = carry
    l_safe = jnp.maximum(l, 1e-30)
    return (acc / l_safe[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh=None, *, sp_axis: str = "sp",
                           dp_axis: str = "dp", mp_axis: str = "mp",
                           causal: bool = True,
                           scale: Optional[float] = None):
    """shard_map wrapper: q, k, v are GLOBAL (b, h, s, d) arrays living on
    the active hybrid mesh; sequence sharded over ``sp``, batch over
    ``dp``, heads over ``mp`` (any of which may be absent)."""
    from jax.sharding import PartitionSpec as P
    from .mp_layers import _clean_spec
    from .topology import get_mesh
    mesh = mesh or get_mesh()
    enforce(mesh is not None and sp_axis in mesh.axis_names,
            f"ring_attention_sharded needs a mesh with axis {sp_axis!r}")
    spec = _clean_spec(mesh, (dp_axis, mp_axis, sp_axis, None))
    fn = functools.partial(ring_attention, axis_name=sp_axis,
                           causal=causal, scale=scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)
