"""paddle.distributed.spawn analog (reference: distributed/spawn.py —
spawn one python process per device with env wiring, join on exit).

TPU note: on real TPU pods a process maps to a HOST (all local chips belong
to one process; jax.distributed handles the rest), so ``nprocs`` defaults to
one per host-slot rather than per chip.  ``paddle_tpu.distributed.launch``
remains the production entry point — spawn is the programmatic twin, wiring
the same PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM env contract.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import queue
import time
import traceback
from typing import Optional, Sequence

from ..framework.errors import enforce

__all__ = ["spawn"]


def _worker(fn, rank: int, nprocs: int, args, error_queue):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_TPU_SPAWN_RANK"] = str(rank)
    try:
        fn(*args)
    except Exception:
        error_queue.put((rank, traceback.format_exc()))
        raise


def spawn(func, args=(), nprocs: int = -1, join: bool = True,
          daemon: bool = False, timeout: Optional[float] = None,
          **options):
    """Launch ``nprocs`` processes running ``func(*args)`` with paddle-style
    rank env wiring.  Returns the list of processes when ``join=False``;
    otherwise monitors them, terminates the survivors as soon as any rank
    fails (a crashed rank must not hang its blocked peers), and re-raises
    the first failure."""
    enforce(not options,
            f"spawn got unsupported options {sorted(options)}")
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    enforce(nprocs >= 1, "spawn needs nprocs >= 1")
    ctx = mp.get_context("spawn")      # never fork a process holding jax
    error_queue = ctx.Queue()          # buffered: a huge traceback must not
    procs = []                         # block the child's put() mid-exit
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, tuple(args), error_queue),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs

    deadline = None if timeout is None else time.monotonic() + timeout
    failure = None
    try:
        while any(p.is_alive() for p in procs):
            try:
                failure = error_queue.get(timeout=0.2)
                break
            except queue.Empty:
                pass
            for p in procs:
                if not p.is_alive() and p.exitcode not in (0, None):
                    # the child's traceback may still be in the queue's
                    # feeder pipe — give it a grace window before falling
                    # back to the bare exit code
                    try:
                        failure = error_queue.get(timeout=2.0)
                    except queue.Empty:
                        failure = (f"pid {p.pid}",
                                   f"exit code {p.exitcode}")
                    break
            if failure is not None:
                break
            if deadline is not None and time.monotonic() > deadline:
                failure = ("-", f"spawn timed out after {timeout}s")
                break
    finally:
        if failure is not None:
            for p in procs:
                if p.is_alive():
                    p.terminate()
        for p in procs:
            p.join()
    if failure is None and not error_queue.empty():
        failure = error_queue.get()
    if failure is not None:
        raise RuntimeError(f"spawned rank {failure[0]} failed:\n"
                           f"{failure[1]}")
    for p in procs:
        enforce(p.exitcode == 0,
                f"spawned process exited with code {p.exitcode}")
    return procs
