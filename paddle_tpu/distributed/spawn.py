"""paddle.distributed.spawn analog (reference: distributed/spawn.py —
spawn one python process per device with env wiring, join on exit).

TPU note: on real TPU pods a process maps to a HOST (all local chips belong
to one process; jax.distributed handles the rest), so ``nprocs`` defaults to
one per host-slot rather than per chip.  ``paddle_tpu.distributed.launch``
remains the production entry point — spawn is the programmatic twin, wiring
the same PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM env contract.
"""
from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Optional, Sequence

from ..framework.errors import enforce

__all__ = ["spawn"]


def _worker(fn, rank: int, nprocs: int, args, error_queue):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_TPU_SPAWN_RANK"] = str(rank)
    try:
        fn(*args)
    except Exception:
        error_queue.put((rank, traceback.format_exc()))
        raise


def spawn(func, args=(), nprocs: int = -1, join: bool = True,
          daemon: bool = False, **options):
    """Launch ``nprocs`` processes running ``func(*args)`` with paddle-style
    rank env wiring.  Returns the context (list of processes) when
    ``join=False``; otherwise joins and re-raises the first failure."""
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    enforce(nprocs >= 1, "spawn needs nprocs >= 1")
    ctx = mp.get_context("spawn")      # never fork a process holding jax
    error_queue = ctx.SimpleQueue()
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, tuple(args), error_queue),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    for p in procs:
        p.join()
    if not error_queue.empty():
        rank, tb = error_queue.get()
        raise RuntimeError(f"spawned rank {rank} failed:\n{tb}")
    for p in procs:
        enforce(p.exitcode == 0,
                f"spawned process exited with code {p.exitcode}")
    return procs
