"""Model-parallel functional ops: vocab-parallel loss and embedding.

Reference:
- `c_softmax_with_cross_entropy` (operators/collective/
  c_softmax_with_cross_entropy_op.cc; CUDA kernel .cu with three in-kernel
  allreduces: logit max :123, label-selected logit :165, sum-exp :184) —
  softmax-CE over vocab-sharded logits without ever materializing the
  gathered logits.
- `c_embedding` (collective/c_embedding_op.cc) — lookup on a vocab shard with
  start_index offset; OOV rows zero, summed across shards.

Both run in two modes:
- inside ``shard_map`` over the mp axis: the explicit pmax/psum algorithm,
  token-for-token the reference kernel's communication pattern, riding ICI;
- outside (GSPMD / serial): numerically-stable global computation with a
  sharding constraint keeping logits vocab-sharded — XLA derives the same
  three reductions from the sharded reduce ops.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.errors import enforce
from .collective import bound_axis_size
from .collective import _arr, _in_axis
from .mp_layers import shard_constraint

__all__ = ["parallel_cross_entropy", "vocab_parallel_embedding",
           "parallel_log_softmax"]


def parallel_cross_entropy(logits, label, mp_axis: str = "mp",
                           reduction: str = "none",
                           ignore_index: int = -100):
    """Softmax cross-entropy over vocab-sharded logits.

    logits: (..., vocab_local) inside shard_map / (..., vocab) otherwise.
    label: (...,) global vocab indices.
    """
    logits = _arr(logits)
    label = _arr(label)
    lf = logits.astype(jnp.float32)

    if _in_axis(mp_axis):
        n = bound_axis_size(mp_axis)
        idx = lax.axis_index(mp_axis)
        vocab_local = logits.shape[-1]
        start = idx * vocab_local
        # 1) global max (reference .cu:123)
        gmax = lax.pmax(jnp.max(lf, axis=-1), mp_axis)
        shifted = lf - gmax[..., None]
        # 2) global sum-exp (reference .cu:184)
        sum_exp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), mp_axis)
        # 3) label-selected logit: only the owning shard contributes
        #    (reference .cu:165)
        local_label = label - start
        in_range = (local_label >= 0) & (local_label < vocab_local)
        safe = jnp.clip(local_label, 0, vocab_local - 1)
        picked_local = jnp.take_along_axis(
            shifted, safe[..., None].astype(jnp.int32), axis=-1)[..., 0]
        picked = lax.psum(jnp.where(in_range, picked_local, 0.0), mp_axis)
        loss = jnp.log(sum_exp) - picked
    else:
        lf = shard_constraint(lf, *((None,) * (lf.ndim - 1)), mp_axis)
        gmax = jnp.max(lf, axis=-1)
        shifted = lf - gmax[..., None]
        sum_exp = jnp.sum(jnp.exp(shifted), axis=-1)
        picked = jnp.take_along_axis(
            shifted, jnp.clip(label, 0, lf.shape[-1] - 1)[..., None]
            .astype(jnp.int32), axis=-1)[..., 0]
        loss = jnp.log(sum_exp) - picked

    return masked_token_reduce(loss, label != ignore_index, reduction)


def masked_token_reduce(loss, valid, reduction: str):
    """Shared ignore-mask + reduction semantics for every CE flavor (this
    module's vocab-parallel path and ops/fused.py's fused linear CE must
    never diverge): invalid tokens contribute 0; "mean" divides by the
    valid count (floor 1 for an all-ignored batch)."""
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(
            jnp.sum(valid.astype(loss.dtype)), 1.0)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def parallel_log_softmax(logits, mp_axis: str = "mp"):
    """log_softmax over a vocab-sharded last axis (shard_map mode)."""
    logits = _arr(logits).astype(jnp.float32)
    if not _in_axis(mp_axis):
        return jax.nn.log_softmax(logits, axis=-1)
    gmax = lax.pmax(jnp.max(logits, axis=-1), mp_axis)
    shifted = logits - gmax[..., None]
    sum_exp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), mp_axis)
    return shifted - jnp.log(sum_exp)[..., None]


def vocab_parallel_embedding(ids, table, mp_axis: str = "mp"):
    """c_embedding semantics: ``table`` is this shard's rows inside
    shard_map (rows [idx*n_local, (idx+1)*n_local)); OOV ids produce zero
    rows which psum combines into the full lookup."""
    ids = _arr(ids)
    table = _arr(table)
    if not _in_axis(mp_axis):
        return jnp.take(table, ids, axis=0)
    n_local = table.shape[0]
    idx = lax.axis_index(mp_axis)
    start = idx * n_local
    local = ids - start
    in_range = (local >= 0) & (local < n_local)
    safe = jnp.clip(local, 0, n_local - 1)
    rows = jnp.take(table, safe, axis=0)
    rows = jnp.where(in_range[..., None], rows, jnp.zeros((), rows.dtype))
    return lax.psum(rows, mp_axis)
