"""On-device pytree fingerprinting (ISSUE 11).

The integrity guard (``supervisor/integrity.py``) needs a digest of the
full training state that is (a) cheap enough to run in-graph every
``PTPU_INTEGRITY_EVERY`` steps with ONE scalar readback, (b) guaranteed
to notice a single flipped bit anywhere in the tree, and (c) equal
across ZeRO-1 dp widths holding the same logical state — dp=8 and dp=4
pad the flat master to different lengths (``comm/zero.py``'s
``repack_flat`` invariant: real elements occupy ``[0, total)``, padding
is trailing zeros), so a layout-aware digest is the only one that can
survive an elastic resize or a cross-width restore.

Digest scheme — chunked multilinear hash mod 2**32:

    leaf(x)  = Σ_j V[j] · ( Σ_k u32(x)[j·C + k] · W[k] )      (mod 2**32)
    tree     = Σ_leaf  nameweight(name) · leaf(x)             (mod 2**32)

with ``C = CHUNK`` lanes per chunk, ``W`` a fixed random vector of ODD
u32 weights, ``V[j] = (j·2654435761 + 0x9E3779B9) | 1`` the (odd) chunk
weight, and ``nameweight`` the (odd) FNV-1a hash of the leaf name.  Odd
weights buy the single-bit guarantee: flipping bit ``b < 32`` of lane
``i`` perturbs the digest by ``±2**b · W[i%C] · V[i//C]``, a power of
two times an odd number — never 0 mod 2**32.  Zero lanes contribute
nothing, so the digest is — deliberately — invariant under trailing
zero padding: that is exactly the ZeRO-1 width-invariance (c), with no
layout metadata needed.  The same argument makes an all-zeros leaf
digest to 0 regardless of its padded length.

Rank-private leaves (error-feedback residuals — legitimately different
on every replica, see ``ElasticCoordinator.ef_keys``) are EXCLUDED by
name-part match and accounted in ``Fingerprint.excluded`` so a report
can prove what the digest does not cover.

Two implementations share the weight schedule and must agree bit-for-
bit (tested): a jitted device path (:class:`TreeFingerprint`, one
compile per tree signature, leaf digests stay on device until the
attribution path asks) and a host numpy path (:func:`digest_tree_host`,
used by ``checkpoint.load_sharded`` to re-verify a restored tree
without touching the device).
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["TreeFingerprint", "Fingerprint", "digest_tree_host",
           "tree_digest", "leaf_name_weight", "is_rank_private",
           "DEFAULT_EXCLUDE", "CHUNK", "DIGEST_ALGO"]

#: lanes per chunk — leaves shorter than this cost a single weighted sum
CHUNK = 4096

#: algorithm tag stamped into checkpoint manifests; digests are only
#: comparable between equal tags
DIGEST_ALGO = "mlh32/1"

#: default rank-private exclusion patterns — MUST stay in sync with
#: ``ElasticCoordinator.ef_keys`` (same name-part match semantics)
DEFAULT_EXCLUDE: Tuple[str, ...] = ("resid", "ef_residual")

_MOD = np.uint64(1) << np.uint64(32)
# fixed seed: digests must be stable across processes, hosts and runs
_W_HOST = (np.random.RandomState(0x17D1)
           .randint(0, 2**32, size=CHUNK, dtype=np.uint64)
           .astype(np.uint32) | np.uint32(1))
_CHUNK_MUL = 2654435761       # Knuth multiplicative constant
_CHUNK_ADD = 0x9E3779B9       # golden-ratio offset


def leaf_name_weight(name: str) -> int:
    """Odd 32-bit FNV-1a of the leaf name — the tree-level combining
    weight, so 'value v under leaf A' and 'under leaf B' hash apart."""
    h = 2166136261
    for b in name.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h | 1


def is_rank_private(name: str, exclude: Sequence[str] = DEFAULT_EXCLUDE
                    ) -> bool:
    """Same name-part match as ``ElasticCoordinator._is_rank_private``."""
    parts = name.split("/")
    return any(k in parts for k in exclude)


def _flatten_named(tree) -> List[Tuple[str, Any]]:
    # identical "/"-joined naming to checkpoint._flatten so digests,
    # manifests and relayout hooks all speak about the same leaves
    from .checkpoint import _flatten
    return _flatten(tree)


# ---------------------------------------------------------------------------
# lane extraction — the exact bit pattern as u32 lanes, numpy and jnp
# ---------------------------------------------------------------------------
def _lanes_np(x) -> np.ndarray:
    x = np.ascontiguousarray(x)
    if x.dtype == np.bool_:
        x = x.astype(np.uint8)
    size = x.dtype.itemsize
    flat = x.reshape(-1)
    if size >= 4:
        # 8-byte dtypes view to two u32 words per element (low word
        # first on little-endian hosts — matched by the jnp path's
        # bitcast minor-dim order on all current platforms)
        return flat.view(np.uint32)
    if size == 2:
        return flat.view(np.uint16).astype(np.uint32)
    return flat.view(np.uint8).astype(np.uint32)


def _lanes_jnp(x: jax.Array) -> jax.Array:
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    size = jnp.dtype(x.dtype).itemsize
    flat = x.reshape(-1)
    if size >= 4:
        bits = lax.bitcast_convert_type(flat, jnp.uint32)
        return bits.reshape(-1) if size > 4 else bits
    if size == 2:
        u16 = lax.bitcast_convert_type(flat, jnp.uint16)
        return u16.astype(jnp.uint32)
    return lax.bitcast_convert_type(flat, jnp.uint8).astype(jnp.uint32)


def _leaf_digest_np(x) -> int:
    lanes = _lanes_np(x)
    n = lanes.size
    if n == 0:
        return 0
    pad = (-n) % CHUNK
    if pad:
        lanes = np.concatenate([lanes, np.zeros(pad, np.uint32)])
    rows = lanes.reshape(-1, CHUNK)
    rowsums = np.einsum("jk,k->j", rows.astype(np.uint64),
                        _W_HOST.astype(np.uint64)) % _MOD
    j = np.arange(rows.shape[0], dtype=np.uint64)
    v = (j * np.uint64(_CHUNK_MUL) + np.uint64(_CHUNK_ADD)) % _MOD | \
        np.uint64(1)
    return int((rowsums * v % _MOD).sum() % _MOD)


def _leaf_digest_jnp(x: jax.Array) -> jax.Array:
    lanes = _lanes_jnp(x)
    n = lanes.size
    if n == 0:
        return jnp.uint32(0)
    pad = (-n) % CHUNK
    if pad:
        lanes = jnp.concatenate(
            [lanes, jnp.zeros(pad, jnp.uint32)])
    rows = lanes.reshape(-1, CHUNK)
    w = jnp.asarray(_W_HOST)
    rowsums = jnp.sum(rows * w[None, :], axis=1, dtype=jnp.uint32)
    j = lax.iota(jnp.uint32, rows.shape[0])
    v = (j * jnp.uint32(_CHUNK_MUL) + jnp.uint32(_CHUNK_ADD)) \
        | jnp.uint32(1)
    return jnp.sum(rowsums * v, dtype=jnp.uint32)


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------
class Fingerprint:
    """One digest pass over a tree.

    ``tree`` (property) is the single scalar readback the per-interval
    check pays; :meth:`leaf_digests` pulls the per-leaf vector to host —
    the attribution path, taken only on mismatch.  ``excluded`` accounts
    for every rank-private leaf the digest deliberately does not cover.
    """

    def __init__(self, names: List[str], excluded: List[str],
                 tree_digest, leaf_digests):
        self.names = list(names)
        self.excluded = list(excluded)
        self._tree = tree_digest
        self._leaves = leaf_digests

    @property
    def tree(self) -> int:
        return int(self._tree)

    def hex(self) -> str:
        return f"{self.tree:08x}"

    def leaf_digests(self) -> Dict[str, int]:
        vals = np.asarray(self._leaves)
        return {n: int(v) for n, v in zip(self.names, vals)}

    def diff(self, other: "Fingerprint") -> List[str]:
        """Names of leaves whose digests differ (attribution)."""
        mine, theirs = self.leaf_digests(), other.leaf_digests()
        return sorted(n for n in mine
                      if theirs.get(n, None) != mine[n])

    def meta(self, with_leaves: bool = True) -> Dict[str, Any]:
        """JSON-ready manifest stamp (``checkpoint.save_sharded``)."""
        out: Dict[str, Any] = {"algo": DIGEST_ALGO, "tree": self.hex(),
                               "excluded": self.excluded}
        if with_leaves:
            out["leaves"] = {n: f"{d:08x}"
                             for n, d in self.leaf_digests().items()}
        return out

    def __repr__(self) -> str:
        return (f"Fingerprint(tree={self.hex()}, leaves={len(self.names)},"
                f" excluded={len(self.excluded)})")


def _combine_tree(names: Sequence[str], leaf_digests):
    w = np.array([leaf_name_weight(n) for n in names], dtype=np.uint32)
    if isinstance(leaf_digests, np.ndarray):
        return int((leaf_digests.astype(np.uint64) * w.astype(np.uint64)
                    % _MOD).sum() % _MOD)
    return jnp.sum(leaf_digests * jnp.asarray(w), dtype=jnp.uint32)


# ---------------------------------------------------------------------------
# device path
# ---------------------------------------------------------------------------
class TreeFingerprint:
    """Jitted tree digester with per-signature compile caching.

    >>> fp = TreeFingerprint()
    >>> r = fp.digest(state)       # device work + ONE scalar readback
    >>> r.hex()
    '9f2a44c1'

    ``exclude``: rank-private name-part patterns (default matches
    ``ElasticCoordinator.ef_keys``) — these leaves are skipped and
    accounted in ``Fingerprint.excluded``.
    """

    def __init__(self, exclude: Sequence[str] = DEFAULT_EXCLUDE):
        self.exclude = tuple(exclude)
        self._cache: Dict[Any, Any] = {}

    def _split(self, tree):
        named = _flatten_named(tree)
        included = [(n, x) for n, x in named
                    if not is_rank_private(n, self.exclude)]
        excluded = sorted(n for n, _ in named
                          if is_rank_private(n, self.exclude))
        included.sort(key=lambda nx: nx[0])
        return included, excluded

    def _fn(self, names, leaves):
        sig = tuple((n, np.shape(x), str(getattr(x, "dtype", type(x))))
                    for n, x in zip(names, leaves))
        fn = self._cache.get(sig)
        if fn is None:
            nm = tuple(names)

            @jax.jit
            def digest_fn(xs):
                per_leaf = jnp.stack([_leaf_digest_jnp(x) for x in xs])
                return _combine_tree(nm, per_leaf), per_leaf

            fn = self._cache[sig] = digest_fn
        return fn

    def digest(self, tree) -> Fingerprint:
        included, excluded = self._split(tree)
        names = [n for n, _ in included]
        leaves = [x for _, x in included]
        if not leaves:
            return Fingerprint(names, excluded, 0,
                               np.zeros(0, np.uint32))
        tree_d, leaf_d = self._fn(names, leaves)(leaves)
        return Fingerprint(names, excluded, tree_d, leaf_d)


# ---------------------------------------------------------------------------
# host path (checkpoint verification — no device, no compile)
# ---------------------------------------------------------------------------
def digest_tree_host(tree, exclude: Sequence[str] = DEFAULT_EXCLUDE
                     ) -> Fingerprint:
    """Numpy mirror of :meth:`TreeFingerprint.digest` — bit-identical
    digests, used where the tree already lives on host (a freshly
    restored checkpoint) or a compile is not worth paying."""
    named = _flatten_named(tree)
    excluded = sorted(n for n, _ in named if is_rank_private(n, exclude))
    included = sorted(((n, x) for n, x in named
                       if not is_rank_private(n, exclude)),
                      key=lambda nx: nx[0])
    names = [n for n, _ in included]
    leaf_d = np.array([_leaf_digest_np(np.asarray(x))
                       for _, x in included], dtype=np.uint32)
    return Fingerprint(names, excluded, _combine_tree(names, leaf_d),
                       leaf_d)


def tree_digest(tree, exclude: Sequence[str] = DEFAULT_EXCLUDE) -> int:
    """Convenience: the (blocking) tree digest as an int."""
    return digest_tree_host(tree, exclude).tree
