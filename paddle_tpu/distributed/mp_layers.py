"""Tensor-(model-)parallel layers.

Reference: fleet/meta_parallel/mp_layers.py — ``VocabParallelEmbedding``:30,
``ColumnParallelLinear``:97, ``RowParallelLinear``:170 (Megatron-style
splits), with collective ops `c_embedding` / `_mp_allreduce` / `c_split`
(collective.py:1167,1128; c_embedding_op.cc).

TPU-native design — the crucial departure from the reference: parameters stay
**global-shaped**; the split lives in a ``PartitionSpec`` attached to each
parameter (``Parameter.pspec``) and in sharding constraints on activations.
GSPMD then partitions the matmuls over the ``mp`` mesh axis and inserts
exactly the collectives the reference codes by hand:

- ColumnParallelLinear: W (in, out) sharded P(None,'mp') → output sharded on
  features; ``gather_output=True`` constrains the output replicated, which
  lowers to the all-gather the reference does with c_concat.
- RowParallelLinear: W sharded P('mp',None), input sharded on features → the
  contraction produces partial sums and GSPMD inserts the psum that the
  reference's `_mp_allreduce` performs.
- VocabParallelEmbedding: table sharded over vocab rows; the gather over a
  sharded axis lowers to the mask-lookup+psum of c_embedding_op.cc.

No weight is ever materialized per-rank in python — one program, one logical
weight, XLA owns the distribution.  Works unchanged when no mesh is active
(the specs are inert metadata), so serial and parallel runs share code —
the parallel==serial invariant (SURVEY §4) holds by construction.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.errors import enforce
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, Parameter
from .topology import get_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "shard_constraint", "param_sharding",
           "variables_sharding"]


def _clean_spec(mesh, spec) -> P:
    """Drop spec entries naming axes the mesh doesn't have (a TP spec on a
    pure-DP mesh degrades to replicated on that dim — serial-compatible)."""
    cleaned = tuple(s if (s is None or all(
        a in mesh.axis_names for a in ((s,) if isinstance(s, str) else s)))
        else None for s in spec)
    return P(*cleaned)


def shard_constraint(x, *spec, mesh=None):
    """with_sharding_constraint against the active hybrid mesh; no-op when no
    mesh is registered or the axes aren't in it (serial mode)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, _clean_spec(mesh, spec)))


def param_sharding(p, mesh=None) -> Optional[NamedSharding]:
    """NamedSharding for one Parameter from its pspec (replicated default)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return None
    spec = getattr(p, "pspec", None) or P()
    return NamedSharding(mesh, _clean_spec(mesh, spec))


def variables_sharding(layer: Layer, mesh=None):
    """{name: NamedSharding} for every parameter/buffer of ``layer`` — feed
    to jit in_shardings / jax.device_put to place the model on the mesh."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return None
    out = {}
    for name, p in layer.named_parameters():
        out[name] = param_sharding(p, mesh)
    for name, _ in layer.named_buffers():
        out[name] = NamedSharding(mesh, P())
    return out


class ColumnParallelLinear(Layer):
    """Y = X @ W[:, shard] (+b[shard]) — reference mp_layers.py:97.

    weight: (in_features, out_features) with pspec P(None, 'mp').
    gather_output=True replicates the output (c_concat analog); False keeps
    it feature-sharded for a following RowParallelLinear.
    """

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 gather_output: bool = True, mp_axis: str = "mp",
                 fuse_matmul_bias: bool = False, name: Optional[str] = None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr)
        self.weight.pspec = P(None, mp_axis)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.pspec = P(mp_axis)
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return shard_constraint(y, *((None,) * y.ndim))
        return shard_constraint(y, *((None,) * (y.ndim - 1)), self.mp_axis)


class RowParallelLinear(Layer):
    """Y = sum_over_shards(X[shard] @ W[shard, :]) + b — reference
    mp_layers.py:170.  weight: (in_features, out_features), pspec
    P('mp', None); the contraction over the sharded axis makes GSPMD emit
    the `_mp_allreduce`."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 input_is_parallel: bool = False, mp_axis: str = "mp",
                 name: Optional[str] = None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr)
        self.weight.pspec = P(mp_axis, None)
        if has_bias:
            # bias added after the cross-shard sum → replicated
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.pspec = P()
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_constraint(
                x, *((None,) * (jnp.ndim(x) - 1)), self.mp_axis)
        y = F.linear(x, self.weight, None)
        y = shard_constraint(y, *((None,) * jnp.ndim(y)))
        if self.bias is not None:
            y = y + self.bias.value.astype(y.dtype)
        return y


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dimension sharded over mp — reference
    mp_layers.py:30 (c_embedding_op.cc: local lookup with start_index offset,
    OOV rows zero, summed by mp_allreduce; GSPMD derives the same plan from
    the row-sharded gather)."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_axis: str = "mp",
                 name: Optional[str] = None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=None if getattr(
                weight_attr, "initializer", None) else I.Normal(std=0.02))
        self.weight.pspec = P(mp_axis, None)

    def forward(self, ids):
        out = F.embedding(ids, self.weight)
        return shard_constraint(out, *((None,) * jnp.ndim(out)))
