"""paddle.distributed.utils compat (reference distributed/utils.py): the
launcher's cluster model (Cluster/Pod/Trainer), host/port discovery, and
local-process management — the plumbing custom launch scripts import.

The real bring-up rides jax.distributed (launch/__init__.py); these
classes model the same topology so ported orchestration code (building a
Cluster from endpoints, watching trainer procs) runs unchanged."""
from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["get_host_name_ip", "Trainer", "get_cluster",
           "start_local_trainers", "watch_local_trainers",
           "find_free_ports", "JobServer", "Cluster", "Pod", "Hdfs",
           "add_arguments", "terminate_local_procs", "TrainerProc",
           "get_logger", "pull_worker_log", "global_scatter",
           "global_gather"]


def get_logger(log_level=20,
               name: str = "paddle_tpu.distributed") -> logging.Logger:
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(levelname)s %(asctime)s %(message)s"))
        logger.addHandler(h)
    return logger


logger = get_logger()


def get_host_name_ip():
    try:
        name = socket.gethostname()
        return name, socket.gethostbyname(name)
    except OSError:
        return None


def find_free_ports(num: int) -> Optional[set]:
    """num locally-free TCP ports (reference find_free_ports)."""
    out: set = set()
    attempts = 0
    while len(out) < num and attempts < 100 * num:
        attempts += 1
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            out.add(s.getsockname()[1])
    return out if len(out) == num else None


class Hdfs:
    """HDFS connection descriptor (reference utils.Hdfs) — config only."""

    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return (self.hdfs_ugi is not None and self.hdfs_name is not None
                and self.hdfs_path is not None)

    def __eq__(self, other):
        return (self.hdfs_ugi == other.hdfs_ugi
                and self.hdfs_name == other.hdfs_name
                and self.hdfs_path == other.hdfs_path)

    def __ne__(self, other):
        return not self == other

    def __str__(self):
        return f"hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} " \
               f"hdfs_path:{self.hdfs_path}"


class Trainer:
    """One trainer endpoint (reference utils.Trainer)."""

    def __init__(self):
        self.gpus: List[int] = []
        self.endpoint: Optional[str] = None
        self.rank: Optional[int] = None

    def __str__(self):
        return f"gpu:{self.gpus} endpoint:{self.endpoint} rank:{self.rank}"

    def __eq__(self, other):
        return (self.gpus == other.gpus and self.endpoint == other.endpoint
                and self.rank == other.rank)

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)


class Pod:
    """One host's worth of trainers (reference utils.Pod)."""

    def __init__(self):
        self.rank: Optional[int] = None
        self.id: Optional[str] = None
        self.addr: Optional[str] = None
        self.port: Optional[int] = None
        self.trainers: List[Trainer] = []
        self.gpus: List[int] = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} trainers:{[str(t) for t in self.trainers]}")

    def __eq__(self, other):
        if (self.rank != other.rank or self.id != other.id
                or self.addr != other.addr or self.port != other.port
                or len(self.trainers) != len(other.trainers)):
            return False
        return all(a == b for a, b in zip(self.trainers, other.trainers))

    def __ne__(self, other):
        return not self == other

    def parse_response(self, res_pods):
        pass

    def rank_str(self):
        return str(self.rank)

    def get_visible_gpus(self):
        return ",".join(str(g) for g in self.gpus)


class Cluster:
    """The whole job (reference utils.Cluster)."""

    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods: List[Pod] = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return f"pods:{[str(p) for p in self.pods]} " \
               f"job_stage_flag:{self.job_stage_flag}"

    def __eq__(self, other):
        if len(self.pods) != len(other.pods):
            return False
        return all(a == b for a, b in zip(self.pods, other.pods))

    def __ne__(self, other):
        return not self == other

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self) -> int:
        return len(self.trainers_endpoints())

    def pods_nranks(self) -> int:
        return len(self.pods)

    def trainers_endpoints(self) -> List[str]:
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self) -> List[str]:
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def get_pod_by_id(self, pod_id):
        for p in self.pods:
            if str(p.id) == str(pod_id):
                return p
        return None


class JobServer:
    def __init__(self):
        self.endpoint: Optional[str] = None

    def __str__(self):
        return str(self.endpoint)

    def __eq__(self, other):
        return self.endpoint == other.endpoint

    def __ne__(self, other):
        return not self == other


def get_cluster(node_ips, node_ip, trainer_endpoints, device_mode=None,
                devices_per_proc=None) -> tuple:
    """Build (Cluster, current Pod) from endpoint lists (reference
    get_cluster); ``devices_per_proc`` defaults to one device per
    trainer."""
    if isinstance(trainer_endpoints[0], str):
        trainer_endpoints = [[e] for e in trainer_endpoints]
    cluster = Cluster(hdfs=None)
    cur_pod = None
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        for i, endpoint in enumerate(trainer_endpoints[node_rank]):
            trainer = Trainer()
            trainer.endpoint = endpoint
            trainer.rank = sum(len(p.trainers) for p in cluster.pods) + i
            if devices_per_proc is not None and i < len(devices_per_proc):
                d = devices_per_proc[i]
                trainer.gpus = list(d) if isinstance(d, (list, tuple)) \
                    else [d]
            pod.trainers.append(trainer)
        cluster.pods.append(pod)
        if ip == node_ip:
            cur_pod = pod
    return cluster, cur_pod


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def start_local_trainers(cluster: Cluster, pod: Pod, training_script: str,
                         training_script_args, log_dir=None,
                         envs=None) -> List[TrainerProc]:
    """Spawn one python process per trainer in ``pod`` with the PADDLE_*
    env contract (reference start_local_trainers)."""
    procs = []
    current_env = {k: v for k, v in os.environ.items()
                   if k not in ("http_proxy", "https_proxy")}
    if envs:
        current_env.update(envs)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for idx, t in enumerate(pod.trainers):
        proc_env = {
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_CURRENT_ENDPOINT": str(t.endpoint),
            "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(
                cluster.trainers_endpoints()),
        }
        env = dict(current_env)
        env.update(proc_env)
        cmd = [sys.executable, "-u", training_script] + list(
            training_script_args)
        fn = None
        if log_dir:
            fn = open(os.path.join(log_dir, f"workerlog.{idx}"),  # noqa: fsio — live stream handle for Popen, not a durable commit
                      "a")
        proc = subprocess.Popen(cmd, env=env, stdout=fn or None,
                                stderr=fn or None)
        tp = TrainerProc()
        tp.proc = proc
        tp.rank = t.rank
        tp.local_rank = idx
        tp.log_fn = fn
        tp.log_offset = fn.tell() if fn else None
        tp.cmd = cmd
        procs.append(tp)
    return procs


def pull_worker_log(tp: TrainerProc):
    if tp.log_fn is None:
        return
    with open(tp.log_fn.name) as fin:
        fin.seek(tp.log_offset, 0)
        for line in fin:
            try:
                sys.stdout.write(line)
            except UnicodeEncodeError:
                pass
        tp.log_offset = fin.tell()


def watch_local_trainers(procs: List[TrainerProc],
                         nranks: int) -> List[TrainerProc]:
    """Poll trainer procs; a failed proc terminates the rest (reference
    watch_local_trainers fail-fast doctrine)."""
    alive = []
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive.append(tp)
        elif ret != 0:
            logger.error(f"trainer rank {tp.rank} exited with {ret}; "
                         "aborting the pod")
            terminate_local_procs(procs)
            raise subprocess.SubprocessError(
                f"trainer {tp.rank} failed (exit {ret})")
    return alive


def terminate_local_procs(procs: List[TrainerProc]) -> None:
    for tp in procs:
        if tp.proc is not None and tp.proc.poll() is None:
            tp.proc.terminate()
    deadline = time.time() + 10
    for tp in procs:
        if tp.proc is not None:
            try:
                tp.proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                tp.proc.kill()
        if tp.log_fn:
            tp.log_fn.close()


def add_arguments(argname: str, type, default, help, argparser):  # noqa: A002
    """argparse helper (reference utils.add_arguments)."""
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + f" Default: {default}.")


# MoE all-to-all dispatch entry points (the reference exports them from
# distributed.utils as well as incubate; same shard_map collectives)
def global_scatter(*args, **kwargs):
    from .moe import global_scatter as _gs
    return _gs(*args, **kwargs)


def global_gather(*args, **kwargs):
    from .moe import global_gather as _gg
    return _gg(*args, **kwargs)
