"""Data parallelism + parallel environment.

Reference: python/paddle/fluid/dygraph/parallel.py:413 ``DataParallel``
(python side of the C++ bucketing Reducer, imperative/reducer.h:126) and
``init_parallel_env`` / ``ParallelEnv`` (distributed/parallel.py).

TPU-native design: data parallelism is a sharding of the batch axis over the
'dp' mesh axis inside one jitted SPMD program.  The gradient all-reduce the
reference implements with a bucketed NCCL Reducer is derived by XLA from the
batch-sharded loss reduction — overlapped and fused by the compiler's
collective scheduler, which is precisely what reducer.cc hand-builds.
``DataParallel`` therefore carries no communication code: it annotates and
validates, keeping the reference's API shape (scale_loss, no_sync) for
ported user code.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.errors import enforce
from ..nn.layer import Layer
from . import topology as topo
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_mesh, set_hybrid_communicate_group)

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "DataParallel", "shard_batch", "device_put_sharded_variables"]


def init_parallel_env(dp_degree: Optional[int] = None) -> "ParallelEnv":
    """Bring up the parallel environment (reference distributed/parallel.py
    init_parallel_env; rendezvous ≙ jax.distributed.initialize, which the TPU
    runtime drives from pod metadata instead of TCPStore env vars).

    Single-host: builds a pure-DP mesh over all local devices unless a
    hybrid mesh was already installed via fleet.init().
    """
    if (int(os.environ.get("PADDLE_TPU_MULTIHOST", "0"))
            or os.environ.get("PADDLE_TRAINERS_NUM", "1") != "1"):
        # multi-host: one process per host, all hosts see the global mesh;
        # rendezvous wired by the launcher's env vars (distributed.launch)
        from .launch import init_from_env
        init_from_env()
    if topo.get_hybrid_communicate_group() is None:
        n = dp_degree or jax.device_count()
        t = CommunicateTopology(["data"], [n])
        set_hybrid_communicate_group(HybridCommunicateGroup(t))
    return ParallelEnv()


def get_rank() -> int:
    """Host process index (reference dist.get_rank; under single-controller
    SPMD this is the controller's process, not a per-device rank)."""
    return jax.process_index()


def get_world_size() -> int:
    """Total device count across the mesh (reference dist.get_world_size
    counts trainer processes = devices, one device per process)."""
    mesh = get_mesh()
    return mesh.size if mesh is not None else jax.device_count()


class ParallelEnv:
    """Reference parallel.py ParallelEnv env-var bundle."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        return 0

    @property
    def nranks(self) -> int:
        return get_world_size()

    local_rank = rank


def shard_batch(batch, mesh=None, axis: str = "dp"):
    """Place a host batch on the mesh, sharded along the leading (batch)
    dimension over the dp axis — the input half of data parallelism."""
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        return jnp.asarray(batch)

    def _put(x):
        x = jnp.asarray(x)
        spec = P(axis, *(None,) * (x.ndim - 1))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(_put, batch)


def device_put_sharded_variables(layer: Layer, mesh=None):
    """Place every parameter/buffer on the mesh per its pspec (replicated
    default) — the analog of the reference's broadcast of initial parameters
    to all ranks (hybrid_parallel_util.py broadcast_dp_parameters)."""
    from .mp_layers import param_sharding
    mesh = mesh or get_mesh()
    if mesh is None:
        return layer
    for _, p in layer.named_parameters():
        p.value = jax.device_put(p.value, param_sharding(p, mesh))
    for path, sub in layer.named_sublayers(include_self=True):
        for bname, b in list(sub._buffers.items()):
            sub._buffers[bname] = jax.device_put(
                b, NamedSharding(mesh, P()))
    return layer


class DataParallel(Layer):
    """API-parity wrapper (reference parallel.py:413).  Validates the mesh,
    places parameters, and forwards; gradient synchronization is derived by
    XLA from batch-sharded loss (see module docstring)."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int = 25,
                 last_comm_buffer_size: int = 1, find_unused_parameters: bool = False):
        super().__init__()
        mesh = get_mesh()
        if mesh is None:
            init_parallel_env()
        self._layers = layers
        device_put_sharded_variables(layers)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        """The reference divides loss by nranks before backward; under a
        batch-sharded mean-loss this is already the global mean — identity."""
        return loss

    def apply_collective_grads(self):
        """No-op: XLA inserts/overlaps the grad all-reduce (reducer.cc:153
        FusedAllReduceSchedule analog is the compiler's collective fusion)."""
        return None

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
