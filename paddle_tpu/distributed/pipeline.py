"""Pipeline parallelism: GSPMD-vectorized micro-batch schedule.

Reference: fleet/meta_parallel/pp_layers.py ``PipelineLayer``:132 (stage
partitioning, ``SegmentLayers``:63), ``PipelineParallel`` 1F1B schedule
(pipeline_parallel.py:80-152) with NCCL p2p (p2p_communication.py:216), and
the static-graph twin ``PipelineOptimizer`` (fluid/optimizer.py:4314,
schedule modes F-then-B :5013 and 1F1B :5043).

TPU-native design (SURVEY §7 hard-part 1, option b): there is no NCCL-style
p2p on ICI, and host-driven per-stage programs would re-create the executor
zoo this framework deliberately collapses.  Instead the whole pipeline is ONE
jitted SPMD program:

- layer parameters are stacked on a leading *stage* axis sharded over the
  ``pp`` mesh axis — each device holds its stage's weights;
- one "tick" applies ALL stages in parallel via ``jax.vmap`` over the stage
  axis — on device s that computes stage s on its current micro-batch;
- the activation buffer rolls by one stage between ticks (``jnp.roll`` on
  the pp-sharded axis → XLA emits exactly the ``collective_permute`` that
  p2p_communication.py's send/recv pairs perform);
- ``lax.scan`` runs M + S - 1 ticks (fill + steady + drain) — the F-then-B
  schedule; the backward of the scan replays ticks in reverse, giving the
  B-phases.  Per-stage activation memory is bounded by ``jax.checkpoint``
  around the stage body (the role 1F1B's early backwards play in the
  reference; remat is the TPU-native lever for the same peak-memory goal).

The bubble fraction is (S-1)/(M+S-1), identical to the reference's F-then-B.
"""
from __future__ import annotations

import functools
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.errors import enforce
from .mp_layers import _clean_spec
from .topology import get_mesh

__all__ = ["gpipe_spmd", "stack_stage_params", "unstack_stage_params",
           "split_microbatches", "merge_microbatches", "pipeline_stage_specs"]


def split_microbatches(batch, num_microbatches: int):
    """(B, ...) → (M, B/M, ...) for every leaf."""
    def _split(x):
        b = x.shape[0]
        enforce(b % num_microbatches == 0,
                f"batch {b} not divisible by {num_microbatches} microbatches")
        return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
    return jax.tree_util.tree_map(_split, batch)


def merge_microbatches(mb):
    return jax.tree_util.tree_map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), mb)


def stack_stage_params(params: Dict[str, Any], layer_re: str,
                       num_stages: int) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Group per-layer parameters into stage-stacked arrays.

    ``layer_re`` must capture the layer index, e.g. r"gpt\\.h\\.(\\d+)\\.(.*)".
    Returns (stacked, rest): stacked maps each per-layer suffix to an array
    of shape (num_stages, layers_per_stage, ...); rest holds all non-layer
    params (embeddings, final LN, head).  ≙ the reference's SegmentLayers
    uniform cut (pp_layers.py:63).
    """
    pat = re.compile(layer_re)
    by_layer: Dict[int, Dict[str, Any]] = {}
    rest: Dict[str, Any] = {}
    for name, v in params.items():
        m = pat.match(name)
        if m:
            idx = int(m.group(1))
            by_layer.setdefault(idx, {})[m.group(2)] = v
        else:
            rest[name] = v
    n_layers = len(by_layer)
    enforce(n_layers > 0, f"no params matched layer pattern {layer_re!r}")
    enforce(n_layers % num_stages == 0,
            f"{n_layers} layers not divisible into {num_stages} stages")
    per = n_layers // num_stages
    suffixes = by_layer[0].keys()
    stacked = {}
    for suf in suffixes:
        leaves = [by_layer[i][suf] for i in range(n_layers)]
        arr = jnp.stack(leaves).reshape(num_stages, per, *leaves[0].shape)
        stacked[suf] = arr
    return stacked, rest


def unstack_stage_params(stacked: Dict[str, Any], name_fmt: str
                         ) -> Dict[str, Any]:
    """Inverse of stack_stage_params: (S, L, ...) arrays → flat per-layer
    dict with names ``name_fmt.format(i=<layer index>, suffix=<suffix>)``."""
    out = {}
    for suf, arr in stacked.items():
        s, l = arr.shape[0], arr.shape[1]
        flat = arr.reshape(s * l, *arr.shape[2:])
        for i in range(s * l):
            out[name_fmt.format(i=i, suffix=suf)] = flat[i]
    return out


def pipeline_stage_specs(stacked: Dict[str, Any], pp_axis: str = "pp",
                         mesh=None) -> Optional[Dict[str, NamedSharding]]:
    """NamedShardings putting the stage axis on ``pp`` (leading dim),
    remaining dims replicated/TP-inherited is left to GSPMD propagation."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return None
    return {k: NamedSharding(mesh, _clean_spec(mesh, (pp_axis,)))
            for k in stacked}


def gpipe_spmd(stage_fn: Callable, stage_params, microbatches, *,
               pp_axis: str = "pp", remat: bool = True):
    """Run the micro-batch pipeline; returns last-stage outputs (M, ...).

    stage_fn(stage_param_slice, x) -> y — applies ONE stage (its chunk of
    layers) to one micro-batch activation; input/output shapes must match
    (uniform trunk), the transformer-decoder property.

    stage_params: pytree with a leading stage axis S on every leaf (from
    stack_stage_params), ideally placed P('pp', ...).
    microbatches: (M, mb, ...) activations entering stage 0.
    """
    leaves = jax.tree_util.tree_leaves(stage_params)
    enforce(len(leaves) > 0, "empty stage params")
    num_stages = leaves[0].shape[0]
    m = microbatches.shape[0]
    enforce(m >= 1, "need at least one microbatch")
    mesh = get_mesh()

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)
    vstage = jax.vmap(body, in_axes=(0, 0))

    def constrain(buf):
        if mesh is not None and pp_axis in mesh.axis_names:
            spec = (pp_axis,) + (None,) * (buf.ndim - 1)
            return jax.lax.with_sharding_constraint(
                buf, NamedSharding(mesh, P(*spec)))
        return buf

    buf0 = jnp.zeros((num_stages,) + microbatches.shape[1:],
                     microbatches.dtype)

    def tick(buf, t):
        # stage s receives what stage s-1 produced last tick (ppermute);
        # stage 0 receives micro-batch t (zeros after the last one — those
        # ticks only drain the tail stages)
        shifted = jnp.roll(buf, 1, axis=0)
        idx = jnp.clip(t, 0, m - 1)
        inp = lax.dynamic_index_in_dim(microbatches, idx, axis=0,
                                       keepdims=False)
        inp = jnp.where(t < m, inp, jnp.zeros_like(inp))
        shifted = shifted.at[0].set(inp)
        shifted = constrain(shifted)
        out = vstage(stage_params, shifted)
        out = constrain(out)
        return out, out[num_stages - 1]

    _, taps = lax.scan(tick, buf0, jnp.arange(m + num_stages - 1))
    # micro-batch j exits the last stage at tick j + S - 1
    return taps[num_stages - 1:]
