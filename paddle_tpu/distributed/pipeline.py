"""Pipeline parallelism: GSPMD-vectorized micro-batch schedule.

Reference: fleet/meta_parallel/pp_layers.py ``PipelineLayer``:132 (stage
partitioning, ``SegmentLayers``:63), ``PipelineParallel`` 1F1B schedule
(pipeline_parallel.py:80-152) with NCCL p2p (p2p_communication.py:216), and
the static-graph twin ``PipelineOptimizer`` (fluid/optimizer.py:4314,
schedule modes F-then-B :5013 and 1F1B :5043).

TPU-native design (SURVEY §7 hard-part 1, option b): there is no NCCL-style
p2p on ICI, and host-driven per-stage programs would re-create the executor
zoo this framework deliberately collapses.  Instead the whole pipeline is ONE
jitted SPMD program:

- layer parameters are stacked on a leading *stage* axis sharded over the
  ``pp`` mesh axis — each device holds its stage's weights;
- one "tick" applies ALL stages in parallel via ``jax.vmap`` over the stage
  axis — on device s that computes stage s on its current micro-batch;
- the activation buffer rolls by one stage between ticks (``jnp.roll`` on
  the pp-sharded axis → XLA emits exactly the ``collective_permute`` that
  p2p_communication.py's send/recv pairs perform);
- ``lax.scan`` runs M + S - 1 ticks (fill + steady + drain) — the F-then-B
  schedule; the backward of the scan replays ticks in reverse, giving the
  B-phases.  Per-stage activation memory is bounded by ``jax.checkpoint``
  around the stage body (the role 1F1B's early backwards play in the
  reference; remat is the TPU-native lever for the same peak-memory goal).

The bubble fraction is (S-1)/(M+S-1), identical to the reference's F-then-B.
"""
from __future__ import annotations

import functools
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.errors import enforce
from .mp_layers import _clean_spec
from .topology import get_mesh

__all__ = ["gpipe_spmd", "one_f_one_b_spmd", "stack_stage_params",
           "unstack_stage_params", "split_microbatches", "merge_microbatches",
           "pipeline_stage_specs", "stacked_stage_specs"]


def split_microbatches(batch, num_microbatches: int):
    """(B, ...) → (M, B/M, ...) for every leaf."""
    def _split(x):
        b = x.shape[0]
        enforce(b % num_microbatches == 0,
                f"batch {b} not divisible by {num_microbatches} microbatches")
        return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
    return jax.tree_util.tree_map(_split, batch)


def merge_microbatches(mb):
    return jax.tree_util.tree_map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), mb)


def stack_stage_params(params: Dict[str, Any], layer_re: str,
                       num_stages: int) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Group per-layer parameters into stage-stacked arrays.

    ``layer_re`` must capture the layer index, e.g. r"gpt\\.h\\.(\\d+)\\.(.*)".
    Returns (stacked, rest): stacked maps each per-layer suffix to an array
    of shape (num_stages, layers_per_stage, ...); rest holds all non-layer
    params (embeddings, final LN, head).  ≙ the reference's SegmentLayers
    uniform cut (pp_layers.py:63).
    """
    pat = re.compile(layer_re)
    by_layer: Dict[int, Dict[str, Any]] = {}
    rest: Dict[str, Any] = {}
    for name, v in params.items():
        m = pat.match(name)
        if m:
            idx = int(m.group(1))
            by_layer.setdefault(idx, {})[m.group(2)] = v
        else:
            rest[name] = v
    n_layers = len(by_layer)
    enforce(n_layers > 0, f"no params matched layer pattern {layer_re!r}")
    enforce(n_layers % num_stages == 0,
            f"{n_layers} layers not divisible into {num_stages} stages")
    per = n_layers // num_stages
    suffixes = by_layer[0].keys()
    stacked = {}
    for suf in suffixes:
        leaves = [by_layer[i][suf] for i in range(n_layers)]
        arr = jnp.stack(leaves).reshape(num_stages, per, *leaves[0].shape)
        stacked[suf] = arr
    return stacked, rest


def unstack_stage_params(stacked: Dict[str, Any], name_fmt: str
                         ) -> Dict[str, Any]:
    """Inverse of stack_stage_params: (S, L, ...) arrays → flat per-layer
    dict with names ``name_fmt.format(i=<layer index>, suffix=<suffix>)``."""
    out = {}
    for suf, arr in stacked.items():
        s, l = arr.shape[0], arr.shape[1]
        flat = arr.reshape(s * l, *arr.shape[2:])
        for i in range(s * l):
            out[name_fmt.format(i=i, suffix=suf)] = flat[i]
    return out


def pipeline_stage_specs(stacked: Dict[str, Any], pp_axis: str = "pp",
                         mesh=None) -> Optional[Dict[str, NamedSharding]]:
    """NamedShardings putting the stage axis on ``pp`` (leading dim) with
    every other dim replicated — the TP-less special case of
    :func:`stacked_stage_specs`."""
    return stacked_stage_specs(stacked, {}, pp_axis=pp_axis, mesh=mesh)


def gpipe_spmd(stage_fn: Callable, stage_params, microbatches, *,
               pp_axis: str = "pp", remat: bool = True):
    """Run the micro-batch pipeline; returns last-stage outputs (M, ...).

    stage_fn(stage_param_slice, x) -> y — applies ONE stage (its chunk of
    layers) to one micro-batch activation; input/output shapes must match
    (uniform trunk), the transformer-decoder property.

    stage_params: pytree with a leading stage axis S on every leaf (from
    stack_stage_params), ideally placed P('pp', ...).
    microbatches: (M, mb, ...) activations entering stage 0.
    """
    leaves = jax.tree_util.tree_leaves(stage_params)
    enforce(len(leaves) > 0, "empty stage params")
    num_stages = leaves[0].shape[0]
    m = microbatches.shape[0]
    enforce(m >= 1, "need at least one microbatch")
    mesh = get_mesh()

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)
    vstage = jax.vmap(body, in_axes=(0, 0))

    def constrain(buf):
        if mesh is not None and pp_axis in mesh.axis_names:
            spec = (pp_axis,) + (None,) * (buf.ndim - 1)
            return jax.lax.with_sharding_constraint(
                buf, NamedSharding(mesh, P(*spec)))
        return buf

    buf0 = jnp.zeros((num_stages,) + microbatches.shape[1:],
                     microbatches.dtype)

    def tick(buf, t):
        # stage s receives what stage s-1 produced last tick (ppermute);
        # stage 0 receives micro-batch t (zeros after the last one — those
        # ticks only drain the tail stages)
        shifted = jnp.roll(buf, 1, axis=0)
        idx = jnp.clip(t, 0, m - 1)
        inp = lax.dynamic_index_in_dim(microbatches, idx, axis=0,
                                       keepdims=False)
        inp = jnp.where(t < m, inp, jnp.zeros_like(inp))
        shifted = shifted.at[0].set(inp)
        shifted = constrain(shifted)
        out = vstage(stage_params, shifted)
        out = constrain(out)
        return out, out[num_stages - 1]

    _, taps = lax.scan(tick, buf0, jnp.arange(m + num_stages - 1))
    # micro-batch j exits the last stage at tick j + S - 1
    return taps[num_stages - 1:]


def stacked_stage_specs(stacked: Dict[str, Any],
                        layer0_pspecs: Dict[str, Any],
                        pp_axis: str = "pp", mesh=None):
    """NamedShardings for stage-stacked params composing pp with TP.

    ``layer0_pspecs`` maps each suffix to the per-layer param's PartitionSpec
    (e.g. a ColumnParallelLinear weight's ``P(None, 'mp')``); the stacked
    leaf (S, L, ...) gets ``P(pp, None, *per_layer_spec)`` — stage axis on
    the pp mesh axis, TP axes intact.  ≙ the reference's per-stage parameter
    placement (pp_layers.py) combined with mp_layers' weight splits."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return None
    out = {}
    for suf in stacked:
        per = tuple(layer0_pspecs.get(suf) or ())
        out[suf] = NamedSharding(
            mesh, _clean_spec(mesh, (pp_axis, None) + per))
    return out


def one_f_one_b_spmd(stage_fn: Callable, stage_params, microbatches,
                     post_fn: Callable, post_params, post_aux, *,
                     pp_axis: str = "pp", batch_axis: str = "dp",
                     has_aux: bool = False, aux_weight: float = 1.0):
    """1F1B pipeline schedule as ONE SPMD program with a hand-scheduled,
    interleaved backward — the TPU-native rendering of the reference's
    defining schedule (pipeline_parallel.py:80-152 forward_backward_pipeline:
    warmup / steady 1F1B / cooldown) and its static twin
    (fluid/optimizer.py:5043 schedule mode '1F1B').

    Why not ``jax.grad`` over the gpipe scan: that saves every tick's rolled
    activation buffer — O((M+S)·S) residual memory, exactly the peak the
    reference adopted 1F1B to avoid.  Here the backward wave runs *inside*
    the same ``lax.scan``, offset so stage s starts micro-batch j's backward
    as soon as the cotangent arrives; forward inputs are stashed in a ring
    of depth 2S (a stage's stash lifetime is ≤ 2(S-1)+1 ticks) and each
    backward tick recomputes its stage forward via ``jax.vjp`` (activation
    recompute, ≙ the reference pairing recompute with pp).  Peak activation
    memory is O(S · 2S · mb) — independent of M, the 1F1B property.

    Like gpipe_spmd, stages are vectorized over the pp mesh axis (vmap +
    roll ≙ the p2p send/recv pairs of p2p_communication.py:216); the
    cotangent buffer rolls the opposite direction.

    Args:
      stage_fn(p_slice, x, mb_idx, stage_idx) -> y: applies one stage to one
        micro-batch; ``mb_idx``/``stage_idx`` are traced scalars for RNG
        folding (ignore them for deterministic stages).  x and y must have
        identical shape/dtype (uniform trunk).
      stage_params: pytree, every leaf with leading stage axis S.
      microbatches: (M, mb, ...) activations entering stage 0.
      post_fn(q, y, aux) -> scalar: per-micro-batch loss contribution on the
        LAST stage's output (ln_f + head + CE for GPT); must already include
        the 1/M factor so the returned per-micro-batch losses sum to the
        batch loss.
      post_params: pytree q (grads for every leaf are accumulated, zeros for
        unused leaves — tied embeddings just appear in both post and embed
        grads and sum outside).
      post_aux: pytree of (M, ...) leaves indexed by exiting micro-batch
        (labels).
      has_aux: when True, stage_fn returns ``(y, aux)`` with ``aux`` a scalar
        per-stage loss term (MoE load-balance loss); the scheduler sums aux
        over every (stage, micro-batch) and differentiates it with cotangent
        ``aux_weight`` alongside the activation cotangents.

    Returns:
      ``(losses (M,), stage_grads, post_grads, d_microbatches)`` — or with
      ``has_aux``, ``(losses, aux_total, stage_grads, post_grads,
      d_microbatches)``.  Total loss = sum(losses) + aux_weight · aux_total;
      d_microbatches is the cotangent w.r.t. the pipeline inputs, to be fed
      into the embedding's backward outside.
    """
    leaves = jax.tree_util.tree_leaves(stage_params)
    enforce(len(leaves) > 0, "empty stage params")
    S = leaves[0].shape[0]
    M = microbatches.shape[0]
    enforce(M >= 1, "need at least one microbatch")
    K = 2 * S                       # stash ring depth ≥ max lifetime 2S-1
    T = M + 2 * S - 1
    mesh = get_mesh()
    stage_ids = jnp.arange(S)

    def constrain(x, *spec):
        if mesh is None:
            return x
        full = spec + (None,) * (x.ndim - len(spec))
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, _clean_spec(mesh, full)))

    if has_aux:
        stage_fn_a = stage_fn
    else:
        def stage_fn_a(p, x, mb_idx, stage_idx):
            return stage_fn(p, x, mb_idx, stage_idx), jnp.zeros(
                (), jnp.float32)

    vfwd = jax.vmap(stage_fn_a, in_axes=(0, 0, 0, 0))

    def _stage_vjp(p, x, mb_idx, stage_idx, g, aux_ct):
        _, pull = jax.vjp(
            lambda pp_, xx: stage_fn_a(pp_, xx, mb_idx, stage_idx), p, x)
        return pull((g, aux_ct))    # (dp, dx)

    vbwd = jax.vmap(_stage_vjp, in_axes=(0, 0, 0, 0, 0, 0))
    vloss = jax.value_and_grad(post_fn, argnums=(0, 1))

    mb_shape = microbatches.shape[1:]
    zeros_mb = jnp.zeros(mb_shape, microbatches.dtype)
    fbuf0 = jnp.zeros((S,) + mb_shape, microbatches.dtype)
    gbuf0 = jnp.zeros((S,) + mb_shape, jnp.float32)
    stash0 = jnp.zeros((S, K) + mb_shape, microbatches.dtype)
    acc_stage0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), stage_params)
    acc_post0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), post_params)
    losses0 = jnp.zeros((M,), jnp.float32)
    dinp0 = jnp.zeros_like(microbatches, shape=(M,) + mb_shape,
                           dtype=jnp.float32)

    def tick(carry, t):
        (fbuf, gbuf, pending, stash, acc_s, acc_p, losses, dinp,
         aux_acc) = carry

        # ---- forward wave: roll down one stage, feed micro-batch t ----
        shifted = jnp.roll(fbuf, 1, axis=0)
        f_in = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, M - 1), axis=0, keepdims=False)
        f_in = jnp.where(t < M, f_in, zeros_mb)
        shifted = shifted.at[0].set(f_in)
        shifted = constrain(shifted, pp_axis, batch_axis)
        f_mb = t - stage_ids                        # (S,)
        f_valid = (f_mb >= 0) & (f_mb < M)

        # stash this tick's stage inputs (ring slot = mb index mod K)
        def put(row, x, r, v):
            cur = lax.dynamic_index_in_dim(row, r, axis=0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                row, jnp.where(v, x, cur), r, axis=0)
        stash = jax.vmap(put)(stash, shifted, jnp.mod(f_mb, K), f_valid)
        stash = constrain(stash, pp_axis, None, batch_axis)

        out, aux_s = vfwd(stage_params, shifted, jnp.maximum(f_mb, 0),
                          stage_ids)
        out = constrain(out, pp_axis, batch_axis)
        aux_acc = aux_acc + jnp.sum(jnp.where(f_valid, aux_s, 0.0))

        # ---- loss + cotangent seed at the exit stage ----
        e = t - (S - 1)
        e_valid = (e >= 0) & (e < M)
        e_c = jnp.clip(e, 0, M - 1)
        aux_e = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, e_c, 0, keepdims=False),
            post_aux)
        loss_e, (dq, dy) = vloss(post_params, out[S - 1], aux_e)
        cur_l = lax.dynamic_index_in_dim(losses, e_c, 0, keepdims=False)
        losses = lax.dynamic_update_index_in_dim(
            losses, jnp.where(e_valid, loss_e, cur_l), e_c, 0)
        acc_p = jax.tree_util.tree_map(
            lambda a, d: a + jnp.where(e_valid, d.astype(a.dtype), 0), acc_p, dq)
        new_pending = jnp.where(e_valid, dy.astype(jnp.float32),
                                jnp.zeros_like(gbuf0[0]))

        # ---- backward wave: roll up one stage, seed at the last stage ----
        gshift = jnp.roll(gbuf, -1, axis=0)
        gshift = gshift.at[S - 1].set(pending)
        gshift = constrain(gshift, pp_axis, batch_axis)
        b_mb = t - 2 * S + 1 + stage_ids            # (S,)
        b_valid = (b_mb >= 0) & (b_mb < M)
        b_c = jnp.clip(b_mb, 0, M - 1)

        def take(row, r):
            return lax.dynamic_index_in_dim(row, r, axis=0, keepdims=False)
        x_saved = jax.vmap(take)(stash, jnp.mod(b_c, K))
        aux_ct = jnp.where(b_valid, jnp.float32(aux_weight), 0.0)
        dp, dx = vbwd(stage_params, x_saved, b_c, stage_ids,
                      gshift.astype(microbatches.dtype), aux_ct)

        def acc(a, d):
            mask = b_valid.reshape((S,) + (1,) * (d.ndim - 1))
            return a + jnp.where(mask, d.astype(a.dtype), 0)
        acc_s = jax.tree_util.tree_map(acc, acc_s, dp)
        bmask = b_valid.reshape((S,) + (1,) * (dx.ndim - 1))
        gbuf_new = jnp.where(bmask, dx.astype(jnp.float32), 0)
        gbuf_new = constrain(gbuf_new, pp_axis, batch_axis)

        # stage 0's dx is the cotangent w.r.t. pipeline input b_mb[0]
        b0 = b_mb[0]
        b0_valid = (b0 >= 0) & (b0 < M)
        b0_c = jnp.clip(b0, 0, M - 1)
        cur_d = lax.dynamic_index_in_dim(dinp, b0_c, 0, keepdims=False)
        dinp = lax.dynamic_update_index_in_dim(
            dinp, jnp.where(b0_valid, dx[0].astype(jnp.float32), cur_d),
            b0_c, 0)

        return (out, gbuf_new, new_pending, stash, acc_s, acc_p, losses,
                dinp, aux_acc), None

    carry0 = (fbuf0, gbuf0, jnp.zeros_like(gbuf0[0]), stash0, acc_stage0,
              acc_post0, losses0, dinp0, jnp.zeros((), jnp.float32))
    carry, _ = lax.scan(tick, carry0, jnp.arange(T))
    _, _, _, _, acc_stage, acc_post, losses, dinp, aux_total = carry
    if has_aux:
        return losses, aux_total, acc_stage, acc_post, dinp
    return losses, acc_stage, acc_post, dinp
