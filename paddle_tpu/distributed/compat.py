"""Reference-surface compat for paddle.distributed's eager/PS-era API
(reference python/paddle/distributed/__init__.py __all__): process groups,
list-style alltoall, p2p send/recv, gloo rendezvous, and the
parameter-server dataset/entry config classes.

The SPMD design note: collectives here are *facades over mesh axes* — the
real communication is emitted by XLA from shardings (see collective.py).
The PS-specific pieces (InMemoryDataset pipelines, feature entries) are
config-surface only, consistent with SURVEY A11's parameter-server
out-of-scope ruling (documented in docs/MIGRATION.md).
"""
from __future__ import annotations

import enum
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.errors import enforce

__all__ = ["ParallelMode", "Group", "new_group", "get_group", "alltoall",
           "send", "recv", "wait", "gloo_init_parallel_env", "gloo_barrier",
           "gloo_release", "QueueDataset", "InMemoryDataset",
           "CountFilterEntry", "ShowClickEntry", "ProbabilityEntry"]


class ParallelMode(enum.IntEnum):
    """Reference fleet.base.topology.ParallelMode."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class Group:
    """Process-group facade (reference collective.Group): a set of ranks
    with an id; mesh-axis collectives accept ``group.axis`` when the
    group was built from a mesh axis."""

    def __init__(self, rank: int, nranks: int, id: int,
                 ranks: Sequence[int], axis: Optional[str] = None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = list(ranks)
        self.axis = axis

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return (f"Group(rank={self.rank}, nranks={self.nranks}, "
                f"id={self.id}, ranks={self.ranks})")


_groups: dict = {}


def new_group(ranks: Optional[List[int]] = None, backend: Optional[str] = None,
              axis: Optional[str] = None) -> Group:
    """Create a process group over ``ranks`` (reference
    collective.new_group).  Under the one-SPMD-program design membership
    is structural (mesh axes), so the group records identity; pass
    ``axis`` to bind it to a mesh axis for the collective facades."""
    me = jax.process_index()
    if ranks is None:
        ranks = list(range(jax.process_count()))
    gid = len(_groups) + 1
    rank = ranks.index(me) if me in ranks else -1
    g = Group(rank, len(ranks), gid, ranks, axis)
    _groups[gid] = g
    return g


def get_group(id: int = 0) -> Group:  # noqa: A002
    if id == 0 and 0 not in _groups:
        # the global/default group exists implicitly (reference semantics)
        _groups[0] = Group(jax.process_index(), jax.process_count(), 0,
                           list(range(jax.process_count())))
    enforce(id in _groups, f"no group with id {id}; create with new_group")
    return _groups[id]


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             use_calc_stream: bool = True):
    """List-style all_to_all (reference collective.alltoall).  Inside
    shard_map the split/concat rides lax.all_to_all over the group's
    axis; outside (single process) it is the identity exchange —
    world=1 semantics."""
    from .collective import all_to_all as _a2a
    stacked = jnp.stack([jnp.asarray(t) for t in in_tensor_list])
    axis = getattr(group, "axis", None) or (group if isinstance(group, str)
                                            else "ep")
    # inside shard_map the named axis is bound: run the real collective
    # (errors there must propagate); outside, world=1 identity exchange
    try:
        jax.lax.axis_index(axis)
        bound = True
    except NameError:
        bound = False
    if bound:
        out = _a2a(stacked, group=axis, split_axis=0, concat_axis=0)
        outs = [out[i] for i in range(out.shape[0])]
    else:
        outs = list(in_tensor_list)     # world=1: each rank keeps its slice
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(outs)
        return None
    return outs


_mailbox: dict = {}


def send(tensor, dst: int = 0, group=None, use_calc_stream: bool = True):
    """Eager p2p send (reference collective.send).  Single-process
    semantics: the tensor lands in an in-process mailbox keyed by dst —
    true cross-chip p2p is expressed with send_recv_permute (ppermute)
    inside the SPMD program (the pipeline does exactly this)."""
    enforce(jax.process_count() == 1,
            "multi-process eager send is not supported: use "
            "send_recv_permute inside the SPMD program (pipeline.py)")
    _mailbox.setdefault(dst, []).append(jnp.asarray(tensor))


def recv(tensor=None, src: int = 0, group=None, use_calc_stream: bool = True):
    """Eager p2p recv — pops the mailbox the matching send filled."""
    enforce(jax.process_count() == 1,
            "multi-process eager recv is not supported: use "
            "send_recv_permute inside the SPMD program (pipeline.py)")
    me = jax.process_index()
    box = _mailbox.get(me, [])
    enforce(len(box) > 0, "recv before any matching send")
    return box.pop(0)


def wait(tensor, group=None, use_calc_stream: bool = True):
    """Block until the tensor's device work is done (reference wait)."""
    jax.block_until_ready(tensor)
    return tensor


def gloo_init_parallel_env(rank_id: int, rank_num: int,
                           server_endpoint: str):
    """Reference gloo_init_parallel_env: CPU rendezvous for host-side
    barriers.  jax.distributed owns rendezvous here (launch/init_from_env);
    single-process initialization is a no-op."""
    enforce(rank_num == 1 or jax.process_count() == rank_num,
            "gloo rendezvous is owned by jax.distributed.initialize — "
            "bring the cluster up via paddle_tpu.distributed.launch")


def gloo_barrier():
    if jax.process_count() > 1:
        from .collective import barrier as _barrier
        _barrier()


def gloo_release():
    pass


# --- parameter-server dataset/entry configs (SURVEY A11: PS out of scope;
# these are the config surface so ported scripts can construct them) ------
class _PSEntry:
    def __init__(self, *args):
        self._args = args

    def __repr__(self):
        return f"{type(self).__name__}{self._args}"


class CountFilterEntry(_PSEntry):
    def __init__(self, count_filter: int = 0):
        enforce(count_filter >= 0, "count_filter must be >= 0")
        super().__init__(count_filter)


class ShowClickEntry(_PSEntry):
    def __init__(self, show_name: str, click_name: str):
        super().__init__(show_name, click_name)


class ProbabilityEntry(_PSEntry):
    def __init__(self, probability: float = 1.0):
        enforce(0 <= probability <= 1, "probability in [0, 1]")
        super().__init__(probability)


class _PSDatasetBase:
    """Config surface of the PS datasets (reference fleet InMemoryDataset/
    QueueDataset).  File-backed init/iteration works (delegates to plain
    host IO); the PS-distributed shuffle/fleet-send paths raise with the
    out-of-scope note."""

    def __init__(self):
        self._files: List[str] = []
        self._pipe_command = None
        self._batch_size = 1
        self._use_var = []

    def init(self, batch_size: int = 1, use_var=None, pipe_command=None,
             **kwargs):
        self._batch_size = batch_size
        self._use_var = use_var or []
        self._pipe_command = pipe_command

    def set_filelist(self, files: List[str]):
        self._files = list(files)

    def _ps_only(self, what: str):
        raise NotImplementedError(
            f"{what} is parameter-server infrastructure (reference fleet "
            f"PS mode) — out of scope for the TPU build (SURVEY A11; "
            f"docs/MIGRATION.md 'parameter server').")


class InMemoryDataset(_PSDatasetBase):
    def load_into_memory(self):
        self._records = []
        for f in self._files:
            with open(f) as fh:
                self._records.extend(fh.read().splitlines())

    def local_shuffle(self):
        import random
        random.shuffle(getattr(self, "_records", []))

    def global_shuffle(self, fleet=None, thread_num: int = 12):
        self._ps_only("global_shuffle")

    def release_memory(self):
        self._records = []

    def get_memory_data_size(self, fleet=None):
        return len(getattr(self, "_records", []))


class QueueDataset(_PSDatasetBase):
    def local_shuffle(self):
        self._ps_only("QueueDataset.local_shuffle")

    def global_shuffle(self, fleet=None, thread_num: int = 12):
        self._ps_only("global_shuffle")
