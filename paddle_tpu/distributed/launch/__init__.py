"""Distributed launcher (component D13).

Reference: ``python -m paddle.distributed.launch`` —
launch/controllers/collective.py spawns one process per device and wires
PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM (:89-92); multi-node rendezvous via
launch/controllers/master.py (etcd/http).

TPU-native model: ONE process per HOST (single-controller SPMD), not one
per device — the per-device process zoo is NCCL's requirement, not XLA's.
Responsibilities that remain real:

- ``init_from_env()``: called in the training process; wires
  ``jax.distributed.initialize`` (the TCPStore-analog rendezvous — on TPU
  pods the runtime discovers the topology itself and all arguments are
  optional) from the reference's PADDLE_* env names or JAX's own.
- ``python -m paddle_tpu.distributed.launch --nnodes N --master host:port
  train.py ...``: spawns N local host-processes with the env wired (the
  localhost simulation of a pod, ≙ the reference's test doctrine), or with
  ``--nnodes 1`` just execs the script.
"""
from __future__ import annotations

import os
import runpy
import subprocess
import sys
from typing import List, Optional

import jax

from ...framework.log import vlog

__all__ = ["init_from_env", "launch"]


def _env(name: str, *alts: str, default: Optional[str] = None
         ) -> Optional[str]:
    for n in (name,) + alts:
        v = os.environ.get(n)
        if v:
            return v
    return default


def _distributed_initialized() -> bool:
    """jax.distributed.is_initialized() with a fallback for jax<0.6,
    which only exposes the coordination client via internal state."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except (ImportError, AttributeError):
        return False


def init_from_env() -> None:
    """Bring up multi-host JAX from launcher env vars.

    Env (reference names first, JAX names accepted):
      PADDLE_MASTER / JAX_COORDINATOR_ADDRESS — host:port of process 0
      PADDLE_TRAINERS_NUM / JAX_NUM_PROCESSES — process count
      PADDLE_TRAINER_ID / JAX_PROCESS_ID — this process's id
    With none set on a TPU pod, jax.distributed.initialize() lets the
    runtime discover everything (the TPU-native path).
    """
    if _distributed_initialized():
        return  # idempotent: the launcher already initialized this process
    coord = _env("PADDLE_MASTER", "JAX_COORDINATOR_ADDRESS")
    nproc = _env("PADDLE_TRAINERS_NUM", "JAX_NUM_PROCESSES")
    pid = _env("PADDLE_TRAINER_ID", "JAX_PROCESS_ID")
    kwargs = {}
    if coord:
        kwargs["coordinator_address"] = coord
    if nproc:
        kwargs["num_processes"] = int(nproc)
    if pid:
        kwargs["process_id"] = int(pid)
    vlog(1, "launch.init_from_env: %s", kwargs or "(TPU pod auto-discovery)")
    jax.distributed.initialize(**kwargs)


def launch(argv: Optional[List[str]] = None) -> int:
    """Entry of ``python -m paddle_tpu.distributed.launch``."""
    import argparse
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a (multi-host) training script.")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_TRAINERS_NUM", "1")),
                   help="number of host processes (local simulation when "
                        "they all run here)")
    p.add_argument("--master", default=os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:37777"),
        help="host:port of the coordinator (process 0)")
    p.add_argument("--node_rank", type=int, default=None,
                   help="run ONLY this rank (real multi-host: one launcher "
                        "per host); default spawns all ranks locally")
    p.add_argument("--run_dir", default=os.environ.get("PTPU_RUN_DIR"),
                   help="supervised run directory: the launcher monitors "
                        "<run_dir>/heartbeats and logs/records run-state "
                        "transitions (healthy/degraded/lost-worker)")
    p.add_argument("--elastic", default=os.environ.get("PTPU_ELASTIC"),
                   metavar="MIN:MAX",
                   help="elastic fleet mode (ISSUE 9): reconcile the "
                        "worker set between MIN and MAX instead of dying "
                        "with the first lost worker — publishes a "
                        "generation-stamped <run_dir>/world.json, shrinks "
                        "the world when a worker dies, respawns it after "
                        "PTPU_ELASTIC_RESPAWN_SECS and re-expands; every "
                        "transition is an elastic.resize event in "
                        "launcher_report.json (requires --run_dir)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    def env_for(rank: int) -> dict:
        env = dict(os.environ)
        env["PADDLE_MASTER"] = args.master
        env["PADDLE_TRAINERS_NUM"] = str(args.nnodes)
        env["PADDLE_TRAINER_ID"] = str(rank)
        return env

    if args.elastic:
        return _reconcile_elastic(args)

    if args.nnodes <= 1:
        sys.argv = [args.script] + list(args.script_args)
        stop_live = (_live_aggregate(args.run_dir) if args.run_dir
                     else None)
        try:
            runpy.run_path(args.script, run_name="__main__")
        finally:
            if stop_live is not None:
                stop_live()
        if args.run_dir:
            _aggregate_metrics(args.run_dir)
        return 0

    if args.node_rank is not None:
        os.environ.update(env_for(args.node_rank))
        init_from_env()
        sys.argv = [args.script] + list(args.script_args)
        runpy.run_path(args.script, run_name="__main__")
        return 0

    # local simulation: spawn every rank here (≙ the reference's
    # localhost-multiprocess test doctrine, test_dist_base.py:782)
    procs = []
    for rank in range(args.nnodes):
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nnodes", str(args.nnodes), "--master", args.master,
               "--node_rank", str(rank), args.script] + list(args.script_args)
        procs.append(subprocess.Popen(cmd, env=env_for(rank)))
    stop_monitor = stop_live = None
    if args.run_dir:
        # one launcher report shared by the heartbeat monitor and the
        # live aggregator — both record onto the same event log
        from ...supervisor.report import SupervisorReport
        report = SupervisorReport(
            os.path.join(args.run_dir, "launcher_report.json"))
        stop_monitor = _monitor_heartbeats(args.run_dir, args.nnodes,
                                           report)
        stop_live = _live_aggregate(args.run_dir, report)
    rc = 0
    for rank, proc in enumerate(procs):
        code = proc.wait()
        vlog(1, "rank %d exited with %d", rank, code)
        rc = rc or code
    if stop_live is not None:
        stop_live()
    if stop_monitor is not None:
        stop_monitor()
    if args.run_dir:
        _aggregate_metrics(args.run_dir)
    return rc


def _parse_elastic(spec: str, nnodes: int):
    """``MIN:MAX`` (or ``MIN``) → (min, max); the launch width must sit
    inside the range."""
    lo, _, hi = str(spec).partition(":")
    min_n = int(lo)
    max_n = int(hi) if hi else max(nnodes, min_n)
    if not (1 <= min_n <= nnodes <= max_n):
        raise SystemExit(
            f"--elastic {spec!r}: need 1 <= MIN <= --nnodes <= MAX "
            f"(got min={min_n} nnodes={nnodes} max={max_n})")
    return min_n, max_n


def _reconcile_elastic(args) -> int:
    """The elastic fleet's control loop (ISSUE 9) — the launcher-side
    half of the reference ElasticManager's watch cycle.

    The launcher is the single writer of ``<run_dir>/world.json``.  Every
    membership change bumps the world generation, which (a) tells the
    surviving workers to rewind to ``last_good_step()`` and re-form at
    the new width, and (b) fences the departed worker: if its process is
    somehow still alive (zombie, GC pause), its checkpoint commits are
    refused against the newer generation.

    Workers are spawned as plain script processes (NOT through the
    ``--node_rank`` re-exec, which would initialize a fixed-size
    ``jax.distributed`` world — on a real TPU pod the runtime re-forms
    the SPMD world per relaunch; membership is the launcher's job).

    Env knobs: ``PTPU_ELASTIC_RESPAWN_SECS`` (delay before a lost rank
    is retried, default 5), ``PTPU_ELASTIC_MAX_RESPAWNS`` (retries per
    rank, default 2).
    """
    import time

    from ...supervisor.heartbeat import HeartbeatMonitor, default_interval
    from ...supervisor.report import SupervisorReport
    from ..elastic import write_world

    if not args.run_dir:
        raise SystemExit("--elastic requires --run_dir (the world "
                         "descriptor and heartbeats live there)")
    min_n, max_n = _parse_elastic(args.elastic, args.nnodes)
    respawn_secs = float(os.environ.get("PTPU_ELASTIC_RESPAWN_SECS", "5"))
    max_respawns = int(os.environ.get("PTPU_ELASTIC_MAX_RESPAWNS", "2"))
    run_dir = args.run_dir
    report = SupervisorReport(os.path.join(run_dir, "launcher_report.json"))

    generation = 0
    members = set(range(args.nnodes))
    write_world(run_dir, generation=generation, members=members,
                min_size=min_n, max_size=max_n, reason="launch")
    report.record("elastic.world", generation=generation,
                  members=sorted(members), min=min_n, max=max_n)

    # workers run the script directly (sys.path[0] becomes the script's
    # dir, not ours) — make sure they can import this very package
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))

    def spawn(rank: int) -> subprocess.Popen:
        env = dict(os.environ)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = str(len(members))
        env["PTPU_RUN_DIR"] = run_dir
        env["PTPU_ELASTIC"] = args.elastic
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get(
            "PYTHONPATH", "")
        cmd = [sys.executable, args.script] + [
            a for a in args.script_args if a != "--"]
        vlog(1, "elastic: spawning rank %d: %s", rank, cmd)
        return subprocess.Popen(cmd, env=env)

    def publish(reason: str, direction: str, changed):
        nonlocal generation
        generation += 1
        write_world(run_dir, generation=generation, members=members,
                    min_size=min_n, max_size=max_n, reason=reason)
        monitor.set_expected(set(members))
        report.record("elastic.resize", generation=generation,
                      world_size=len(members), members=sorted(members),
                      direction=direction, changed=sorted(changed),
                      reason=reason)
        try:
            from ...observability import get_registry
            reg = get_registry()
            reg.counter("elastic.resizes").inc()
            reg.gauge("elastic.generation").set(generation)
            reg.gauge("elastic.world_size").set(len(members))
        except Exception as e:
            vlog(1, "elastic: resize metrics failed: %r", e)
        vlog(0, "elastic: world generation %d — %s %s (%d member(s): %s)",
             generation, direction, sorted(changed), len(members),
             sorted(members))

    monitor = HeartbeatMonitor(run_dir, expected=set(members),
                               report=report)
    stop_live = _live_aggregate(run_dir, report)
    procs = {rank: spawn(rank) for rank in sorted(members)}
    respawn_at: dict = {}      # rank -> monotonic deadline
    respawns: dict = {}        # rank -> attempts used
    finished_clean = set()
    failed = False
    poll_every = min(0.2, default_interval() / 2.0)
    last_hb_poll = 0.0
    try:
        while procs or respawn_at:
            now = time.monotonic()
            if now - last_hb_poll >= default_interval() / 2.0:
                last_hb_poll = now
                monitor.poll()
            for rank, proc in list(procs.items()):
                rc = proc.poll()
                if rc is None:
                    continue
                del procs[rank]
                if rc == 0:
                    finished_clean.add(rank)
                    vlog(1, "elastic: rank %d finished clean", rank)
                    continue
                if rank not in members:
                    vlog(1, "elastic: retired rank %d exited %d", rank, rc)
                    continue
                # lost worker — shrink the world (or fail below MIN)
                members.discard(rank)
                report.record("elastic.worker_lost", rank=rank,
                              returncode=rc)
                if len(members) < min_n:
                    report.record("elastic.failed", reason="below-min",
                                  world_size=len(members), min=min_n)
                    vlog(0, "elastic: %d member(s) left < min %d — "
                         "failing the run", len(members), min_n)
                    failed = True
                    for p in procs.values():
                        p.terminate()
                    return 1
                publish(f"lost-worker:{rank}", "shrink", {rank})
                if respawns.get(rank, 0) < max_respawns \
                        and len(members) < max_n:
                    respawn_at[rank] = now + respawn_secs
            # a finished world means the run is over: members that are
            # neither running nor scheduled for respawn all exited clean
            live_members = [r for r in members
                            if r in procs or r in respawn_at]
            if not live_members and members <= finished_clean:
                respawn_at.clear()
                break
            for rank, deadline in list(respawn_at.items()):
                if time.monotonic() < deadline:
                    continue
                del respawn_at[rank]
                respawns[rank] = respawns.get(rank, 0) + 1
                members.add(rank)
                publish(f"respawn:{rank}", "grow", {rank})
                procs[rank] = spawn(rank)
            time.sleep(poll_every)
    finally:
        for rank, proc in procs.items():
            if proc.poll() is None:   # retired stragglers: the run is over
                vlog(1, "elastic: terminating leftover rank %d", rank)
                proc.terminate()
        if stop_live is not None:
            stop_live()
        monitor.poll()
        rc_final = 1 if failed or not (members <= finished_clean) else 0
        report.record("elastic.done", returncode=rc_final,
                      generation=generation, members=sorted(members),
                      finished=sorted(finished_clean),
                      respawns=dict(respawns))
        _aggregate_metrics(run_dir)
    return rc_final


def _aggregate_metrics(run_dir: str) -> None:
    """Merge the workers' ``<run_dir>/metrics/worker-*.jsonl`` telemetry
    streams into ``metrics/summary.json`` (ISSUE 3) — the launcher is the
    one process guaranteed to outlive every worker, so cross-worker
    aggregation happens here."""
    from ...observability import aggregate_run
    try:
        summary = aggregate_run(run_dir)
    except OSError as e:
        vlog(0, "launch: metrics aggregation under %s failed: %s",
             run_dir, e)
        return
    if summary is not None:
        vlog(0, "launch: merged %d worker metric streams (%d records) → "
             "%s/metrics/summary.json", len(summary["workers"]),
             summary["records"], run_dir)
        _run_doctor(run_dir)


def _run_doctor(run_dir: str) -> None:
    """Post-run diagnosis (ISSUE 4): rank retrace storms / HBM pressure /
    stragglers / data starvation into ``<run_dir>/diagnosis.json`` and
    log the verdicts — the launcher outlives every worker, so this is
    where the whole-run view exists."""
    from ...observability import doctor as doctor_mod
    try:
        diagnosis = doctor_mod.diagnose(run_dir)
    except Exception as e:  # diagnosis is best-effort, the run is done
        vlog(0, "launch: run doctor failed under %s: %r", run_dir, e)
        return
    if diagnosis is None:
        return
    if diagnosis["healthy"]:
        vlog(0, "launch: run doctor — no findings (healthy run)")
        return
    top = diagnosis["findings"][0]
    vlog(0, "launch: run doctor — %d finding(s) → %s/diagnosis.json; "
         "top: [%d] %s: %s", len(diagnosis["findings"]), run_dir,
         top["severity"], top["kind"], top["title"])


def _live_aggregate(run_dir: str, report=None):
    """In-flight cross-worker aggregation (ISSUE 5): a background
    :class:`~paddle_tpu.observability.monitor.LiveAggregator` tail-reads
    the workers' still-growing JSONL streams every
    ``PTPU_MONITOR_INTERVAL`` seconds, re-runs the doctor's rules on the
    window, keeps ``<run_dir>/live_status.json`` rolling, and records
    ``monitor.alert`` events in ``launcher_report.json`` the moment a
    verdict first fires — the launcher names a retrace storm or a
    straggler while the run still burns chips, not at teardown.
    Returns a callable that stops the thread (with one final poll)."""
    from ...observability.monitor import LiveAggregator

    if report is None:
        from ...supervisor.report import SupervisorReport
        report = SupervisorReport(os.path.join(run_dir,
                                               "launcher_report.json"))
    aggregator = LiveAggregator(run_dir, report=report).start()

    def stop_fn():
        aggregator.stop()
        if aggregator.alerts:
            vlog(0, "launch: live monitor raised %d alert(s); first: %s",
                 len(aggregator.alerts), aggregator.alerts[0]["title"])

    return stop_fn


def _monitor_heartbeats(run_dir: str, nnodes: int, report=None):
    """Launcher-side health view (ISSUE 2): poll the workers' heartbeat
    files and record every healthy/degraded/lost-worker transition in
    ``<run_dir>/launcher_report.json`` — the acting end of the heartbeat
    subsystem (the relaunch decision itself belongs to the cluster
    scheduler, ≙ the reference ElasticManager's watch loop).  Returns a
    callable that stops the monitor and does one final poll."""
    import threading

    from ...supervisor.heartbeat import HeartbeatMonitor, default_interval
    from ...supervisor.report import SupervisorReport

    if report is None:
        report = SupervisorReport(os.path.join(run_dir,
                                               "launcher_report.json"))
    monitor = HeartbeatMonitor(run_dir, expected=nnodes, report=report)
    stop = threading.Event()

    def poll_loop():
        while not stop.wait(default_interval()):
            monitor.poll()

    t = threading.Thread(target=poll_loop, name="ptpu-launch-monitor",
                         daemon=True)
    t.start()

    def stop_fn():
        stop.set()
        t.join(timeout=2.0)
        monitor.poll()

    return stop_fn
