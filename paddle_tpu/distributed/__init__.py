"""paddle_tpu.distributed — the distributed layer (SURVEY.md §1 L8, §2 D1-D16).

What the reference builds with NCCL rings, process groups, and program
rewrites, this package expresses as ONE SPMD program over a named
``jax.sharding.Mesh``:

- topology.py   — mesh axes ≙ CommunicateTopology / HybridCommunicateGroup
- collective.py — lax collectives ≙ operators/collective/* + ProcessGroup
- parallel.py   — DP ≙ DataParallel + Reducer (batch sharding, XLA allreduce)
- mp_layers.py  — TP ≙ fleet.meta_parallel.mp_layers (GSPMD annotations)
- mp_ops.py     — vocab-parallel CE/embedding ≙ c_softmax_with_cross_entropy
- random.py     — TP RNG ≙ RNGStatesTracker
- fleet/        — facade ≙ fleet_base.py + DistributedStrategy (+ recompute)
- pipeline.py   — PP ≙ PipelineLayer + 1F1B (shard_map + ppermute)
- sharding.py   — ZeRO ≙ sharding stage 1/2/3 (opt-state PartitionSpecs)
- moe.py        — EP ≙ global_scatter/gather all-to-all dispatch
- checkpoint.py — sharded save/load ≙ auto_parallel dist_saver/converter
- comm/         — compressed collectives + ZeRO-1 weight-update sharding
                  (ISSUE 8: CommConfig, int8/bf16 gradient sync with
                  error feedback, ShardedOptimizer)
"""
from __future__ import annotations

from . import fleet  # noqa: F401
from . import comm  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import ProcessMesh, shard_tensor, shard_op  # noqa: F401
from .collective import (ReduceOp, all_gather, all_reduce,  # noqa: F401
                         all_reduce_quantized, all_to_all, barrier,
                         broadcast, p2p_push, reduce, reduce_scatter,
                         scatter, send_recv_permute, split)
from .mp_layers import (ColumnParallelLinear, RowParallelLinear,  # noqa: F401
                        VocabParallelEmbedding, shard_constraint,
                        param_sharding, variables_sharding)
from .checkpoint import (save_sharded, load_sharded,  # noqa: F401
                         verify_sharded, AsyncSaveHandle,
                         CheckpointCorruption, DigestMismatch,
                         read_integrity)
from .fingerprint import (TreeFingerprint, Fingerprint,  # noqa: F401
                          digest_tree_host, tree_digest)
from .moe import (MoELayer, ExpertFFN, global_scatter,  # noqa: F401
                  global_gather, limit_by_capacity, switch_gating,
                  gshard_gating, collect_aux_losses)
from .mp_ops import (parallel_cross_entropy, parallel_log_softmax,  # noqa: F401
                     vocab_parallel_embedding)
from .parallel import (DataParallel, ParallelEnv, get_rank,  # noqa: F401
                       get_world_size, init_parallel_env, shard_batch,
                       device_put_sharded_variables)
from .spawn import spawn  # noqa: F401
from .random import (RNGStatesTracker, get_rng_state_tracker,  # noqa: F401
                     model_parallel_random_seed)
from .topology import (CommunicateTopology, HybridCommunicateGroup,  # noqa: F401
                       get_hybrid_communicate_group, get_mesh,
                       set_hybrid_communicate_group)

__all__ = [
    "fleet", "comm", "ReduceOp", "all_gather", "all_reduce",
    "all_reduce_quantized", "all_to_all", "barrier", "spawn",
    "broadcast", "p2p_push", "reduce", "reduce_scatter", "scatter",
    "send_recv_permute", "split", "ColumnParallelLinear", "RowParallelLinear",
    "VocabParallelEmbedding", "shard_constraint", "param_sharding",
    "variables_sharding", "save_sharded", "load_sharded", "verify_sharded",
    "AsyncSaveHandle", "CheckpointCorruption", "DigestMismatch",
    "read_integrity", "TreeFingerprint", "Fingerprint",
    "digest_tree_host", "tree_digest",
    "MoELayer", "ExpertFFN", "global_scatter",
    "global_gather", "limit_by_capacity", "switch_gating", "gshard_gating",
    "collect_aux_losses", "parallel_cross_entropy", "parallel_log_softmax",
    "vocab_parallel_embedding", "DataParallel", "ParallelEnv", "get_rank",
    "get_world_size", "init_parallel_env", "shard_batch",
    "device_put_sharded_variables", "RNGStatesTracker",
    "get_rng_state_tracker", "model_parallel_random_seed",
    "CommunicateTopology", "HybridCommunicateGroup",
    "get_hybrid_communicate_group", "get_mesh",
    "set_hybrid_communicate_group",
]

from . import launch  # noqa: F401,E402  (reference paddle.distributed.launch)
from . import utils  # noqa: F401,E402  (launcher plumbing compat)
from .compat import (ParallelMode, Group, new_group, get_group,  # noqa: F401,E402
                     alltoall, send, recv, wait, gloo_init_parallel_env,
                     gloo_barrier, gloo_release, QueueDataset,
                     InMemoryDataset, CountFilterEntry, ShowClickEntry,
                     ProbabilityEntry)

__all__ += ["launch", "utils", "ParallelMode", "Group", "new_group", "get_group",
            "alltoall", "send", "recv", "wait", "gloo_init_parallel_env",
            "gloo_barrier", "gloo_release", "QueueDataset",
            "InMemoryDataset", "CountFilterEntry", "ShowClickEntry",
            "ProbabilityEntry"]
