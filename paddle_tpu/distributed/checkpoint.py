"""Sharded checkpoint with resharding-on-load.

Reference capability being matched:
- per-rank shard save/load for hybrid-parallel training
  (hybrid_parallel_pp_save_load.py test family; each rank persists only its
  own parameter/optimizer shards);
- cross-config conversion — load a checkpoint written under one parallel
  layout into a different one
  (auto_parallel/dist_saver.py + converter.py, auto_parallel_autoconvert.py).

TPU-native design (tensorstore/orbax-style, self-contained):
- every leaf is written as one file PER ADDRESSABLE SHARD (only
  replica_id==0 shards, so replicated axes are written once; on multi-host
  each host writes exactly its own shards — no gather to host 0, which is
  what breaks the pickle path at 1.3B+);
- a JSON manifest records the tree structure, dtypes, global shapes and
  every shard's index window;
- load builds each array with ``jax.make_array_from_callback`` against the
  TARGET sharding: each device's window is stitched from whichever saved
  shard files overlap it (numpy memmap reads touch only the needed bytes).
  The saved and target layouts are fully decoupled — dp=4,mp=2 checkpoints
  load into dp=2,mp=4 (or single-device) without a conversion pass;
- ``save_sharded(..., use_async=True)`` returns immediately and flushes
  device-to-host copies + file writes on a background thread (async
  checkpointing for the elastic/preemption path).

Resilience (manifest **v2**, ISSUE 1): every shard entry additionally
records the CRC32 and byte size of its ``.npy`` file; all durable writes go
through the retry-wrapped ``utils.fsio`` seam (fsync'd, fault-injectable);
``load_sharded`` verifies existence/size/CRC of every referenced shard
before materializing anything and raises :class:`CheckpointCorruption`
(``strict=False`` demotes that to a warning).  v1 manifests (no checksums)
still load — the verification pass is skipped with a warning.
"""
from __future__ import annotations

import io as _io
import json
import os
import threading
import warnings
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.errors import enforce
from ..framework.log import vlog
from ..utils import fsio
from ..utils.retry import RetryPolicy, retry_call

__all__ = ["save_sharded", "load_sharded", "verify_sharded",
           "AsyncSaveHandle", "CheckpointCorruption", "DigestMismatch",
           "read_integrity"]

_MANIFEST = "manifest.json"          # single-host name (kept for reading)
MANIFEST_VERSION = 2                 # v2 = per-shard crc32 + byte sizes

#: Retry schedule for checkpoint file I/O (module-level so the fault
#: harness / tests can swap in a sleepless policy).
IO_RETRY_POLICY = RetryPolicy(max_attempts=4, base_delay=0.05)


class CheckpointCorruption(OSError):
    """A checkpoint failed integrity verification (missing shard file,
    size mismatch, or CRC32 mismatch).  Deliberately NOT retryable: the
    bytes on disk are wrong and will stay wrong."""


class DigestMismatch(CheckpointCorruption):
    """The restored tree's fingerprint differs from the digest stamped
    into the manifest at save time (ISSUE 11).  CRC32 covers the bytes
    each shard file held when it was written; the tree digest covers the
    whole save→reshard→restore round trip of the LIVE state — a state
    corrupted between hashing and serialization passes every CRC and
    only this check catches it."""


def _count(name: str) -> None:
    """Best-effort observability counter (checkpoint layer must not
    depend hard on the registry)."""
    try:
        from ..observability import get_registry
        get_registry().counter(name).inc()
    except Exception:
        pass  # noqa: swallow


def _manifest_name() -> str:
    # one manifest per process: multi-host saves must not overwrite each
    # other's shard lists; load merges every manifest-p*.json it finds
    return f"manifest-p{jax.process_index()}.json"


def _flatten(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        out.append(("/".join(parts), leaf))
    return out


def _index_to_json(index, shape) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _leaf_dir(path: str, name: str) -> str:
    return os.path.join(path, name.replace("/", "__"))


class AsyncSaveHandle:
    """Returned by ``save_sharded(use_async=True)``; ``wait()`` blocks until
    every shard is durably on disk (join before preemption exit)."""

    def __init__(self, thread: threading.Thread, errors: list):
        self._thread = thread
        self._errors = errors

    def wait(self) -> None:
        self._thread.join()
        if self._errors:
            raise self._errors[0]

    def done(self) -> bool:
        return not self._thread.is_alive()


def save_sharded(state, path: str, *, use_async: bool = False,
                 integrity: Optional[Dict[str, Any]] = None
                 ) -> Optional[AsyncSaveHandle]:
    """Write ``state`` (pytree of jax/numpy arrays) as a sharded checkpoint.

    Each process writes only its addressable replica-0 shards, so the
    aggregate across hosts is exactly one copy of every element.

    Durability contract: every shard file and the manifest are written via
    the fsync'd + retry-wrapped ``fsio`` seam, the manifest is written
    LAST, and each shard's CRC32/size is recorded in it — so a reader that
    sees a manifest sees (and can verify) every byte it references.  The
    device→host copy happens synchronously before this returns even with
    ``use_async=True``; only serialization + file I/O runs on the thread.

    ``integrity`` (ISSUE 11): a JSON-ready fingerprint stamp — typically
    ``Fingerprint.meta()`` plus the ``exclude`` patterns it was computed
    with — recorded verbatim in the manifest.  ``load_sharded`` re-hashes
    the restored tree against it, closing the live-state gap CRC32
    leaves open.
    """
    os.makedirs(path, exist_ok=True)
    leaves = _flatten(state)
    # world count recorded so load merges EXACTLY p0..p{world-1} and never
    # picks up stale manifests from an earlier save with more processes
    manifest: Dict[str, Any] = {"version": MANIFEST_VERSION,
                                "world": jax.process_count(), "leaves": {}}
    if integrity is not None:
        manifest["integrity"] = dict(integrity)
    work: List[Tuple[str, List[Dict[str, Any]]]] = []
    proc = jax.process_index()

    for name, leaf in leaves:
        arr = jnp.asarray(leaf) if not isinstance(leaf, jax.Array) else leaf
        entry: Dict[str, Any] = {
            "shape": list(arr.shape),
            "dtype": str(np.dtype(arr.dtype)) if arr.dtype != jnp.bfloat16
                     else "bfloat16",
            "shards": [],
        }
        shard_specs = []
        for i, shard in enumerate(arr.addressable_shards):
            if shard.replica_id != 0:
                continue
            # process index in the name: hosts share the directory and must
            # never collide on shard files
            fname = f"shard-p{proc}-{i}.npy"
            idx = _index_to_json(shard.index, arr.shape)
            meta = {"file": fname, "index": idx}
            entry["shards"].append(meta)
            # device→host copy happens NOW, synchronously: the caller may
            # donate these buffers to the next jitted step the moment we
            # return, so only file I/O may be deferred to the thread
            data = np.asarray(shard.data)
            if data.dtype == jnp.bfloat16:
                data = data.view(np.uint16)  # npy has no bf16: raw bits
            shard_specs.append({"data": data, "meta": meta})
        manifest["leaves"][name] = entry
        work.append((name, shard_specs))

    def _write():
        for name, shard_specs in work:
            d = _leaf_dir(path, name)
            os.makedirs(d, exist_ok=True)
            for spec in shard_specs:
                buf = _io.BytesIO()
                np.save(buf, spec["data"])
                payload = buf.getvalue()
                # checksum the exact on-disk bytes (header included) so
                # verification is a pure file read, no npy parsing
                spec["meta"]["crc32"] = zlib.crc32(payload) & 0xFFFFFFFF
                spec["meta"]["bytes"] = len(payload)
                retry_call(fsio.write_bytes,
                           os.path.join(d, spec["meta"]["file"]), payload,
                           policy=IO_RETRY_POLICY)
            fsio.fsync_dir(d)
        retry_call(fsio.write_bytes, os.path.join(path, _manifest_name()),
                   json.dumps(manifest, indent=1).encode("utf-8"),
                   policy=IO_RETRY_POLICY)
        fsio.fsync_dir(path)

    if not use_async:
        _write()
        return None
    errors: list = []

    def _run():
        try:
            _write()
        except Exception as e:  # surfaced by handle.wait()
            errors.append(e)

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return AsyncSaveHandle(t, errors)


def _read_manifests(path: str) -> Tuple[int, Dict[str, Any],
                                        Optional[Dict[str, Any]]]:
    """Merge every process's manifest; returns (version, leaves,
    integrity) — ``integrity`` is the head (p0) manifest's fingerprint
    stamp, or None for checkpoints saved without one."""
    p0 = os.path.join(path, "manifest-p0.json")
    if not os.path.exists(p0) and os.path.exists(
            os.path.join(path, _MANIFEST)):
        p0 = os.path.join(path, _MANIFEST)  # legacy single-host name
    enforce(os.path.exists(p0), f"no manifest found under {path!r}")

    def _load_json(mpath):
        return json.loads(retry_call(fsio.read_bytes, mpath,
                                     policy=IO_RETRY_POLICY))

    try:
        head = _load_json(p0)
    except json.JSONDecodeError as e:
        # a truncated/garbled manifest is corruption, not a usage error —
        # restore_or quarantines on this
        raise CheckpointCorruption(f"manifest {p0} unreadable: {e}") from e
    version = int(head.get("version", 1))
    world = int(head.get("world", 1))
    names = [p0] + [os.path.join(path, f"manifest-p{i}.json")
                    for i in range(1, world)]
    missing_m = [n for n in names if not os.path.exists(n)]
    if missing_m:
        raise CheckpointCorruption(
            f"checkpoint written by {world} processes but manifests missing:"
            f" {missing_m}")
    leaves: Dict[str, Any] = {}
    for mpath in names:  # union of exactly this save's shard lists
        try:
            part = _load_json(mpath)["leaves"]
        except json.JSONDecodeError as e:
            raise CheckpointCorruption(
                f"manifest {mpath} unreadable: {e}") from e
        for lname, entry in part.items():
            if lname in leaves:
                leaves[lname]["shards"].extend(entry["shards"])
            else:
                leaves[lname] = entry
    return version, leaves, head.get("integrity")


def read_integrity(path: str) -> Optional[Dict[str, Any]]:
    """The fingerprint stamp a checkpoint's head manifest carries (or
    None) — ``{"algo", "tree", "exclude", "excluded", "leaves"}``."""
    return _read_manifests(path)[2]


def verify_sharded(path: str) -> List[str]:
    """Integrity-check every shard file a checkpoint's manifests reference.

    Returns a list of problem strings (empty = clean).  v2 manifests get
    existence + byte-size + CRC32 checks; v1 manifests (no checksums) get
    existence checks only.
    """
    version, leaves, _integrity = _read_manifests(path)
    problems: List[str] = []
    for name, entry in leaves.items():
        d = _leaf_dir(path, name)
        for shard in entry["shards"]:
            fpath = os.path.join(d, shard["file"])
            rel = os.path.join(os.path.basename(d), shard["file"])
            if not os.path.exists(fpath):
                problems.append(f"{rel}: missing")
                continue
            if "bytes" in shard:
                size = os.path.getsize(fpath)
                if size != int(shard["bytes"]):
                    problems.append(
                        f"{rel}: size {size} != recorded {shard['bytes']}")
                    continue  # CRC would fail too; report the root cause
            if "crc32" in shard:
                crc = zlib.crc32(retry_call(
                    fsio.read_bytes, fpath,
                    policy=IO_RETRY_POLICY)) & 0xFFFFFFFF
                if crc != int(shard["crc32"]):
                    problems.append(
                        f"{rel}: crc32 {crc:#010x} != recorded "
                        f"{int(shard['crc32']):#010x}")
    return problems


def _read_window(leaf_dir: str, entry: Dict[str, Any], window) -> np.ndarray:
    """Assemble one index window from the saved shard files (memmap reads
    touch only the overlapping byte ranges) — the resharding core."""
    shape = entry["shape"]
    dtype = entry["dtype"]
    np_dtype = np.uint16 if dtype == "bfloat16" else np.dtype(dtype)
    win = []
    for sl, dim in zip(window, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        win.append((start, stop))
    out = np.empty([b - a for a, b in win], np_dtype)
    filled = 0
    for shard in entry["shards"]:
        idx = shard["index"]
        # overlap of the saved shard window with the requested window
        inter = [(max(a, c), min(b, d)) for (a, b), (c, d) in zip(win, idx)]
        if any(a >= b for a, b in inter):
            continue
        mm = np.load(os.path.join(leaf_dir, shard["file"]), mmap_mode="r")
        src = tuple(slice(a - c, b - c)
                    for (a, b), (c, d) in zip(inter, idx))
        dst = tuple(slice(a - wa, b - wa)
                    for (a, b), (wa, _) in zip(inter, win))
        out[dst] = mm[src]
        filled += int(np.prod([b - a for a, b in inter]))
    enforce(filled == out.size,
            f"checkpoint window {win} only {filled}/{out.size} covered — "
            f"missing shard files?")
    if dtype == "bfloat16":
        return out.view(jnp.bfloat16)
    return out


def _verify_digest(path: str, restored, meta: Optional[Dict[str, Any]],
                   strict: bool) -> None:
    """Re-hash a restored tree against the manifest's fingerprint stamp
    (ISSUE 11).  Raises :class:`DigestMismatch` (a corruption — the
    restore fallback chain quarantines on it); ``strict=False`` demotes
    to a warning.  Width-change restores verify too: the digest is
    invariant under ZeRO-1 trailing-zero relayout and the stamp's
    ``exclude`` patterns skip the rank-private leaves a resize resets.
    """
    if not meta:
        return
    from .fingerprint import DEFAULT_EXCLUDE, DIGEST_ALGO, \
        digest_tree_host
    if meta.get("algo") != DIGEST_ALGO:
        warnings.warn(
            f"checkpoint {path!r} stamped with unknown digest algo "
            f"{meta.get('algo')!r}; fingerprint verification skipped",
            RuntimeWarning, stacklevel=3)
        return
    if jax.process_count() > 1:
        # per-host windows can't be rehashed against a global digest
        # without a gather; multi-host re-verification is the integrity
        # guard's cross-worker compare, not the loader's
        return
    got = digest_tree_host(
        restored, tuple(meta.get("exclude", DEFAULT_EXCLUDE)))
    want = str(meta.get("tree"))
    if got.hex() == want:
        _count("integrity.ckpt_verified")
        vlog(1, "checkpoint: %s tree digest %s verified", path, want)
        return
    _count("integrity.ckpt_digest_mismatch")
    stamped = meta.get("leaves") or {}
    mine = got.leaf_digests()
    bad = sorted(n for n, h in stamped.items()
                 if n in mine and f"{mine[n]:08x}" != h)
    msg = (f"checkpoint {path!r} restored tree digest {got.hex()} != "
           f"stamped {want}"
           + (f" (leaves differing: {bad[:5]}"
              + (" …" if len(bad) > 5 else "") + ")" if bad else "")
           + " — state corrupted between fingerprint and serialization,"
           " or mangled by the restore/reshard path (shard CRCs cover"
           " bytes on disk, not this)")
    if strict:
        raise DigestMismatch(msg)
    warnings.warn(msg + " — loading anyway (strict=False)",
                  RuntimeWarning, stacklevel=3)
    vlog(0, "checkpoint: %s", msg)


def load_sharded(path: str, template=None, *, strict: bool = True,
                 mismatch=None, verify_digest: bool = True):
    """Load a sharded checkpoint.

    ``template``: a pytree matching the saved structure whose leaves carry
    the TARGET placement — jax.Arrays, ShapeDtypeStructs with ``.sharding``,
    or NamedShardings.  Each leaf is materialized directly into that
    sharding, reading only the slices every device needs (resharding-on-load;
    ≙ auto_parallel converter).  With ``template=None`` returns a nested
    dict of host numpy arrays (names split on '/').

    ``mismatch``: optional ``fn(name, saved_np, template_leaf) -> array``
    called for leaves whose saved GLOBAL shape differs from the
    template's — the elastic-resize relayout hook (ISSUE 9): a ZeRO-1
    flat master padded for one dp width re-packs to another, and
    rank-private error-feedback state resets.  The full saved array is
    assembled on host and handed over; the returned leaf is used as-is.
    Without it a shape mismatch is an error, as before.

    Integrity: with a v2 manifest every referenced shard file is verified
    (existence, byte size, CRC32) BEFORE any array is materialized; a
    failure raises :class:`CheckpointCorruption`.  ``strict=False`` demotes
    verification failures to warnings and loads whatever it can (forensics
    / partial-recovery mode).  v1 manifests skip the checksum pass with a
    warning — pre-checksum checkpoints stay loadable.

    Fingerprint round-trip (ISSUE 11): when the manifest carries an
    ``integrity`` stamp (``save_sharded(integrity=...)``), the RESTORED
    tree is re-hashed and compared — :class:`DigestMismatch` on failure
    (``verify_digest=False`` opts out; ``strict=False`` demotes).
    """
    version, leaves, integrity = _read_manifests(path)
    if not verify_digest:
        integrity = None
    if version < 2:
        warnings.warn(
            f"checkpoint {path!r} has a v{version} manifest (no checksums); "
            "integrity verification skipped", RuntimeWarning, stacklevel=2)
    else:
        problems = verify_sharded(path)
        if problems:
            msg = (f"checkpoint {path!r} failed verification "
                   f"({len(problems)} problem(s)): "
                   + "; ".join(problems[:5])
                   + (" …" if len(problems) > 5 else ""))
            if strict:
                raise CheckpointCorruption(msg)
            warnings.warn(msg + " — loading anyway (strict=False)",
                          RuntimeWarning, stacklevel=2)
            vlog(0, "checkpoint: %s", msg)

    if template is None:
        out: Dict[str, Any] = {}
        for name, entry in leaves.items():
            full = _read_window(
                _leaf_dir(path, name), entry,
                tuple(slice(0, d) for d in entry["shape"]))
            node = out
            parts = name.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = full
        _verify_digest(path, out, integrity, strict)
        return out

    tpl_leaves = _flatten(template)
    tpl_names = {n for n, _ in tpl_leaves}
    missing = tpl_names - set(leaves)
    enforce(not missing, f"checkpoint missing leaves: {sorted(missing)[:5]}")

    restored = {}
    for name, tpl in tpl_leaves:
        entry = leaves[name]
        d = _leaf_dir(path, name)
        shape = tuple(entry["shape"])
        dtype = (jnp.bfloat16 if entry["dtype"] == "bfloat16"
                 else np.dtype(entry["dtype"]))
        sharding = getattr(tpl, "sharding", None)
        if sharding is None and hasattr(tpl, "spec"):
            sharding = tpl  # a NamedSharding itself
        if isinstance(sharding, jax.sharding.SingleDeviceSharding):
            # leave single-device leaves uncommitted so they can mix with
            # mesh-sharded arrays in one jitted computation
            sharding = None
        tshape = tuple(getattr(tpl, "shape", shape))
        if tshape != shape and mismatch is not None:
            full = _read_window(d, entry,
                                tuple(slice(0, s) for s in shape))
            restored[name] = mismatch(name, full, tpl)
            continue
        enforce(tshape == shape,
                f"{name}: template shape {tshape} != saved {shape}")
        if sharding is None:
            restored[name] = jnp.asarray(
                _read_window(d, entry, tuple(slice(0, s) for s in shape)))
        else:
            restored[name] = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, d=d, e=entry: _read_window(d, e, idx))
    # rebuild the template's tree structure with restored leaves
    flat_tpl, treedef = jax.tree_util.tree_flatten_with_path(template)
    ordered = []
    for pathkeys, _ in flat_tpl:
        parts = []
        for k in pathkeys:
            parts.append(str(k.key) if hasattr(k, "key")
                         else str(getattr(k, "idx", k)))
        ordered.append(restored["/".join(parts)])
    out = jax.tree_util.tree_unflatten(treedef, ordered)
    _verify_digest(path, out, integrity, strict)
    return out
