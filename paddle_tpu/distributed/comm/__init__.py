"""paddle_tpu.distributed.comm — the communication subsystem (ISSUE 8).

PRs 1–7 made training survivable, observable, and per-chip fast; this
package owns the remaining MFU lever on the dp axis: how gradients move
and where optimizer state lives.  Two cooperating pieces:

1. **Compressed collectives** (`collectives.py`): drop-in
   ``all_reduce``/``reduce_scatter``/``sync_gradients`` variants behind
   the same mesh-axis semantics as ``distributed.collective``, selectable
   per-call (or process-wide through fleet's
   ``DistributedStrategy.comm_configs``) via :class:`CommConfig`:

   - ``dtype="float32"`` — exact lax path (the default; zero risk),
   - ``dtype="bfloat16"`` — cast-on-the-wire, 2× fewer bytes,
   - ``dtype="int8"`` — EQuARX-style block-wise absmax quantization with
     a two-phase (all-to-all reduce-scatter + all-gather) schedule so the
     wire really carries int8, ~4× fewer bytes,
   - optional **error feedback** (``error_feedback=True``): each worker
     keeps the part of its gradient the quantizer dropped and re-injects
     it next step, which is what lets int8 gradient sync track the fp32
     loss trajectory.

2. **ZeRO-1 weight-update sharding** (`zero.py`):
   :class:`ShardedOptimizer` wraps any elementwise optimizer (Adam/
   AdamW/SGD/Momentum/...) with the reference
   ``DygraphShardingOptimizer`` semantics, TPU-native: reduce-scatter
   grads along the dp/sharding axis, run the update on each replica's
   1/dp shard of a padded flat fp32 master (+ slots), all-gather the
   updated params.  Works both inside ``shard_map`` (explicit
   collectives) and under plain ``jit``/GSPMD (sharding constraints —
   the *Automatic Cross-Replica Sharding of Weight Update* form, where
   XLA derives the same reduce-scatter + sharded update + all-gather).

Telemetry: every entry point reports through the PR 3 registry —
``collective.<op>.ms`` latency histograms plus ``comm.bytes`` (what the
exact fp32 schedule would put on the wire), ``comm.compressed_bytes``
(what this call ships) and the ``comm.compress_ratio`` gauge.  Byte
accounting happens when the collective is *traced* (shapes are static),
so counters advance once per compilation while every executed step
moves exactly the accounted bytes.
"""
from __future__ import annotations

from .config import (CommConfig, get_default_comm_config,  # noqa: F401
                     resolve_comm_config, set_default_comm_config)
from .compress import (dequantize_blockwise, quantize_blockwise,  # noqa: F401
                       quantization_error_bound)
from .collectives import (all_reduce, reduce_scatter,  # noqa: F401
                          sync_gradients, stacked_specs, wire_bytes)
from .zero import ShardedOptimizer, repack_flat  # noqa: F401

__all__ = [
    "CommConfig", "get_default_comm_config", "set_default_comm_config",
    "resolve_comm_config", "quantize_blockwise", "dequantize_blockwise",
    "quantization_error_bound", "all_reduce", "reduce_scatter",
    "sync_gradients", "stacked_specs", "wire_bytes", "ShardedOptimizer",
    "repack_flat",
]
