"""CommConfig: the per-collective compression contract.

One frozen config object decides how a gradient-sync collective moves
bytes; it is hashable so it can ride jit closures without retraces, and
a process-wide default (installed by ``fleet.init`` from
``DistributedStrategy.comm_configs``) lets a whole training script flip
to compressed sync with one config line.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Union

from ...framework.errors import enforce

__all__ = ["CommConfig", "get_default_comm_config",
           "set_default_comm_config", "resolve_comm_config"]

_DTYPES = ("float32", "bfloat16", "int8")


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """How a collective ships its payload.

    dtype:
        "float32" — exact (the lax collective untouched).
        "bfloat16" — cast on the wire, 2× compression.
        "int8" — block-wise absmax quantization (``bits`` wide, stored
        int8) with per-block fp32 scales, ~4× compression.
    bits:
        quantization width for the int8 path (2..8; narrower bits reuse
        the int8 container but quantize coarser).
    block_size:
        elements per scale block; smaller blocks mean tighter error and
        proportionally more scale bytes on the wire.
    error_feedback:
        keep each worker's quantization residual and add it back into
        the next sync (EF-SGD); needs a residual state threaded through
        :func:`collectives.sync_gradients`.
    min_size_to_compress:
        tensors below this many elements always take the exact path —
        small payloads are latency-bound, not bandwidth-bound, and
        per-block scales would dominate their wire cost.
    """

    dtype: str = "float32"
    bits: int = 8
    block_size: int = 256
    error_feedback: bool = False
    min_size_to_compress: int = 2048

    def __post_init__(self):
        enforce(self.dtype in _DTYPES,
                f"CommConfig.dtype must be one of {_DTYPES}, "
                f"got {self.dtype!r}")
        enforce(2 <= int(self.bits) <= 8,
                f"CommConfig.bits supports 2..8 (int8 container), "
                f"got {self.bits}")
        enforce(int(self.block_size) > 0, "block_size must be positive")
        enforce(int(self.min_size_to_compress) >= 0,
                "min_size_to_compress must be >= 0")

    @property
    def compressed(self) -> bool:
        return self.dtype != "float32"

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "CommConfig":
        """Build from a strategy-style dict; unknown keys rejected so a
        typo'd knob fails loudly instead of silently staying exact."""
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        enforce(not unknown,
                f"unknown CommConfig key(s) {sorted(unknown)}; "
                f"valid: {sorted(known)}")
        return cls(**d)


_default = CommConfig()


def set_default_comm_config(config: Union[CommConfig, Dict[str, Any], None]
                            ) -> CommConfig:
    """Install the process-wide default (``None`` resets to exact
    fp32).  Returns the installed config."""
    global _default
    if config is None:
        _default = CommConfig()
    elif isinstance(config, CommConfig):
        _default = config
    else:
        _default = CommConfig.from_dict(config)
    return _default


def get_default_comm_config() -> CommConfig:
    return _default


def resolve_comm_config(config: Union[CommConfig, Dict[str, Any], None]
                        ) -> CommConfig:
    """Per-call override → config object; ``None`` → the process-wide
    default."""
    if config is None:
        return _default
    if isinstance(config, CommConfig):
        return config
    return CommConfig.from_dict(config)
