"""Block-wise quantization primitives for compressed collectives.

The scheme is EQuARX's (PAPERS.md): per-block absmax scales, symmetric
round-to-nearest integer codes in an int8 container.  Unlike the late
``all_reduce_quantized`` stub (which pmax-agreed scales so int payloads
could accumulate in int16 on the wire), scales here travel *with* the
payload — each worker quantizes against its own data's range, which
halves the worst-case error and is what makes the two-phase
all-to-all/all-gather schedule in :mod:`collectives` carry true int8.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ...framework.errors import enforce

__all__ = ["quantize_blockwise", "dequantize_blockwise",
           "quantization_error_bound", "pad_to_multiple"]

_SCALE_FLOOR = 1e-30     # all-zero blocks divide by this, decode to 0


def qmax_for_bits(bits: int) -> float:
    return float(2 ** (int(bits) - 1) - 1)


def pad_to_multiple(flat, multiple: int) -> Tuple[jnp.ndarray, int]:
    """Zero-pad a 1-D array so its length divides ``multiple``; returns
    (padded, pad).  Zero padding is exact for sum/avg reductions and
    quantizes to code 0."""
    pad = (-flat.shape[0]) % int(multiple)
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), flat.dtype)])
    return flat, pad


def quantize_blockwise(flat, bits: int = 8, block_size: int = 256
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """f32[(k*block_size,)] → (codes int8[k, block_size], scales f32[k]).

    Symmetric absmax: code = round(x / scale * qmax) ∈ [-qmax, qmax], so
    dequantization error per element is bounded by scale/(2·qmax) — see
    :func:`quantization_error_bound`.
    """
    enforce(flat.ndim == 1, "quantize_blockwise takes a flat vector")
    enforce(flat.shape[0] % int(block_size) == 0,
            f"length {flat.shape[0]} not a multiple of block_size "
            f"{block_size} (pad_to_multiple first)")
    qmax = qmax_for_bits(bits)
    blocks = flat.astype(jnp.float32).reshape(-1, int(block_size))
    scale = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1), _SCALE_FLOOR)
    q = jnp.clip(jnp.round(blocks / scale[:, None] * qmax), -qmax, qmax)
    return q.astype(jnp.int8), scale


def dequantize_blockwise(codes, scale, bits: int = 8) -> jnp.ndarray:
    """(codes int8[k, bs], scales f32[k]) → f32[(k*bs,)]."""
    qmax = qmax_for_bits(bits)
    return (codes.astype(jnp.float32)
            * (scale[:, None] / qmax)).reshape(-1)


def quantization_error_bound(scale, bits: int = 8) -> jnp.ndarray:
    """Per-block worst-case |x - dequant(quant(x))|: half a code step,
    scale/(2·qmax).  The round-trip tests pin the implementation to this
    bound per block size."""
    return scale / (2.0 * qmax_for_bits(bits))
