"""Compressed collectives: the same mesh-axis semantics as
``distributed.collective``, with a CommConfig deciding what the wire
carries.

int8 schedule (the part the old ``all_reduce_quantized`` stub could not
do — a stock psum cannot carry int8 without cross-lane overflow): a
ring all-reduce is a reduce-scatter followed by an all-gather, and BOTH
halves compress independently:

    quantize(x + residual)  →  all_to_all int8 codes + f32 scales
    local dequant + sum     →  each rank owns 1/n of the reduced vector
    requantize own chunk    →  all_gather int8 codes + f32 scales
    dequant                 →  full reduced vector everywhere

Wire bytes per device: 2·(N + 4·N/block_size) versus the exact
schedule's 2·4·N — ≈3.9× compression at block_size=256 (bf16 cast is
the same shape with 2-byte payloads: 2×).

Error feedback (EF-SGD): the residual a worker's quantizer dropped,
``(x+e) - dequant(quantize(x+e))``, is returned to the caller and added
back in before the next sync.  :func:`sync_gradients` threads that
residual pytree for a whole gradient tree.

Byte accounting rides the PR 3 registry at trace time (shapes are
static): ``comm.bytes`` counts the exact-fp32 schedule,
``comm.compressed_bytes`` what this call ships, and
``comm.compress_ratio`` the running ratio.  Since ISSUE 20 the
counters carry ``[axis=<group>]`` labels (plus ``leg=all_to_all`` /
``leg=all_gather`` for the int8 two-phase halves, booked separately);
readers sum the metric *family* via
:func:`~paddle_tpu.observability.registry.split_labels` so labeled and
legacy-unlabeled series aggregate without double-counting.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax.tree_util import tree_flatten, tree_map, tree_unflatten

from ...framework.errors import enforce
from ..collective import (ReduceOp, _arr, _in_axis, _observed,
                          bound_axis_size)
from ..collective import all_reduce as _exact_all_reduce
from ..collective import reduce_scatter as _exact_reduce_scatter
from .compress import (dequantize_blockwise, pad_to_multiple,
                       quantize_blockwise)
from .config import CommConfig, resolve_comm_config

__all__ = ["all_reduce", "reduce_scatter", "sync_gradients",
           "stacked_specs", "wire_bytes"]


# ---------------------------------------------------------------------------
# byte accounting (trace-time; see module docstring)
# ---------------------------------------------------------------------------
def wire_bytes(n_elements: int, cfg: CommConfig, rounds: int = 2) -> int:
    """Bytes a ``rounds``-round schedule ships per device for an
    ``n_elements`` payload under ``cfg`` (2 rounds = all-reduce's
    reduce-scatter + all-gather; 1 = a lone reduce-scatter or
    all-gather)."""
    if cfg.dtype == "int8":
        n_scales = -(-n_elements // cfg.block_size)   # ceil
        return rounds * (n_elements + 4 * n_scales)
    itemsize = 2 if cfg.dtype == "bfloat16" else 4
    return rounds * n_elements * itemsize


def _account(n_elements: int, cfg: CommConfig, rounds: int = 2,
             group=None, leg: Optional[str] = None) -> None:
    """Book one schedule's bytes.  ``group`` (a mesh-axis name) and
    ``leg`` (which half of the int8 two-phase schedule — ``all_to_all``
    or ``all_gather``) ride as instrument labels (ISSUE 20) so the
    interconnect microscope attributes wire bytes per axis and
    compression efficiency per leg; the running ``comm.compress_ratio``
    gauge stays unlabeled (one headline number)."""
    from ...observability import get_registry
    raw = wire_bytes(n_elements, CommConfig(), rounds)
    wire = wire_bytes(n_elements, cfg, rounds)
    labels = []
    if isinstance(group, str):
        labels.append(f"axis={group}")
    if leg:
        labels.append(f"leg={leg}")
    suffix = "[%s]" % ",".join(labels) if labels else ""
    reg = get_registry()
    reg.counter("comm.bytes" + suffix).inc(raw)
    reg.counter("comm.compressed_bytes" + suffix).inc(wire)
    if wire:
        reg.gauge("comm.compress_ratio").set(raw / wire)


# ---------------------------------------------------------------------------
# compressed cores (flat f32 vectors, inside a bound axis)
# ---------------------------------------------------------------------------
def _avg(x, op: str, n: int):
    return x / n if op == ReduceOp.AVG else x


def _int8_reduce_scatter_flat(flat, group: str, cfg: CommConfig,
                              op: str) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Phase 1: flat f32[(n·chunk,)] → (my reduced chunk f32[(chunk,)],
    own transmitted value f32 like ``flat`` — the dequantized payload
    this rank shipped, for error feedback).  ``flat`` must already be
    padded to n·block_size."""
    n = bound_axis_size(group)
    bs = int(cfg.block_size)
    nb = flat.shape[0] // bs
    enforce(nb % n == 0, "flat length must divide n*block_size")
    codes, scale = quantize_blockwise(flat, cfg.bits, bs)
    own = dequantize_blockwise(codes, scale, cfg.bits)
    # destination-major: row j of (n, nb/n, bs) is rank j's chunk
    codes = codes.reshape(n, nb // n, bs)
    scale = scale.reshape(n, nb // n)
    codes_r = lax.all_to_all(codes, group, split_axis=0, concat_axis=0,
                             tiled=True)
    scale_r = lax.all_to_all(scale, group, split_axis=0, concat_axis=0,
                             tiled=True)
    qmax = float(2 ** (cfg.bits - 1) - 1)
    contrib = codes_r.astype(jnp.float32) * (scale_r[..., None] / qmax)
    reduced = _avg(jnp.sum(contrib, axis=0), op, n).reshape(-1)
    return reduced, own


def _int8_all_gather_flat(chunk, group: str, cfg: CommConfig
                          ) -> jnp.ndarray:
    """Phase 2: requantize my reduced chunk and all-gather — returns the
    full vector (n·chunk,) on every rank."""
    codes, scale = quantize_blockwise(chunk, cfg.bits, cfg.block_size)
    codes_g = lax.all_gather(codes, group, axis=0, tiled=True)
    scale_g = lax.all_gather(scale, group, axis=0, tiled=True)
    return dequantize_blockwise(codes_g, scale_g, cfg.bits)


def _compressed_all_reduce(x, op: str, group: str, cfg: CommConfig
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(reduced, own-transmitted-value), both shaped/typed like ``x``.
    ``own`` is what error feedback subtracts; exact paths return x."""
    n = bound_axis_size(group)
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    size = flat.shape[0]
    if cfg.dtype == "bfloat16":
        _account(size, cfg, rounds=2, group=group)
        sent = flat.astype(jnp.bfloat16)
        own = sent.astype(jnp.float32)
        out = _avg(lax.psum(sent, group).astype(jnp.float32), op, n)
        return (out.reshape(shape).astype(dtype),
                own.reshape(shape).astype(dtype))
    flat, pad = pad_to_multiple(flat, n * cfg.block_size)
    # per-leg wire accounting (ISSUE 20): the two-phase schedule ships
    # codes+scales once over all_to_all and once over all_gather —
    # booked separately so compression efficiency is measurable per leg
    _account(flat.shape[0], cfg, rounds=1, group=group, leg="all_to_all")
    _account(flat.shape[0], cfg, rounds=1, group=group, leg="all_gather")
    chunk, own = _int8_reduce_scatter_flat(flat, group, cfg, op)
    full = _int8_all_gather_flat(chunk, group, cfg)
    if pad:
        full = full[:-pad]
        own = own[:-pad]
    return (full.reshape(shape).astype(dtype),
            own.reshape(shape).astype(dtype))


def _should_compress(x, cfg: CommConfig, op: str) -> bool:
    # compression only makes sense for linear reductions; MAX/MIN/PROD
    # and sub-threshold payloads stay exact
    return (cfg.compressed and op in (ReduceOp.SUM, ReduceOp.AVG)
            and x.size >= cfg.min_size_to_compress
            and jnp.issubdtype(x.dtype, jnp.floating))


# ---------------------------------------------------------------------------
# public surface
# ---------------------------------------------------------------------------
@_observed
def all_reduce(x, op: str = ReduceOp.SUM, group: Optional[str] = "dp",
               config=None):
    """Drop-in ``collective.all_reduce`` with a CommConfig deciding the
    wire format.  Exact (fp32 / non-sum ops / small payloads / no
    config) delegates to the lax path; identity outside a bound axis,
    like every collective here."""
    cfg = resolve_comm_config(config)
    x = _arr(x)
    if not _in_axis(group if isinstance(group, str) else (group or [None])[0]):
        return x
    if not _should_compress(x, cfg, op):
        _account(x.size, CommConfig(), rounds=2,   # exact: raw == wire
                 group=group)
        return _exact_all_reduce(x, op, group)
    out, _own = _compressed_all_reduce(x, op, group, cfg)
    return out


@_observed
def reduce_scatter(x, op: str = ReduceOp.SUM, group: Optional[str] = "dp",
                   axis: int = 0, config=None):
    """Compressed ``collective.reduce_scatter``.  The compressed path is
    defined for flat (1-D, axis 0) payloads — the gradient-sync shape
    ZeRO uses; anything else takes the exact path."""
    cfg = resolve_comm_config(config)
    x = _arr(x)
    if not _in_axis(group):
        return x
    if (not _should_compress(x, cfg, op) or x.ndim != 1 or axis != 0
            or cfg.dtype == "bfloat16"):
        if cfg.dtype == "bfloat16" and _should_compress(x, cfg, op):
            n = bound_axis_size(group)
            _account(x.size, cfg, rounds=1, group=group)
            out = lax.psum_scatter(x.astype(jnp.bfloat16), group,
                                   scatter_dimension=axis, tiled=True)
            return _avg(out.astype(jnp.float32), op, n).astype(x.dtype)
        _account(x.size, CommConfig(), rounds=1, group=group)
        # the legacy exact surface only sums (reference c_reducescatter);
        # honor AVG here so compressed and exact paths agree on semantics
        out = _exact_reduce_scatter(x, ReduceOp.SUM, group, axis=axis)
        return _avg(out, op, bound_axis_size(group))
    n = bound_axis_size(group)
    shape_ok = x.shape[0] % (n * cfg.block_size) == 0
    enforce(shape_ok,
            f"compressed reduce_scatter needs length divisible by "
            f"group·block_size ({n}·{cfg.block_size}); pad first "
            f"(got {x.shape[0]})")
    _account(x.shape[0], cfg, rounds=1, group=group, leg="all_to_all")
    dtype = x.dtype
    chunk, _own = _int8_reduce_scatter_flat(
        x.astype(jnp.float32), group, cfg, op)
    return chunk.astype(dtype)


def sync_gradients(grads, config=None, group: Optional[str] = "dp",
                   residual=None, op: str = ReduceOp.AVG):
    """Synchronize a gradient pytree across ``group`` — the dp gradient
    all-reduce with optional compression and error feedback.

    Returns ``(synced, new_residual)``; ``new_residual`` is ``None``
    unless the config asks for error feedback, in which case pass it
    back in on the next call (a ``None`` residual starts at zero).
    Leaves below ``min_size_to_compress`` sync exactly and keep a zero
    residual.  Outside a bound axis this is the identity (world size 1).
    """
    cfg = resolve_comm_config(config)
    leaves, treedef = tree_flatten(grads)
    if not _in_axis(group):
        return grads, (tree_map(jnp.zeros_like, grads)
                       if cfg.error_feedback else None)
    res_leaves = (treedef.flatten_up_to(residual)
                  if residual is not None else [None] * len(leaves))
    out, new_res = [], []
    for g, e in zip(leaves, res_leaves):
        if g is None:
            out.append(None)
            new_res.append(None)
            continue
        g = _arr(g)
        if not _should_compress(g, cfg, op):
            _account(g.size, CommConfig(), rounds=2,  # exact: raw == wire
                     group=group)
            out.append(_exact_all_reduce(g, op, group))
            new_res.append(jnp.zeros_like(g) if cfg.error_feedback
                           else None)
            continue
        xe = (g + e.astype(g.dtype)) if (cfg.error_feedback
                                         and e is not None) else g
        synced, own = _compressed_all_reduce(xe, op, group, cfg)
        out.append(synced)
        new_res.append((xe - own) if cfg.error_feedback else None)
    synced_tree = tree_unflatten(treedef, out)
    if not cfg.error_feedback:
        return synced_tree, None
    return synced_tree, tree_unflatten(treedef, new_res)


def stacked_specs(tree, axis: str = "dp"):
    """PartitionSpecs that stack per-rank state (e.g. error-feedback
    residuals) along ``axis`` dim 0 — the out_specs/in_specs a
    ``shard_map`` needs to carry rank-private pytrees across steps.
    Leaves must be at least 1-D (reshape scalars to ``(1,)``)."""
    def _spec(leaf):
        ndim = getattr(leaf, "ndim", None)
        if ndim is None:
            ndim = jnp.asarray(leaf).ndim
        enforce(ndim >= 1,
                "stacked_specs: scalar leaves cannot stack along an "
                "axis; reshape to (1,)")
        return P(axis, *([None] * (ndim - 1)))
    return tree_map(_spec, tree)
