"""ZeRO-1 weight-update sharding: :class:`ShardedOptimizer`.

Reference: ``DygraphShardingOptimizer``
(fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:28)
assigns whole parameters to ranks; the TPU-native form (PAPERS.md
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training") shards a padded flat view instead, which makes every shape
even by construction:

    pack     params → fp32 flat master, zero-padded to n·alignment
    sync     reduce-scatter the flat gradient along the dp axis
             (exact psum_scatter, or the comm package's int8 two-phase)
    update   inner optimizer's elementwise rule on MY (flat_len/n,)
             shard of master + slots — 1/n of the Adam state per replica
    gather   all-gather the updated flat master, unpack to leaves

Two execution modes, one state layout (global flat leaves are
``(padded_len,)`` sharded along the axis):

- **shard_map** (the axis is bound in the current trace): explicit
  collectives; state leaves are the per-rank ``(chunk,)`` view.  Call
  ``init`` inside the same shard_map (out_specs from
  :meth:`state_sharding_specs`).
- **jit/GSPMD** (mesh exists, axis unbound — the hapi path): sharding
  constraints on the flat state make XLA derive the same
  reduce-scatter + sharded update + all-gather.
- no mesh at all → plain single-replica flat update (numerics identical
  to the inner optimizer).

Only *elementwise* update rules shard this way (Adam/AdamW/SGD/
Momentum/...); trust-ratio optimizers (Lamb, Lars) need per-parameter
norms a flat shard cannot see and are rejected at construction.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.errors import enforce
from ..collective import _in_axis, bound_axis_size
from ..topology import get_mesh
from .collectives import _account, _int8_reduce_scatter_flat
from .config import CommConfig, resolve_comm_config

__all__ = ["ShardedOptimizer", "repack_flat"]


def repack_flat(saved, target_len: int) -> np.ndarray:
    """Re-pad a zero-padded flat pack (the ZeRO-1 master / slot layout)
    from one shard count's alignment to another's — the elastic-resize
    relayout (ISSUE 9).

    The pack invariant makes this exact: real elements occupy
    ``[0, total)`` and everything past ``total`` is zeros, so moving
    between ``padded_old`` and ``padded_new`` (both ≥ total) only drops
    or adds zero padding — the real elements are preserved **bitwise**.
    Dropping a nonzero tail is refused loudly: that would mean the
    target was packed for different params, not a different width.
    """
    saved = np.asarray(saved)
    enforce(saved.ndim == 1,
            f"repack_flat wants a flat (1-D) pack, got {saved.shape}")
    n = saved.shape[0]
    target_len = int(target_len)
    if target_len == n:
        return saved
    if target_len < n:
        tail = saved[target_len:]
        enforce(not np.any(tail),
                f"repack_flat would drop {int(np.count_nonzero(tail))} "
                f"nonzero element(s) truncating {n} -> {target_len}; the "
                f"saved pack belongs to different params")
        return np.ascontiguousarray(saved[:target_len])
    return np.concatenate(
        [saved, np.zeros((target_len - n,), saved.dtype)])


class _LeafInfo(NamedTuple):
    index: int          # position in the flattened params leaf list
    path: str           # dotted key path (for decay gating / debugging)
    shape: Tuple[int, ...]
    dtype: Any
    size: int
    offset: int         # into the flat vector


class _PackMeta(NamedTuple):
    treedef: Any
    n_leaves: int
    packed: Tuple[_LeafInfo, ...]
    total: int          # packed elements before padding
    padded: int         # after padding (divisible by n·alignment)
    chunk: int          # padded // n


def _path_str(path) -> str:
    parts = []
    for k in path:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
    return ".".join(parts)


class ShardedOptimizer:
    """ZeRO-1 wrapper with the framework optimizer's functional
    contract (``init(params)`` / ``apply_gradients(grads, params,
    state, lr=None)``) plus a dygraph-style ``step``.

    Args:
        inner: an elementwise framework optimizer (Adam, AdamW, SGD,
            Momentum, ...).
        axis: mesh axis to shard along; default "sharding" when the
            mesh has it, else "dp".
        num_shards: override the shard count (otherwise resolved from
            the bound axis or the installed mesh; 1 with no mesh).
        comm: optional :class:`CommConfig` compressing the gradient
            reduce-scatter (shard_map mode only; error feedback is the
            per-leaf :func:`sync_gradients` path's job and is rejected
            here — a sharded residual would change the EF semantics).
        grad_op: "avg" (dp convention, default) or "sum" — how local
            gradients combine across the axis in shard_map mode.  Under
            GSPMD the mean over the global batch already happened in
            the loss.
    """

    def __init__(self, inner, axis: Optional[str] = None,
                 num_shards: Optional[int] = None, comm=None,
                 grad_op: str = "avg", mesh=None):
        from ...optimizer import (Adam, Adagrad, Adadelta, AdamMax,
                                  ClipGradByNorm, Momentum, RMSProp, SGD)
        enforce(isinstance(inner, (Adam, Adagrad, Adadelta, AdamMax,
                                   Momentum, RMSProp, SGD)),
                f"ShardedOptimizer needs an elementwise optimizer "
                f"(Adam/AdamW/SGD/Momentum/...); {type(inner).__name__} "
                f"updates through cross-element statistics a flat shard "
                f"cannot see")
        enforce(not isinstance(getattr(inner, "_grad_clip", None),
                               ClipGradByNorm),
                "ClipGradByNorm clips per-parameter norms, which a flat "
                "shard cannot see; use ClipGradByGlobalNorm or "
                "ClipGradByValue")
        self._inner = inner
        self._axis_opt = axis
        self._mesh_opt = mesh
        self._num_shards_opt = num_shards
        cfg = resolve_comm_config(comm) if comm is not None else None
        if cfg is not None:
            enforce(not cfg.error_feedback,
                    "error feedback needs a per-replica residual that "
                    "ZeRO's sharded state does not carry; use "
                    "comm.sync_gradients for EF gradient sync")
            enforce(cfg.dtype != "bfloat16",
                    "bf16 reduce-scatter would down-cast the master "
                    "gradient; use int8 (blockwise scales) or exact")
        self._comm = cfg
        enforce(grad_op in ("avg", "sum"),
                f"grad_op must be 'avg' or 'sum', got {grad_op!r}")
        self._grad_op = grad_op
        self._bound: Optional[Tuple[Any, str, int]] = None
        self._zstate = None     # dygraph-style step() state

    # -- delegation ---------------------------------------------------------
    @property
    def inner(self):
        return self._inner

    def __getattr__(self, name):
        if name.startswith("__") or name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)

    # -- topology -----------------------------------------------------------
    def bind_mesh(self, mesh=None) -> "ShardedOptimizer":
        """(Re)resolve the mesh/axis/shard-count binding — hapi's
        ``prepare`` calls this so the fleet mesh active at prepare time
        is the one the jitted step constrains against."""
        if mesh is not None:
            self._mesh_opt = mesh
        self._bound = None
        self._resolve()
        return self

    def _resolve(self) -> Tuple[Any, str, int]:
        if self._bound is not None:
            return self._bound
        mesh = self._mesh_opt if self._mesh_opt is not None else get_mesh()
        axis = self._axis_opt
        if axis is None:
            axis = ("sharding" if mesh is not None
                    and "sharding" in mesh.axis_names
                    and mesh.shape["sharding"] > 1 else "dp")
        n = self._num_shards_opt
        if n is None:
            if _in_axis(axis):
                n = int(bound_axis_size(axis))
            elif mesh is not None and axis in mesh.axis_names:
                n = int(mesh.shape[axis])
            else:
                n = 1
        self._bound = (mesh, axis, int(n))
        return self._bound

    @property
    def num_shards(self) -> int:
        return self._resolve()[2]

    @property
    def axis(self) -> str:
        return self._resolve()[1]

    # -- packing ------------------------------------------------------------
    def _alignment(self, n: int) -> int:
        return n * (self._comm.block_size if self._comm is not None
                    and self._comm.dtype == "int8" else 1)

    def _meta(self, params) -> _PackMeta:
        flat_wp, treedef = jax.tree_util.tree_flatten_with_path(params)
        packed: List[_LeafInfo] = []
        offset = 0
        for i, (path, leaf) in enumerate(flat_wp):
            arr = jnp.asarray(leaf)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                continue            # non-float leaves pass through as-is
            size = int(np.prod(arr.shape)) if arr.ndim else 1
            packed.append(_LeafInfo(i, _path_str(path), tuple(arr.shape),
                                    arr.dtype, size, offset))
            offset += size
        _, _, n = self._resolve()
        align = self._alignment(n)
        padded = -(-max(offset, 1) // align) * align
        return _PackMeta(treedef, len(flat_wp), tuple(packed), offset,
                         padded, padded // n)

    def _pack_flat(self, leaves, meta: _PackMeta,
                   fill_missing: bool = False) -> jnp.ndarray:
        parts = []
        for info in meta.packed:
            leaf = leaves[info.index]
            if leaf is None:
                enforce(fill_missing,
                        f"missing leaf for {info.path} in pack")
                parts.append(jnp.zeros((info.size,), jnp.float32))
            else:
                arr = jnp.asarray(leaf)
                if (isinstance(arr, jax.Array)
                        and not isinstance(arr, jax.core.Tracer)
                        and len(getattr(arr, "devices", lambda: [])()) > 1):
                    # concrete leaves of a TP-placed model carry MIXED
                    # shardings; eagerly concatenating those miscompiles
                    # on this stack (observed: replicated LN weights
                    # summed across devices).  Round-trip through host —
                    # init-time only; traced packs (the jitted step) are
                    # resharded correctly by the partitioner.
                    arr = jnp.asarray(np.asarray(arr))
                parts.append(jnp.ravel(arr).astype(jnp.float32))
        pad = meta.padded - meta.total
        if pad or not parts:
            parts.append(jnp.zeros((meta.padded - meta.total,),
                                   jnp.float32))
        return jnp.concatenate(parts) if len(parts) > 1 else parts[0]

    def _unpack(self, flat, meta: _PackMeta, params):
        leaves = list(meta.treedef.flatten_up_to(params))
        for info in meta.packed:
            seg = lax.slice(flat, (info.offset,),
                            (info.offset + info.size,))
            leaves[info.index] = seg.reshape(info.shape).astype(info.dtype)
        return jax.tree_util.tree_unflatten(meta.treedef, leaves)

    def _coeff_flat(self, params, meta: _PackMeta, tree) -> jnp.ndarray:
        """Static per-leaf coefficient tree (decay / L1) → flat np
        vector matching the pack layout (zeros in the padding)."""
        leaves = meta.treedef.flatten_up_to(tree)
        out = np.zeros((meta.padded,), np.float32)
        for info in meta.packed:
            c = float(leaves[info.index])
            if c:
                out[info.offset:info.offset + info.size] = c
        return jnp.asarray(out)

    # -- functional contract ------------------------------------------------
    def init(self, params) -> Dict[str, Any]:
        """Flat sharded state: ``{"step", "flat" (fp32 master shard),
        "slots" {name: shard}}``.  Inside ``shard_map`` the leaves are
        this rank's ``(chunk,)`` slice; on the host they are the full
        ``(padded,)`` vectors, placed sharded when a mesh is
        installed."""
        mesh, axis, n = self._resolve()
        meta = self._meta(params)
        leaves = meta.treedef.flatten_up_to(params)
        flat = self._pack_flat(leaves, meta)
        if _in_axis(axis):
            idx = lax.axis_index(axis)
            flat = lax.dynamic_slice(flat, (idx * meta.chunk,),
                                     (meta.chunk,))
        state = {"step": jnp.zeros((), jnp.int32), "flat": flat,
                 "slots": self._inner._init_slot(flat)}
        if (not _in_axis(axis) and mesh is not None and n > 1
                and axis in mesh.axis_names):
            shard = NamedSharding(mesh, P(axis))
            state["flat"] = jax.device_put(state["flat"], shard)
            state["slots"] = jax.tree_util.tree_map(
                lambda s: jax.device_put(s, shard), state["slots"])
        return state

    def relayout_state(self, state, params):
        """Re-pack a (host or globally-gathered) ZeRO-1 state built for a
        DIFFERENT shard count onto this optimizer's currently-resolved
        mesh/axis/shard-count binding — the elastic dp-resize path
        (ISSUE 9).  ``state`` leaves must be the full ``(padded_old,)``
        vectors (what a checkpoint restore without a sharded template
        yields); returns the state placed for the current mesh.  Values
        are preserved bitwise (only zero padding moves)."""
        mesh, axis, n = self._resolve()
        meta = self._meta(params)

        def _repack(leaf):
            leaf = np.asarray(leaf)
            if leaf.ndim != 1:
                return jnp.asarray(leaf)      # "step" scalar passthrough
            enforce(leaf.shape[0] >= meta.total,
                    f"flat state of {leaf.shape[0]} elements cannot hold "
                    f"{meta.total} packed params — wrong checkpoint?")
            return jnp.asarray(repack_flat(leaf, meta.padded))

        out = {"step": jnp.asarray(np.asarray(state["step"]), jnp.int32),
               "flat": _repack(state["flat"]),
               "slots": jax.tree_util.tree_map(_repack, state["slots"])}
        if mesh is not None and n > 1 and axis in mesh.axis_names:
            shard = NamedSharding(mesh, P(axis))
            out["flat"] = jax.device_put(out["flat"], shard)
            out["slots"] = jax.tree_util.tree_map(
                lambda s: jax.device_put(s, shard), out["slots"])
        return out

    def state_sharding_specs(self, params=None):
        """PartitionSpecs for the state pytree — the out_specs/in_specs
        a ``shard_map`` drill threads the state through."""
        _, axis, _ = self._resolve()
        slots = self._inner._init_slot(jnp.zeros((1,), jnp.float32))
        return {"step": P(),
                "flat": P(axis),
                "slots": jax.tree_util.tree_map(lambda _: P(axis), slots)}

    def _clip_scale(self, flat_g, axis: str, sharded: bool):
        """ClipGradByGlobalNorm over the *synced* gradient: local
        shard's sum of squares + one scalar psum."""
        from ...optimizer import ClipGradByGlobalNorm, ClipGradByValue
        clip = getattr(self._inner, "_grad_clip", None)
        if clip is None:
            return flat_g
        if isinstance(clip, ClipGradByValue):
            return jnp.clip(flat_g, clip.min, clip.max)
        if isinstance(clip, ClipGradByGlobalNorm):
            sq = jnp.sum(jnp.square(flat_g))
            if sharded:
                sq = lax.psum(sq, axis)
            norm = jnp.sqrt(sq)
            scale = jnp.minimum(1.0, clip.clip_norm
                                / jnp.maximum(norm, 1e-12))
            return flat_g * scale
        raise TypeError(f"unsupported grad clip {type(clip).__name__} "
                        f"for ShardedOptimizer")

    def apply_gradients(self, grads, params, state, lr=None):
        """Pure ZeRO-1 update: (new_params, new_state).  ``grads`` are
        the LOCAL gradients in shard_map mode (the sync happens here,
        compressed when configured); already-global under GSPMD."""
        inner = self._inner
        mesh, axis, n = self._resolve()
        meta = self._meta(params)
        sharded = _in_axis(axis)
        if sharded:
            enforce(int(bound_axis_size(axis)) == n,
                    f"bound axis {axis} has size {bound_axis_size(axis)} "
                    f"but state was built for {n} shards")
        g_leaves = meta.treedef.flatten_up_to(grads)
        flat_g = self._pack_flat(g_leaves, meta, fill_missing=True)

        step = state["step"] + 1
        lr_t = (jnp.asarray(lr, jnp.float32) if lr is not None
                else inner._lr_at(step - 1))
        wd_flat = self._coeff_flat(params, meta, inner._decay_tree(params))
        l1_flat = (self._coeff_flat(params, meta, inner._l1_tree(params))
                   if getattr(inner, "_l1", 0.0) else None)

        if sharded:
            if self._comm is not None and self._comm.dtype == "int8":
                _account(meta.padded, self._comm, rounds=1)
                g_shard, _own = _int8_reduce_scatter_flat(
                    flat_g, axis, self._comm, self._grad_op)
            else:
                _account(meta.padded, CommConfig(), rounds=1)
                g_shard = lax.psum_scatter(flat_g, axis,
                                           scatter_dimension=0, tiled=True)
                if self._grad_op == "avg":
                    g_shard = g_shard / n
            idx = lax.axis_index(axis)
            off = idx * meta.chunk
            wd = lax.dynamic_slice(wd_flat, (off,), (meta.chunk,))
            l1 = (lax.dynamic_slice(l1_flat, (off,), (meta.chunk,))
                  if l1_flat is not None else None)
        else:
            g_shard, wd, l1 = flat_g, wd_flat, l1_flat
            if mesh is not None and n > 1 and axis in mesh.axis_names:
                cons = NamedSharding(mesh, P(axis))
                g_shard = lax.with_sharding_constraint(g_shard, cons)

        g_shard = self._clip_scale(g_shard, axis, sharded)
        p_shard = state["flat"]
        if l1 is not None:
            g_shard = g_shard + l1 * jnp.sign(p_shard)
        # weight decay as a flat vector: the inner's scalar-wd branches
        # (`if wd`) can't take one, so reproduce its two decay modes
        # around a wd=0 update — coupled (L2 into the gradient) before,
        # decoupled (AdamW's -lr·wd·p) after
        decoupled = bool(getattr(inner, "_decoupled", False))
        if not decoupled:
            g_shard = g_shard + wd * p_shard
        new_shard, new_slots = inner._update(
            g_shard, p_shard, state["slots"], lr_t, step, 0.0)
        if decoupled:
            new_shard = new_shard - lr_t * wd * p_shard

        if sharded:
            _account(meta.padded, CommConfig(), rounds=1)  # param gather
            full = lax.all_gather(new_shard, axis, axis=0, tiled=True)
        else:
            full = new_shard
            if mesh is not None and n > 1 and axis in mesh.axis_names:
                full = lax.with_sharding_constraint(
                    full, NamedSharding(mesh, P(axis)))
        new_params = self._unpack(full, meta, params)
        return new_params, {"step": step, "flat": new_shard,
                            "slots": new_slots}

    def update(self, grads, params, state):
        return self.apply_gradients(grads, params, state)

    # -- stateful (dygraph-parity) path -------------------------------------
    def step(self, grads=None):
        """Eager convenience over the inner's bound parameters (GSPMD/
        single-replica modes; a shard_map drill drives the functional
        contract directly)."""
        from ...optimizer import LRScheduler
        inner = self._inner
        enforce(inner._parameters is not None,
                "stateful step() needs parameters= at construction")
        keys = inner._param_keys()
        if grads is None:
            grads = [p._grad for p in inner._parameters]
        values = dict(zip(keys, (p.value for p in inner._parameters)))
        gdict = dict(zip(keys, (None if not t.trainable else g
                                for g, t in zip(grads, inner._parameters))))
        if self._zstate is None:
            self._zstate = self.init(values)
        lr = inner.get_lr() if isinstance(inner._lr, LRScheduler) else None
        new_values, self._zstate = self.apply_gradients(
            gdict, values, self._zstate, lr=lr)
        for p, k in zip(inner._parameters, keys):
            p.value = new_values[k]
            p._grad = None

    def clear_grad(self):
        self._inner.clear_grad()
