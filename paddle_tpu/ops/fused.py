"""Fused transformer epilogues + rotary embedding.

Reference semantics:
- fused bias+dropout+residual(+LayerNorm): operators/fused/
  fused_dropout_helper.h `FusedDropoutHelper`:110 (bias+dropout+residual) and
  `FusedDropoutLayerNormHelper`:207 (…+LN) — the epilogue of
  fused_attention_op.cc and fused_feedforward_op.cc.
- fused_feedforward: operators/fused/fused_feedforward_op.cc —
  [pre-LN] → GEMM → act(+dropout) → GEMM → bias+dropout+residual[+post-LN].
- rope: no op in this snapshot (SURVEY §7 spec-vs-snapshot note) —
  BASELINE.json names it for the Pallas set; standard GPT-NeoX rotary
  formulation.

TPU-native design: these are *compositions* — XLA's fusion pass emits the
single fused HBM pass the reference hand-writes in CUDA (cost model: one
read of x/residual, one write), so a hand kernel would only re-derive what
the compiler already does.  Kept as named ops for API parity and so the
fusion boundary is testable (OpTest-style numeric parity in
tests/test_ops.py).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as fw_random
from ..nn import functional as F


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else x


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.0, epsilon: float = 1e-5,
        training: bool = True, key=None):
    """out = LayerNorm(residual + dropout(x + bias)) — the reference's
    FusedDropoutLayerNormHelper (fused_dropout_helper.h:207)."""
    x = _arr(x)
    if bias is not None:
        x = x + _arr(bias).astype(x.dtype)
    if dropout_rate > 0.0 and training:
        x = F.dropout(x, dropout_rate, training=True, key=key)
    y = _arr(residual) + x
    return F.layer_norm(y, (y.shape[-1],), ln_scale, ln_bias, epsilon)


def fused_bias_dropout_residual(x, residual, bias=None,
                                dropout_rate: float = 0.0,
                                training: bool = True, key=None):
    """out = residual + dropout(x + bias) (fused_dropout_helper.h:110)."""
    x = _arr(x)
    if bias is not None:
        x = x + _arr(bias).astype(x.dtype)
    if dropout_rate > 0.0 and training:
        x = F.dropout(x, dropout_rate, training=True, key=key)
    return _arr(residual) + x


def fused_feedforward(x, w1, b1, w2, b2, ln_scale=None, ln_bias=None,
                      activation: str = "gelu", dropout1: float = 0.0,
                      dropout2: float = 0.0, epsilon: float = 1e-5,
                      pre_layer_norm: bool = True, training: bool = True):
    """The fused FFN block (fused_feedforward_op.cc): one jit region —
    XLA fuses the activation and dropout into the GEMM epilogues."""
    x = _arr(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, (x.shape[-1],), ln_scale, ln_bias, epsilon)
    act = {"gelu": F.gelu, "relu": F.relu}[activation]
    h = act(F.linear(x, w1, b1))
    if dropout1 > 0.0 and training:
        h = F.dropout(h, dropout1, training=True)
    out = F.linear(h, w2, None)
    out = fused_bias_dropout_residual(out, residual, b2, dropout2, training)
    if not pre_layer_norm:
        out = F.layer_norm(out, (out.shape[-1],), ln_scale, ln_bias, epsilon)
    return out


@functools.lru_cache(maxsize=64)
def _rope_tables(seq_len: int, head_dim: int, base: float):
    """Host-side cache of the rope cos/sin tables per (seq_len, head_dim,
    base) — computed ONCE (eagerly, same f32 jnp expressions the inline
    path used, so numerics are identical) and embedded as trace constants
    thereafter.  Before this cache every layer of every traced step
    rebuilt inv_freq/cos/sin from scratch; now per-layer rope cost is the
    two multiplies (ISSUE 7 satellite)."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    angles = (jnp.arange(seq_len, dtype=jnp.float32)[:, None]
              * inv_freq)                                # (s, d/2)
    return jnp.cos(angles), jnp.sin(angles)


def rotary_position_embedding(q, k, position_ids=None, base: float = 10000.0):
    """GPT-NeoX-style rotary embedding on (batch, heads, seq, head_dim)
    q/k; rotates the first/second halves of head_dim.  cos/sin come from
    the per-(seq_len, head_dim, base) lru cache when positions are the
    default arange or concrete ids; only traced position_ids fall back to
    the on-the-fly computation."""
    q, k = _arr(q), _arr(k)
    b, h, s, d = q.shape
    ids = _arr(position_ids) if position_ids is not None else None
    if ids is None:
        cos_t, sin_t = _rope_tables(s, d, float(base))
        cos = cos_t[None, None, :, :]                    # (1, 1, s, d/2)
        sin = sin_t[None, None, :, :]
    elif not isinstance(ids, jax.core.Tracer):
        pos = np.asarray(ids)
        cos_t, sin_t = _rope_tables(int(pos.max()) + 1, d, float(base))
        cos = cos_t[pos][:, None, :, :]                  # (b|1, 1, s, d/2)
        sin = sin_t[pos][:, None, :, :]
    else:
        pos = ids
        inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2,
                                              dtype=jnp.float32) / d))
        angles = pos[..., None].astype(jnp.float32) * inv_freq
        cos = jnp.cos(angles)[:, None, :, :]             # (b|1, 1, s, d/2)
        sin = jnp.sin(angles)[:, None, :, :]

    def rot(x):
        x1, x2 = x[..., : d // 2], x[..., d // 2:]
        xf1 = x1.astype(jnp.float32)
        xf2 = x2.astype(jnp.float32)
        r1 = xf1 * cos - xf2 * sin
        r2 = xf2 * cos + xf1 * sin
        return jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


# ---------------------------------------------------------------------------
# Fused linear + softmax cross-entropy (memory-efficient LM loss).
#
# Reference semantics: the c_softmax_with_cross_entropy objective
# (operators/collective/c_softmax_with_cross_entropy_op.cu) applied to a
# tied-embedding LM head.  The naive composition materializes the full
# [B, S, V] logits **twice** (bf16 matmul output + the f32 softmax
# probabilities XLA saves for backward) — measured on v5e at GPT-125M
# B=8/S=2048 that is ~4.5GB of HLO temps, and B=32 OOMs outright
# (benchmarks/batch_scan_125m.json).  This op never materializes more than
# one [B, chunk, V] block: forward scans over sequence chunks saving only
# the per-token logsumexp; backward recomputes each chunk's logits and
# fuses softmax-grad into the dW / dh matmuls.
# ---------------------------------------------------------------------------
def _lce_chunk(s: int, batch: int = 1, vocab: int = 0):
    """Largest sequence chunk (a multiple of the 128-lane tile) dividing s
    whose per-chunk f32 logits block [batch, chunk, vocab] stays under
    ~1.6GB of HBM (the measured B=32 OOM headroom — batch_scan_125m.json);
    None = sequence too irregular, caller should fall back to the unfused
    path."""
    budget = 1.6e9
    best = None
    for c in (512, 256, 128):
        if s % c == 0:
            best = best or c                   # largest divisor as fallback
            if batch * c * vocab * 4 <= budget:
                return c
    return 128 if best else None               # smallest tile when over budget


def _lce_constraint(logits, spec):
    if spec is None:
        return logits
    from ..distributed.mp_layers import shard_constraint
    return shard_constraint(logits, *spec)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _linear_ce(hidden, table, labels, chunk, spec):
    loss, _ = _linear_ce_fwd(hidden, table, labels, chunk, spec)
    return loss


def _lce_split(x, chunk):
    """[b, s, ...] -> [s/chunk, b, chunk, ...] (scan-major)."""
    b, s = x.shape[0], x.shape[1]
    x = x.reshape((b, s // chunk, chunk) + x.shape[2:])
    return jnp.moveaxis(x, 1, 0)


def _lce_merge(x):
    """[n, b, chunk, ...] -> [b, n*chunk, ...]."""
    x = jnp.moveaxis(x, 0, 1)
    return x.reshape((x.shape[0], x.shape[1] * x.shape[2]) + x.shape[3:])


def _linear_ce_fwd(hidden, table, labels, chunk, spec):
    vocab = table.shape[0]
    hs = _lce_split(hidden, chunk)
    ls = _lce_split(labels, chunk)

    def body(_, inp):
        hc, lc = inp
        logits = jnp.einsum("bch,vh->bcv", hc, table,
                            preferred_element_type=jnp.float32)
        logits = _lce_constraint(logits, spec)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, jnp.clip(lc, 0, vocab - 1)[..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        return 0, (lse, picked)

    _, (lse, picked) = jax.lax.scan(body, 0, (hs, ls))
    loss = _lce_merge(lse - picked)
    return loss, _lce_merge(lse)


def _linear_ce_fwd_rule(hidden, table, labels, chunk, spec):
    loss, lse = _linear_ce_fwd(hidden, table, labels, chunk, spec)
    return loss, (hidden, table, labels, lse)


def _linear_ce_bwd_rule(chunk, spec, res, g):
    import numpy as _np
    hidden, table, labels, lse = res
    vocab = table.shape[0]
    hs = _lce_split(hidden, chunk)
    ls = _lce_split(labels, chunk)
    lses = _lce_split(lse, chunk)
    gs = _lce_split(g, chunk)

    def body(dw, inp):
        hc, lc, lsec, gc = inp
        logits = jnp.einsum("bch,vh->bcv", hc, table,
                            preferred_element_type=jnp.float32)
        logits = _lce_constraint(logits, spec)
        p = jnp.exp(logits - lsec[..., None])
        onehot = (lc[..., None] ==
                  jax.lax.broadcasted_iota(lc.dtype, (1, 1, vocab), 2))
        grad = ((p - onehot.astype(p.dtype))
                * gc[..., None].astype(p.dtype)).astype(table.dtype)
        dh = jnp.einsum("bcv,vh->bch", grad, table,
                        preferred_element_type=jnp.float32)
        dw = dw + jnp.einsum("bcv,bch->vh", grad, hc,
                             preferred_element_type=jnp.float32)
        return dw, dh.astype(hidden.dtype)

    dw0 = jnp.zeros(table.shape, jnp.float32)
    dw, dhs = jax.lax.scan(body, dw0, (hs, ls, lses, gs))
    dh = _lce_merge(dhs)
    return (dh, dw.astype(table.dtype),
            _np.zeros(labels.shape, jax.dtypes.float0))


_linear_ce.defvjp(_linear_ce_fwd_rule, _linear_ce_bwd_rule)


def linear_softmax_cross_entropy(hidden, table, labels, *,
                                 ignore_index: int = -100,
                                 reduction: str = "mean",
                                 seq_chunk: Optional[int] = None,
                                 logits_spec=None):
    """Cross-entropy of ``softmax(hidden @ table.T)`` against ``labels``
    without materializing full logits (see module note above).

    hidden: (b, s, h); table: (v, h) — e.g. a tied embedding; labels:
    (b, s) int ids, ``ignore_index`` masked out.  ``logits_spec`` optionally
    names mesh axes for the per-chunk logits (e.g. ("dp", None, "mp")) so
    GSPMD keeps the vocab dimension sharded through the scan.  Falls back
    to the unfused path when the sequence has no 128-multiple chunking.
    """
    hidden, table, labels = _arr(hidden), _arr(table), _arr(labels)
    b, s, _ = hidden.shape
    chunk = (seq_chunk if seq_chunk is not None
             else _lce_chunk(s, b, table.shape[0]))
    if chunk is None or s % chunk != 0:
        from ..distributed.mp_ops import parallel_cross_entropy
        logits = jnp.einsum("bsh,vh->bsv", hidden, table)
        return parallel_cross_entropy(
            logits.astype(jnp.float32), labels,
            ignore_index=ignore_index, reduction=reduction)
    spec = tuple(logits_spec) if logits_spec is not None else None
    loss = _linear_ce(hidden, table, labels.astype(jnp.int32), chunk, spec)
    from ..distributed.mp_ops import masked_token_reduce
    return masked_token_reduce(loss, labels != ignore_index, reduction)
