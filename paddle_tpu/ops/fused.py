"""Fused transformer epilogues + rotary embedding.

Reference semantics:
- fused bias+dropout+residual(+LayerNorm): operators/fused/
  fused_dropout_helper.h `FusedDropoutHelper`:110 (bias+dropout+residual) and
  `FusedDropoutLayerNormHelper`:207 (…+LN) — the epilogue of
  fused_attention_op.cc and fused_feedforward_op.cc.
- fused_feedforward: operators/fused/fused_feedforward_op.cc —
  [pre-LN] → GEMM → act(+dropout) → GEMM → bias+dropout+residual[+post-LN].
- rope: no op in this snapshot (SURVEY §7 spec-vs-snapshot note) —
  BASELINE.json names it for the Pallas set; standard GPT-NeoX rotary
  formulation.

TPU-native design: these are *compositions* — XLA's fusion pass emits the
single fused HBM pass the reference hand-writes in CUDA (cost model: one
read of x/residual, one write), so a hand kernel would only re-derive what
the compiler already does.  Kept as named ops for API parity and so the
fusion boundary is testable (OpTest-style numeric parity in
tests/test_ops.py).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework import random as fw_random
from ..nn import functional as F


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else x


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate: float = 0.0, epsilon: float = 1e-5,
        training: bool = True, key=None):
    """out = LayerNorm(residual + dropout(x + bias)) — the reference's
    FusedDropoutLayerNormHelper (fused_dropout_helper.h:207)."""
    x = _arr(x)
    if bias is not None:
        x = x + _arr(bias).astype(x.dtype)
    if dropout_rate > 0.0 and training:
        x = F.dropout(x, dropout_rate, training=True, key=key)
    y = _arr(residual) + x
    return F.layer_norm(y, (y.shape[-1],), ln_scale, ln_bias, epsilon)


def fused_bias_dropout_residual(x, residual, bias=None,
                                dropout_rate: float = 0.0,
                                training: bool = True, key=None):
    """out = residual + dropout(x + bias) (fused_dropout_helper.h:110)."""
    x = _arr(x)
    if bias is not None:
        x = x + _arr(bias).astype(x.dtype)
    if dropout_rate > 0.0 and training:
        x = F.dropout(x, dropout_rate, training=True, key=key)
    return _arr(residual) + x


def fused_feedforward(x, w1, b1, w2, b2, ln_scale=None, ln_bias=None,
                      activation: str = "gelu", dropout1: float = 0.0,
                      dropout2: float = 0.0, epsilon: float = 1e-5,
                      pre_layer_norm: bool = True, training: bool = True):
    """The fused FFN block (fused_feedforward_op.cc): one jit region —
    XLA fuses the activation and dropout into the GEMM epilogues."""
    x = _arr(x)
    residual = x
    if pre_layer_norm:
        x = F.layer_norm(x, (x.shape[-1],), ln_scale, ln_bias, epsilon)
    act = {"gelu": F.gelu, "relu": F.relu}[activation]
    h = act(F.linear(x, w1, b1))
    if dropout1 > 0.0 and training:
        h = F.dropout(h, dropout1, training=True)
    out = F.linear(h, w2, None)
    out = fused_bias_dropout_residual(out, residual, b2, dropout2, training)
    if not pre_layer_norm:
        out = F.layer_norm(out, (out.shape[-1],), ln_scale, ln_bias, epsilon)
    return out


def rotary_position_embedding(q, k, position_ids=None, base: float = 10000.0):
    """GPT-NeoX-style rotary embedding on (batch, heads, seq, head_dim)
    q/k; rotates the first/second halves of head_dim."""
    q, k = _arr(q), _arr(k)
    b, h, s, d = q.shape
    if position_ids is None:
        pos = jnp.arange(s)[None, :]                     # (1, s)
    else:
        pos = _arr(position_ids)
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[..., None].astype(jnp.float32) * inv_freq  # (b|1, s, d/2)
    cos = jnp.cos(angles)[:, None, :, :]                 # (b|1, 1, s, d/2)
    sin = jnp.sin(angles)[:, None, :, :]

    def rot(x):
        x1, x2 = x[..., : d // 2], x[..., d // 2:]
        xf1 = x1.astype(jnp.float32)
        xf2 = x2.astype(jnp.float32)
        r1 = xf1 * cos - xf2 * sin
        r2 = xf2 * cos + xf1 * sin
        return jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)

    return rot(q), rot(k)
