"""Flash attention: the fused FMHA Pallas kernel.

Semantic reference: operators/fused/fused_attention_op.cc:221-357 FMHA path
(`FMHARef`, fused/fmha_ref.h:58 — QK^T, scale, mask, softmax, dropout, PV),
the causal-mask fusion `fused_softmax_mask_upper_triangle_op.cu`, the
in-kernel Philox dropout seeds (fused_attention_op.cc:292-311), and the
decode-time CacheKV path (fused_attention_op.cc:235).  The reference
materializes the (S, S) probability matrix in HBM; this kernel never does —
online softmax over KV blocks keeps everything in VMEM (the whole point of a
TPU-native rewrite: HBM bandwidth is the bottleneck, SURVEY §7 hard-part 2).

Layout: q, k, v are (batch, heads, seq, head_dim), flattened to
(batch*heads, seq, head_dim) for the kernel; grid = (batch*heads, q block,
kv block) with kv innermost — the flash (m, l, acc) recurrence lives in
VMEM scratch across the kv steps, in fp32, so per-step residency is
O(block) and sequence length is HBM-bound (S=65536 runs single-chip).
Backward is recompute-based (no probability tensor saved): a dkdv kernel on a
(bh, kv block, q block) grid accumulating into revisited f32 output blocks,
and a dq kernel over Q blocks, both replaying p = exp(qk - lse).  Backward
VMEM residency is O(block), so sequence length is bounded by HBM, not the
16MB scoped-vmem limit (S=8192 fwd+bwd measured 30ms vs 737ms for XLA
attention on v5e; benchmarks/flash_seqlen_ab.json).

Causal masking is block-skipped: programs never visit KV blocks strictly
above the diagonal, so the causal fwd does ~half the FLOPs — the fusion
`fused_softmax_mask_upper_triangle` only saves bandwidth, not compute.

Attention-prob dropout runs IN-KERNEL (the reference's Philox-offset
trick, counter-based): the keep mask for element (bh, row, col) is a pure
hash of (seed, bh, row, col), so forward and the recompute backward
regenerate bit-identical masks with no mask tensor in HBM.  The dropout
mask applies to the PV accumulation only; the softmax normalizer (and the
saved lse) stay dropout-free, and the output is rescaled by 1/(1-p).

Ragged sequence lengths are auto-padded to a Mosaic-legal multiple; padded
KV columns are masked to -inf in every kernel, and padded Q rows are
sliced away from the output, so callers can pass any length.

On non-TPU backends the kernels run in interpret mode, so the CPU test
mesh exercises the same code paths (the hash dropout is plain integer
jnp, identical under interpret and Mosaic).
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..framework.errors import enforce

_NEG_INF = -1e30

# Mosaic requires the last two block dims to be (multiple of 8, multiple of
# 128) or equal to the array dims, so per-row statistics (lse, delta) can't be
# 2D (bh, seq) blocks of shape (1, bq).  Like the upstream TPU flash kernel,
# they travel as (bh, seq, _LANES) with the value broadcast across the 128
# lanes; kernels slice lanes back down to the KV-block width elementwise.
_LANES = 128



def _dot(a, b, dimension_numbers):
    """``lax.dot_general`` with f32 accumulation and a Mosaic-legal precision.

    The global ``jax_default_matmul_precision`` (e.g. "highest") leaks into
    Pallas kernel traces, and Mosaic rejects fp32 contract precision on bf16
    operands ("Bad lhs type").  Pin the precision from the operand dtypes
    instead: the native MXU bf16 pass for bf16 inputs, exact fp32
    contraction for f32 inputs (the hw parity test holds fp32 to 2e-5).
    """
    prec = (lax.Precision.HIGHEST
            if (a.dtype == jnp.float32 and b.dtype == jnp.float32)
            else lax.Precision.DEFAULT)
    return lax.dot_general(a, b, dimension_numbers,
                           preferred_element_type=jnp.float32,
                           precision=prec)

def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _stat_tile(x, width):
    """Widen a (rows, _LANES) lane-broadcast statistic to (rows, width)."""
    if width <= _LANES:
        return x[:, :width]
    assert width % _LANES == 0, (width, _LANES)
    return jnp.tile(x, (1, width // _LANES))


def _block_sizes(seq_q: int, seq_k: int):
    # swept on v5e (3D-grid kernels, bh·S·d with d=64, best-of-3 fwd+bwd;
    # benchmarks/flash_block_sweep.json): at S=2048, 512/512 = 13.9ms vs
    # 19.5ms for 1024 and 46ms for 128 (small blocks starve the MXU when
    # the contraction dim is only 64); at S>=4096 the longer grid favors
    # 1024/1024 (S=4096: 23.1 vs 25.6ms; S=8192: 30.1 vs 35.2ms).
    # Fall back to the largest power-of-two block that divides the sequence
    # so every multiple of 128 stays supported; the resulting widths are
    # always either <=128 or a multiple of _LANES, which _stat_tile needs.
    def pick(seq):
        cands = (1024, 512, 256, 128) if seq >= 4096 else (512, 256, 128)
        for b in cands:
            if seq % b == 0:
                return b
        return seq
    return pick(seq_q), pick(seq_k)


def _pad_to_legal(seq: int) -> int:
    """Smallest Mosaic-legal padded length >= seq: a multiple of 128, or
    for short sequences a multiple of 8 (full-array blocks are legal)."""
    if seq % 128 == 0:
        return seq
    if seq < 128:
        return -(-seq // 8) * 8
    return -(-seq // 128) * 128


# ---------------------------------------------------------------------------
# Counter-based dropout hash (the Philox-offset analog,
# fused_attention_op.cc:292-311): keep(bh,row,col) is a murmur3-fmix mix of
# (seed, bh, row, col) — stateless, so fwd and recompute-bwd agree exactly.
# ---------------------------------------------------------------------------
def _keep_mask(seed_u32, bh, rows, cols, dropout_p):
    x = (rows.astype(jnp.uint32) * np.uint32(0x85EBCA6B)
         ^ cols.astype(jnp.uint32) * np.uint32(0xC2B2AE35)
         ^ seed_u32
         ^ bh.astype(jnp.uint32) * np.uint32(0x9E3779B1))
    x = x ^ (x >> 16)
    x = x * np.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * np.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    # top 24 bits as a uniform in [0, 1); route the cast through int32 —
    # Mosaic has no uint32->float32 lowering, and the value fits 24 bits
    u = ((x >> 8).astype(jnp.int32).astype(jnp.float32)
         * np.float32(1.0 / (1 << 24)))
    return u >= dropout_p


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------
def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                kv_len, offset, dropout_p):
    # 3D grid (bh, q block, kv block): k/v arrive as per-kv-block tiles and
    # the flash (m, l, acc) state lives in VMEM scratch across the innermost
    # kv steps — residency is O(block) in sequence length (a 2D grid that
    # kept full k/v resident hit the 16MB scoped-vmem limit at S=8192 f32).
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    num_kv = pl.num_programs(2)
    seed = seed_ref[0, 0].astype(jnp.uint32)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr[...], _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr[...])
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    # visit only blocks that touch real keys and (causal) the lower
    # triangle; queries are bottom-right aligned against the REAL key
    # length (``offset`` = kv_len - q_len over unpadded lengths)
    work = kj * block_k < kv_len
    if causal:
        work &= (qi + 1) * block_q - 1 + offset >= kj * block_k

    @pl.when(work)
    def _step():
        # dots stay in the input dtype (bf16 on the fast path) with fp32
        # accumulation — casting to fp32 would run the MXU at 1/4 rate
        q = q_ref[0]                                      # (bq, d)
        k = k_ref[0]                                      # (bk, d)
        v = v_ref[0]
        s = _dot(q, k, (((1,), (1,)), ((), ()))) * scale
        rows = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kj * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < kv_len
        if causal:
            valid = valid & (rows + offset >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        # stats are lane-broadcast (bq, _LANES) tiles, all lanes equal
        m_prev = m_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1)[:, None])
        p = jnp.exp(s - _stat_tile(m_new, block_k))
        alpha = jnp.exp(m_prev - m_new)
        # PV accumulation uses the dropped probabilities; the softmax
        # normalizer l does not (dropout applies after normalization)
        l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=1)[:, None]
        if dropout_p > 0.0:
            p = jnp.where(_keep_mask(seed, bh, rows, cols, dropout_p),
                          p, 0.0)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + _dot(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())))
        m_scr[...] = m_new

    @pl.when(kj == num_kv - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...]
                    / (l_safe[:, :1] * (1.0 - dropout_p))).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l_safe)


def _flash_fwd(q, k, v, seed, scale, causal, dropout_p, kv_len, offset):
    from jax.experimental.pallas import tpu as pltpu
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk)
    grid = (bh, sq // bq, sk // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, kv_len=kv_len, offset=offset,
        dropout_p=dropout_p)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)),       # seed
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),   # m
            pltpu.VMEM((bq, _LANES), jnp.float32),   # l
            pltpu.VMEM((bq, d), jnp.float32),        # acc
        ],
        interpret=_interpret(),
    )(seed, q, k, v)
    return out, lse[:, :, 0]  # keep the compact (bh, sq) form as residual


# ---------------------------------------------------------------------------
# Backward (recompute): dkdv over KV blocks, dq over Q blocks
# ---------------------------------------------------------------------------
def _dkdv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                 kv_len, offset, dropout_p):
    # 3D grid (bh, kv block, q block): q/do/lse/delta arrive as per-q-block
    # tiles, so VMEM residency is O(block) — a 2D grid that kept the full
    # sequence resident hit the 16MB scoped-vmem limit at S=8192.  dk/dv
    # accumulate in the (revisited) f32 output blocks across the innermost
    # q-block steps.
    bh = pl.program_id(0)
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    seed = seed_ref[0, 0].astype(jnp.uint32)
    keep_scale = 1.0 / (1.0 - dropout_p)

    @pl.when(qi == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    # causal block-skip: a block whose every (row, col) pair sits strictly
    # above the diagonal contributes nothing; padded-KV blocks likewise
    work = kj * block_k < kv_len
    if causal:
        work &= (qi + 1) * block_q - 1 + offset >= kj * block_k

    @pl.when(work)
    def _accumulate():
        k = k_ref[0]                                      # (bk, d)
        v = v_ref[0]
        q = q_ref[0]                                      # (bq, d)
        do = do_ref[0]
        # lane-broadcast stats: every lane holds the row's value, so widening
        # to block_k lanes gives an elementwise-ready (bq, bk) tile
        lse = _stat_tile(lse_ref[0], block_k)
        delta = _stat_tile(delta_ref[0], block_k)
        s = _dot(q, k, (((1,), (1,)), ((), ()))) * scale
        rows = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kj * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < kv_len
        if causal:
            valid = valid & (rows + offset >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse)                              # (bq, bk)
        if dropout_p > 0.0:
            pd = jnp.where(_keep_mask(seed, bh, rows, cols, dropout_p),
                           p * keep_scale, 0.0)
        else:
            pd = p
        dv_ref[0] += _dot(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())))
        dp = _dot(do, v, (((1,), (1,)), ((), ())))
        ds = (pd * dp - p * delta) * scale
        dk_ref[0] += _dot(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())))


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, scale, causal, block_q, block_k, kv_len,
               offset, dropout_p):
    # 3D grid (bh, q block, kv block), mirroring _dkdv_kernel: k/v arrive
    # per-kv-block and dq accumulates in the revisited f32 output block.
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    seed = seed_ref[0, 0].astype(jnp.uint32)
    keep_scale = 1.0 / (1.0 - dropout_p)

    @pl.when(kj == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    work = kj * block_k < kv_len
    if causal:
        work &= (qi + 1) * block_q - 1 + offset >= kj * block_k

    @pl.when(work)
    def _accumulate():
        q = q_ref[0]
        do = do_ref[0]
        lse = _stat_tile(lse_ref[0], block_k)  # lane-broadcast → (bq, bk)
        delta = _stat_tile(delta_ref[0], block_k)
        k = k_ref[0]
        v = v_ref[0]
        s = _dot(q, k, (((1,), (1,)), ((), ()))) * scale
        rows = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kj * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = cols < kv_len
        if causal:
            valid = valid & (rows + offset >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse)
        if dropout_p > 0.0:
            pd = jnp.where(_keep_mask(seed, bh, rows, cols, dropout_p),
                           p * keep_scale, 0.0)
        else:
            pd = p
        dp = _dot(do, v, (((1,), (1,)), ((), ())))
        ds = (pd * dp - p * delta) * scale
        dq_ref[0] += _dot(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())))


def _flash_bwd(scale, causal, dropout_p, kv_len, offset, res, g):
    q, k, v, seed, out, lse = res
    do = g
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # NOTE with dropout, out includes the 1/(1-p) rescale; delta =
    # rowsum(do * out) is exactly sum_k dP_ik P_ik of the dropped softmax
    # backward, so the standard recurrence still holds.
    lse_b = jnp.broadcast_to(lse[..., None], (bh, sq, _LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (bh, sq, _LANES))

    dkdv = functools.partial(
        _dkdv_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=kv_len, offset=offset, dropout_p=dropout_p)
    # f32 outputs: they double as the cross-q-block accumulators (Mosaic
    # keeps a revisited output block in VMEM until the revisit chain ends)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, sk // bk, sq // bq),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, j, i: (0, 0)),        # seed
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # do
            pl.BlockSpec((1, bq, _LANES), lambda b, j, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, _LANES),
                         lambda b, j, i: (b, i, 0)),              # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(seed, q, k, v, do, lse_b, delta_b)
    dk = dk.astype(k.dtype)
    dv = dv.astype(v.dtype)

    dqk = functools.partial(
        _dq_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        kv_len=kv_len, offset=offset, dropout_p=dropout_p)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, sq // bq, sk // bk),
        in_specs=[
            pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)),         # seed
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),  # do
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, _LANES),
                         lambda b, i, j: (b, i, 0)),              # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), jnp.float32),
        interpret=_interpret(),
    )(seed, q, k, v, do, lse_b, delta_b).astype(q.dtype)
    seed_zero = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, seed_zero


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_core(q, k, v, seed, scale, causal, dropout_p, kv_len,
                          offset):
    out, _ = _flash_fwd(q, k, v, seed, scale, causal, dropout_p, kv_len,
                        offset)
    return out


def _core_fwd(q, k, v, seed, scale, causal, dropout_p, kv_len, offset):
    out, lse = _flash_fwd(q, k, v, seed, scale, causal, dropout_p, kv_len,
                          offset)
    return out, (q, k, v, seed, out, lse)


_flash_attention_core.defvjp(_core_fwd, _flash_bwd)


def _pad_seq(x, target):
    pad = target - x.shape[2]
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, dropout_p: float = 0.0,
                    training: bool = True, seed=None):
    """Fused attention over (batch, heads, seq, head_dim) inputs.

    Matches ``F.scaled_dot_product_attention(..., is_causal=causal)``
    numerics (bottom-right causal alignment) without materializing the
    (seq, seq) probabilities.  Ragged sequence lengths are auto-padded;
    ``dropout_p > 0`` stays on the fused path with an in-kernel
    counter-based mask (deterministic given ``seed``; when ``seed`` is
    None one is drawn from the framework RNG stream)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    enforce(k.shape == (b, h, sk, d) and v.shape == (b, h, sk, d),
            f"k/v shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if scale is None:
        scale = d ** -0.5
    if not training:
        dropout_p = 0.0
    if dropout_p > 0.0:
        if seed is None:
            # op_key() honors key_scope, so the per-step traced key (not a
            # trace-time constant) varies the mask across jitted steps
            from ..framework import random as fw_random
            seed = jax.random.randint(fw_random.op_key(), (), 0,
                                      np.iinfo(np.int32).max, jnp.int32)
        seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    else:
        seed_arr = jnp.zeros((1, 1), jnp.int32)
    sq_pad, sk_pad = _pad_to_legal(sq), _pad_to_legal(sk)
    qf = _pad_seq(q, sq_pad).reshape(b * h, sq_pad, d)
    kf = _pad_seq(k, sk_pad).reshape(b * h, sk_pad, d)
    vf = _pad_seq(v, sk_pad).reshape(b * h, sk_pad, d)
    out = _flash_attention_core(qf, kf, vf, seed_arr, float(scale),
                                bool(causal), float(dropout_p), sk,
                                sk - sq)
    return out.reshape(b, h, sq_pad, d)[:, :, :sq, :]


# ---------------------------------------------------------------------------
# Decode: single-step attention against a KV cache (reference CacheKV,
# fused_attention_op.cc:235) — memory-bound; the kernel streams only the
# cache blocks that hold real entries (dynamic trip count on cache_seqlen).
# ---------------------------------------------------------------------------
def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, scale, block_k):
    q = q_ref[0]                                          # (sq, d)
    kv_len = len_ref[0, 0]
    num_iter = (kv_len + block_k - 1) // block_k          # dynamic

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot(q, k, (((1,), (1,)), ((), ()))) * scale
        cols = j * block_k + lax.broadcasted_iota(
            jnp.int32, (q.shape[0], block_k), 1)
        s = jnp.where(cols < kv_len, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + _dot(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())))
        return m_new, l_new, acc_new

    m0 = jnp.full((q.shape[0],), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    m, l, acc = lax.fori_loop(0, num_iter, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention_kvcache(q, k_cache, v_cache, cache_seqlen,
                            scale: Optional[float] = None):
    """Decode-step attention: ``q`` (batch, heads, sq, head_dim) attends to
    ``k_cache/v_cache[:, :, :cache_seqlen]``.  ``cache_seqlen`` may be a
    traced scalar — the kernel's trip count is dynamic, so one compiled
    program serves every decode position (no per-step retrace)."""
    b, h, sq, d = q.shape
    smax = k_cache.shape[2]
    enforce(smax % 8 == 0,
            f"kv cache capacity {smax} must be a multiple of 8 "
            "(allocate the cache padded)")
    if scale is None:
        scale = d ** -0.5
    bk = min(_block_sizes(smax, smax)[1], smax)
    qf = q.reshape(b * h, sq, d)
    kf = k_cache.reshape(b * h, smax, d)
    vf = v_cache.reshape(b * h, smax, d)
    len_arr = jnp.asarray(cache_seqlen, jnp.int32).reshape(1, 1)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale), block_k=bk),
        grid=(b * h,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, sq, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, smax, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, smax, d), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, sq, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=_interpret(),
    )(len_arr, qf, kf, vf)
    return out.reshape(b, h, sq, d)
