"""Flash attention: the fused FMHA Pallas kernel.

Semantic reference: operators/fused/fused_attention_op.cc:221-357 FMHA path
(`FMHARef`, fused/fmha_ref.h:58 — QK^T, scale, mask, softmax, PV) and the
causal-mask fusion `fused_softmax_mask_upper_triangle_op.cu`.  The reference
materializes the (S, S) probability matrix in HBM; this kernel never does —
online softmax over KV blocks keeps everything in VMEM (the whole point of a
TPU-native rewrite: HBM bandwidth is the bottleneck, SURVEY §7 hard-part 2).

Layout: q, k, v are (batch, heads, seq, head_dim), flattened to
(batch*heads, seq, head_dim) for the kernel; grid = (batch*heads, q blocks);
each program streams this head's KV blocks with `fori_loop`, carrying the
running max/denominator (m, l) in fp32 — the standard flash recurrence.
Backward is recompute-based (no probability tensor saved): a dkdv kernel over
KV blocks and a dq kernel over Q blocks, both replaying p = exp(qk - lse).

Causal masking is block-skipped: programs never visit KV blocks strictly
above the diagonal, so the causal fwd does ~half the FLOPs — the fusion
`fused_softmax_mask_upper_triangle` only saves bandwidth, not compute.

dropout_p > 0 falls back to the XLA path (F.scaled_dot_product_attention):
attention-prob dropout requires in-kernel RNG which would pin the mask to
block layout; the training configs that matter (BASELINE #3/#4) run
attn dropout 0.  On non-TPU backends the kernel runs in interpret mode, so
the CPU test mesh exercises the same code path.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..framework.errors import enforce

_NEG_INF = -1e30

# Mosaic requires the last two block dims to be (multiple of 8, multiple of
# 128) or equal to the array dims, so per-row statistics (lse, delta) can't be
# 2D (bh, seq) blocks of shape (1, bq).  Like the upstream TPU flash kernel,
# they travel as (bh, seq, _LANES) with the value broadcast across the 128
# lanes; kernels slice lanes back down to the KV-block width elementwise.
_LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _stat_tile(x, width):
    """Widen a (rows, _LANES) lane-broadcast statistic to (rows, width)."""
    if width <= _LANES:
        return x[:, :width]
    assert width % _LANES == 0, (width, _LANES)
    return jnp.tile(x, (1, width // _LANES))


def _block_sizes(seq_q: int, seq_k: int):
    # swept on v5e at (8, 12, 2048, 64): 512/512 gives 2.5x over 128/128
    # (small blocks starve the MXU when the contraction dim is only 64).
    # Fall back to the largest power-of-two block that divides the sequence
    # so every multiple of 128 stays supported; the resulting widths are
    # always either <=128 or a multiple of _LANES, which _stat_tile needs.
    def pick(seq):
        for b in (512, 256, 128):
            if seq % b == 0:
                return b
        return seq
    return pick(seq_q), pick(seq_k)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k):
    qi = pl.program_id(1)
    # dots stay in the input dtype (bf16 on the fast path) with fp32
    # accumulation — casting inputs to fp32 would run the MXU at 1/4 rate
    q = q_ref[0]                                          # (bq, d)
    num_kv = seq_k // block_k
    if causal:
        # visit only blocks intersecting the lower triangle; queries are
        # bottom-right aligned against the key sequence (decode semantics,
        # matches F.scaled_dot_product_attention)
        offset = seq_k - q_ref.shape[1] * pl.num_programs(1)
        last = (offset + (qi + 1) * block_q + block_k - 1) // block_k
        num_iter = jnp.minimum(last, num_kv)
    else:
        offset = 0
        num_iter = num_kv

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = offset + qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = lax.fori_loop(0, num_iter, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log(l_safe)
    lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _flash_fwd(q, k, v, scale, causal):
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk)
    grid = (bh, sq // bq)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=bq, block_k=bk, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse[:, :, 0]  # keep the compact (bh, sq) form as residual


# ---------------------------------------------------------------------------
# Backward (recompute): dkdv over KV blocks, dq over Q blocks
# ---------------------------------------------------------------------------
def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, *, scale, causal, block_q, block_k, seq_q,
                 seq_k):
    kj = pl.program_id(1)
    k = k_ref[0]                                          # (bk, d)
    v = v_ref[0]
    num_q = seq_q // block_q
    if causal:
        offset = seq_k - seq_q
        start = jnp.maximum((kj * block_k - offset) // block_q, 0)
    else:
        offset = 0
        start = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        # lane-broadcast stats: every lane holds the row's value, so widening
        # to block_k lanes gives an elementwise-ready (bq, bk) tile
        lse = _stat_tile(lse_ref[0, pl.ds(i * block_q, block_q), :], block_k)
        delta = _stat_tile(
            delta_ref[0, pl.ds(i * block_q, block_q), :], block_k)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = offset + i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dv_new = dv + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_new = dk + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    z = jnp.zeros((block_k, k.shape[1]), jnp.float32)
    dk, dv = lax.fori_loop(start, num_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = _stat_tile(lse_ref[0], block_k)     # lane-broadcast → (bq, bk)
    delta = _stat_tile(delta_ref[0], block_k)
    num_kv = seq_k // block_k
    if causal:
        offset = seq_k - q_ref.shape[1] * pl.num_programs(1)
        last = (offset + (qi + 1) * q.shape[0] + block_k - 1) // block_k
        num_iter = jnp.minimum(last, num_kv)
    else:
        offset = 0
        num_iter = num_kv

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = offset + qi * q.shape[0] + lax.broadcasted_iota(
                jnp.int32, (q.shape[0], block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(
                jnp.int32, (q.shape[0], block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, num_iter, body,
                       jnp.zeros((q.shape[0], q.shape[1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(scale, causal, res, g):
    q, k, v, out, lse = res
    do = g
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = _block_sizes(sq, sk)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # broadcast per-row stats across lanes for Mosaic-legal block layouts
    lse_b = jnp.broadcast_to(lse[..., None], (bh, sq, _LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (bh, sq, _LANES))

    dkdv = functools.partial(
        _dkdv_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_q=sq, seq_k=sk)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, sk // bk),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),   # v
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),   # do
            pl.BlockSpec((1, sq, _LANES), lambda b, j: (b, 0, 0)),   # lse
            pl.BlockSpec((1, sq, _LANES), lambda b, j: (b, 0, 0)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse_b, delta_b)

    dqk = functools.partial(
        _dq_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_k=sk)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),   # q
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),   # k
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),   # do
            pl.BlockSpec((1, bq, _LANES), lambda b, i: (b, i, 0)),   # lse
            pl.BlockSpec((1, bq, _LANES), lambda b, i: (b, i, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_core(q, k, v, scale, causal):
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _core_fwd(q, k, v, scale, causal):
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


_flash_attention_core.defvjp(_core_fwd, _flash_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None, dropout_p: float = 0.0,
                    training: bool = True):
    """Fused attention over (batch, heads, seq, head_dim) inputs.

    Matches ``F.scaled_dot_product_attention(..., is_causal=causal)``
    numerics (bottom-right causal alignment) without materializing the
    (seq, seq) probabilities."""
    if dropout_p > 0.0 and training:
        # prob-dropout needs in-kernel RNG; XLA reference path handles it
        from ..nn import functional as F
        return F.scaled_dot_product_attention(
            q, k, v, is_causal=causal, dropout_p=dropout_p,
            training=training, scale=scale)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk)
    enforce(sq % bq == 0 and sk % bk == 0,
            f"flash_attention needs seq multiples of {bq}/{bk}; pad inputs "
            f"(got q={sq}, kv={sk})")
    if scale is None:
        scale = d ** -0.5
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    out = _flash_attention_core(qf, kf, vf, float(scale), bool(causal))
    return out.reshape(b, h, sq, d)
