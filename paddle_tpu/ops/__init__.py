"""paddle_tpu.ops — the fused-kernel family (reference C16:
paddle/fluid/operators/fused/).

Design stance (SURVEY §7): XLA auto-fuses the elementwise epilogues the
reference hand-writes in CUDA (bias+dropout+residual+LN —
fused_dropout_helper.h:110,207; GEMM epilogues — fused_gemm_epilogue_op.cu),
so those are thin compositions here and the compiler does the fusion.  The
kernels XLA can NOT derive — online-softmax flash attention — are hand-written
in Pallas (flash_attention.py).

``FLAGS_use_pallas_kernels`` (framework/flags.py) gates the Pallas paths;
with the flag off everything lowers through the jnp reference semantics.
"""
from ..framework import flags as _flags
from .flash_attention import (flash_attention,  # noqa: F401
                              flash_attention_kvcache)
from .fused import (fused_bias_dropout_residual_layer_norm,  # noqa: F401
                    fused_feedforward, rotary_position_embedding)
from .fused_block import (fused_attention_block,  # noqa: F401
                          fused_attention_block_kvcache, fused_block_route,
                          fused_ffn_block, fused_linear_residual,
                          fused_ln_linear)

__all__ = ["flash_attention", "fused_bias_dropout_residual_layer_norm",
           "fused_feedforward", "rotary_position_embedding",
           "fused_attention_block", "fused_attention_block_kvcache",
           "fused_ffn_block", "fused_ln_linear", "fused_linear_residual",
           "fused_block_route", "pallas_enabled"]


def pallas_enabled() -> bool:
    """True when the Pallas kernel family should be used."""
    try:
        return bool(_flags.get_flags()["use_pallas_kernels"])
    except Exception:
        return True
