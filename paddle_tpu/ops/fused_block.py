"""Block-level fused transformer execution (ISSUE 7).

Reference semantics: the fused_multi_transformer family — one CUDA op per
decoder block covering pre-LN → QKV GEMM → (rope) → FMHA → out-proj →
bias+dropout+residual, plus fused_feedforward for the MLP half
(operators/fused/fused_multi_transformer_op.cu, fused_attention_op.cc,
fused_feedforward_op.cc).  PAPERS.md backs the block-level ambition:
*ClusterFusion++* fuses whole-block decoding, *Neptune* shows
operator-fusion locality wins beyond what a compiler pass finds.

TPU-native layout of that idea.  The block is expressed as THREE Pallas
kernel surfaces chained under one op call per block half, each owning the
piece XLA cannot (or measurably does not) fuse on its own:

  attention half (``fused_attention_block``):
    [K1 ln_linear]   LN(x) @ W_qkv + b   — one read of x; the normalized
                     activations never round-trip HBM (VMEM scratch),
                     unlike the LN-then-GEMM pair XLA emits.
    [rope]           two multiplies against the lru-cached cos/sin tables
                     (ops/fused.py) — optional, GPT-NeoX formulation.
    [flash fwd/bwd]  the existing ops/flash_attention.py kernels, with
                     their in-kernel counter-hash attention dropout.
    [K2 epilogue]    attn @ W_out + b → dropout → +residual — the GEMM
                     epilogue and the residual add in one output pass.
  FFN half (``fused_ffn_block``):
    [K3 ffn]         LN → GEMM → act(+drop) → GEMM → drop → +residual as
                     ONE kernel: the (rows, ffn) intermediate lives only
                     as a VMEM tile per grid step, never in HBM.

Why the boundary sits here and not at "one kernel for the whole block":
the out-projection contracts over *all heads* while the flash grid is
one-head-per-program, so folding the epilogue into the attention kernel
would need cross-program reduction; chaining kernels keeps each at
O(block) VMEM residency (same argument as the flash bwd split).
docs/ARCHITECTURE.md "Fused block execution" has the full diagram.

Differentiation: every Pallas surface carries a ``jax.custom_vjp`` whose
backward is *recompute-based* — it replays the cheap jnp composition (two
extra GEMMs; XLA fuses those epilogues fine in backward) and, for the
attention segment, re-enters ``_flash_attention_core`` so the flash
dkdv/dq Pallas kernels do the heavy lifting.  Nothing beyond the residual
stream and the per-row lse is saved.

Dropout everywhere in the block is the counter-based hash of
ops/flash_attention.py (the reference's Philox-offset trick): the keep
mask for (salt, row, col) is a pure function of a traced int32 seed, so
forward, recompute-backward, and the interpret-mode oracle regenerate
bit-identical masks with zero HBM mask traffic — and the jnp reference
route is deterministic given the same seed (the cross-route parity and
dropout-determinism tests in tests/test_fused_block.py rely on this).

Routing (same pattern as inference/paged_attention.py): the Pallas route
on a real TPU, the pure-jnp reference route elsewhere — the reference IS
the tier-1/CPU default and the numerics oracle.  ``PTPU_FUSED_BLOCK=
pallas|reference`` forces a route; ``FLAGS_pallas_interpret_routing``
also forces the kernels (interpret mode) for cross-path tests.  Shapes a
Mosaic block can't tile (rows % 8, GEMM cols % 128) silently take the
reference route.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..amp import state as amp_state
from ..framework import random as fw_random
from ..framework.errors import enforce
from .flash_attention import (_NEG_INF, _dot, _interpret, _keep_mask,
                              flash_attention, flash_attention_kvcache)

__all__ = ["fused_ln_linear", "fused_linear_residual",
           "fused_attention_block", "fused_ffn_block",
           "fused_attention_block_kvcache", "fused_block_route"]

FUSED_BLOCK_ENV = "PTPU_FUSED_BLOCK"

# distinct dropout sub-streams per epilogue (the bh slot of the flash hash;
# attention itself salts with the real bh index)
_SALT_RESID = 0x52455344
_SALT_FFN1 = 0x46464E31
_SALT_FFN2 = 0x46464E32


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else x


def fused_block_route() -> str:
    """'pallas' or 'reference' — which implementation the fused-block ops
    take on this backend (before per-shape legality)."""
    # deliberate trace-time pin: the route IS part of the trace signature
    # (a retrace re-reads it; flipping mid-run is not supported)
    forced = os.environ.get(FUSED_BLOCK_ENV, "")  # noqa: trace — route pinned at trace time by design
    if forced in ("pallas", "reference"):
        return forced
    from ..framework import flags as _flags
    try:
        if not _flags.get_flag("use_pallas_kernels"):
            return "reference"
        if _flags.get_flag("pallas_interpret_routing"):
            return "pallas"
    except KeyError:
        pass  # flags not registered (minimal import) — fall to backend
    return "pallas" if jax.default_backend() == "tpu" else "reference"


def _pallas_ok(rows: int, *gemm_cols: int) -> bool:
    """Mosaic tiling legality for the block kernels: row blocks are
    sublane-aligned, every GEMM output/ffn column count tiles by 128."""
    return rows % 8 == 0 and all(c % 128 == 0 for c in gemm_cols)


def _pick_rows(n: int) -> int:
    for b in (256, 128, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return n


def _pick_cols(n: int) -> int:
    for b in (512, 256, 128):
        if n % b == 0:
            return b
    return n


def _seed_or_draw(seed, need: bool):
    """A traced int32 scalar seed for the hash-dropout streams; drawn from
    the framework RNG (key_scope-aware, so jitted steps vary it) when the
    caller didn't pass one."""
    if not need:
        return jnp.zeros((), jnp.int32)
    if seed is None:
        seed = jax.random.randint(fw_random.op_key(), (), 0,
                                  np.iinfo(np.int32).max, jnp.int32)
    return jnp.asarray(seed, jnp.int32)


def _hash_drop(y, seed, salt: int, p: float, rows=None, cols=None):
    """jnp rendering of the kernels' in-register dropout: keep(salt, row,
    col) from the flash counter hash, post-normalization 1/(1-p) rescale.
    ``y`` is (n, c); row/col default to global indices over y."""
    n, c = y.shape
    if rows is None:
        rows = lax.broadcasted_iota(jnp.int32, (n, c), 0)
    if cols is None:
        cols = lax.broadcasted_iota(jnp.int32, (n, c), 1)
    keep = _keep_mask(seed.astype(jnp.uint32), jnp.uint32(salt),
                      rows, cols, p)
    return jnp.where(keep, y / (1.0 - p), jnp.zeros((), y.dtype))


def _ln_f32(x, g, beta, epsilon: float):
    """LayerNorm in f32 (the oracle F.layer_norm math, amp-independent),
    returned in f32 — callers cast to the GEMM dtype."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + epsilon)
    if g is not None:
        y = y * g.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y


# ---------------------------------------------------------------------------
# K1: fused pre-LN + GEMM (the LN → QKV projection pair as one HBM pass)
# ---------------------------------------------------------------------------
def _ln_linear_kernel(x_ref, w_ref, b_ref, g_ref, beta_ref, o_ref, lnx_scr,
                      *, epsilon):
    # grid (row block, col block), cols innermost: the normalized row block
    # is computed once at j == 0 and served from VMEM scratch for every
    # column tile — x is read once, LN(x) never lands in HBM
    @pl.when(pl.program_id(1) == 0)
    def _ln():
        xf = x_ref[...].astype(jnp.float32)
        mean = jnp.mean(xf, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + epsilon)
        y = (y * g_ref[...].astype(jnp.float32)
             + beta_ref[...].astype(jnp.float32))
        lnx_scr[...] = y.astype(lnx_scr.dtype)

    o_ref[...] = (_dot(lnx_scr[...], w_ref[...], (((1,), (0,)), ((), ())))
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_linear_pallas(x, w, b, g, beta, epsilon):
    from jax.experimental.pallas import tpu as pltpu
    n, h = x.shape
    cols = w.shape[1]
    br, bc = _pick_rows(n), _pick_cols(cols)
    return pl.pallas_call(
        functools.partial(_ln_linear_kernel, epsilon=epsilon),
        grid=(n // br, cols // bc),
        in_specs=[
            pl.BlockSpec((br, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bc), lambda i, j: (0, j)),
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),
            pl.BlockSpec((1, h), lambda i, j: (0, 0)),
            pl.BlockSpec((1, h), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, bc), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, cols), w.dtype),
        scratch_shapes=[pltpu.VMEM((br, h), w.dtype)],
        interpret=_interpret(),
    )(x, w, b.reshape(1, -1), g.reshape(1, -1), beta.reshape(1, -1))


def _ln_linear_ref(x, w, b, g, beta, epsilon):
    y = _ln_f32(x, g, beta, epsilon).astype(w.dtype)
    return jnp.matmul(y, w) + b.astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _ln_linear_p(x, w, b, g, beta, epsilon):
    return _ln_linear_pallas(x, w, b, g, beta, epsilon)


def _ln_linear_p_fwd(x, w, b, g, beta, epsilon):
    return _ln_linear_p(x, w, b, g, beta, epsilon), (x, w, b, g, beta)


def _ln_linear_p_bwd(epsilon, res, gout):
    x, w, b, g, beta = res
    # recompute-based: two GEMMs + the LN chain rule, all XLA-fused
    _, vjp = jax.vjp(
        lambda x_, w_, b_, g_, bb_: _ln_linear_ref(x_, w_, b_, g_, bb_,
                                                   epsilon),
        x, w, b, g, beta)
    return vjp(gout)


_ln_linear_p.defvjp(_ln_linear_p_fwd, _ln_linear_p_bwd)


def fused_ln_linear(x, w, b, ln_scale, ln_bias, *, epsilon: float = 1e-5):
    """``LN(x) @ w + b`` over the last dim of ``x`` — the pre-LN + QKV
    (or pre-LN + fc_in) pair as one kernel pass.  LN runs in f32 on the
    raw activations; the GEMM runs in the AMP dtype (one Pallas kernel on
    TPU, the jnp composition elsewhere)."""
    x, w = _arr(x), _arr(w)
    b, g, beta = _arr(b), _arr(ln_scale), _arr(ln_bias)
    _, w = amp_state.cast_for_op("linear", x, w)
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    if (fused_block_route() == "pallas"
            and _pallas_ok(x2.shape[0], w.shape[1])):
        out = _ln_linear_p(x2, w, b, g, beta, float(epsilon))
    else:
        out = _ln_linear_ref(x2, w, b, g, beta, float(epsilon))
    return out.reshape(shape[:-1] + (w.shape[1],))


# ---------------------------------------------------------------------------
# K2: GEMM epilogue — y @ W + b → dropout → + residual in one output pass
# ---------------------------------------------------------------------------
def _linear_residual_kernel(seed_ref, x_ref, w_ref, b_ref, r_ref, o_ref, *,
                            dropout_p, salt, block_r, block_c):
    y = (_dot(x_ref[...], w_ref[...], (((1,), (0,)), ((), ())))
         + b_ref[...].astype(jnp.float32))
    if dropout_p > 0.0:
        i, j = pl.program_id(0), pl.program_id(1)
        rows = i * block_r + lax.broadcasted_iota(
            jnp.int32, (block_r, block_c), 0)
        cols = j * block_c + lax.broadcasted_iota(
            jnp.int32, (block_r, block_c), 1)
        keep = _keep_mask(seed_ref[0, 0].astype(jnp.uint32),
                          jnp.uint32(salt), rows, cols, dropout_p)
        y = jnp.where(keep, y / (1.0 - dropout_p), 0.0)
    o_ref[...] = (r_ref[...].astype(jnp.float32) + y).astype(o_ref.dtype)


def _linear_residual_pallas(x, w, b, r, seed, dropout_p, salt):
    n, k = x.shape
    cols = w.shape[1]
    br, bc = _pick_rows(n), _pick_cols(cols)
    return pl.pallas_call(
        functools.partial(_linear_residual_kernel, dropout_p=dropout_p,
                          salt=salt, block_r=br, block_c=bc),
        grid=(n // br, cols // bc),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),     # seed
            pl.BlockSpec((br, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bc), lambda i, j: (0, j)),
            pl.BlockSpec((1, bc), lambda i, j: (0, j)),
            pl.BlockSpec((br, bc), lambda i, j: (i, j)),   # residual
        ],
        out_specs=pl.BlockSpec((br, bc), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n, cols), r.dtype),
        interpret=_interpret(),
    )(seed.reshape(1, 1), x, w, b.reshape(1, -1), r)


def _linear_residual_ref(x, w, b, r, seed, dropout_p, salt):
    y = (jnp.matmul(x, w).astype(jnp.float32) + b.astype(jnp.float32))
    if dropout_p > 0.0:
        y = _hash_drop(y, seed, salt, dropout_p)
    return (r.astype(jnp.float32) + y).astype(r.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _linear_residual_p(x, w, b, r, seed, dropout_p, salt):
    return _linear_residual_pallas(x, w, b, r, seed, dropout_p, salt)


def _linear_residual_p_fwd(x, w, b, r, seed, dropout_p, salt):
    out = _linear_residual_p(x, w, b, r, seed, dropout_p, salt)
    return out, (x, w, b, r, seed)


def _linear_residual_p_bwd(dropout_p, salt, res, gout):
    x, w, b, r, seed = res
    _, vjp = jax.vjp(
        lambda x_, w_, b_, r_: _linear_residual_ref(x_, w_, b_, r_, seed,
                                                    dropout_p, salt),
        x, w, b, r)
    dx, dw, db, dr = vjp(gout)
    return dx, dw, db, dr, np.zeros(seed.shape, jax.dtypes.float0)


_linear_residual_p.defvjp(_linear_residual_p_fwd, _linear_residual_p_bwd)


def fused_linear_residual(x, w, b, residual, *, dropout_p: float = 0.0,
                          training: bool = True, seed=None,
                          salt: int = _SALT_RESID):
    """``residual + dropout(x @ w + b)`` — the out-projection epilogue of
    the reference's fused_attention_op (bias+dropout+residual) with the
    hash-dropout mask regenerated in backward instead of stored."""
    x, w = _arr(x), _arr(w)
    b, residual = _arr(b), _arr(residual)
    x, w = amp_state.cast_for_op("linear", x, w)
    if not training:
        dropout_p = 0.0
    seed = _seed_or_draw(seed, dropout_p > 0.0)
    shape = residual.shape
    x2 = x.reshape(-1, x.shape[-1])
    r2 = residual.reshape(-1, shape[-1])
    if (fused_block_route() == "pallas"
            and _pallas_ok(x2.shape[0], w.shape[1])):
        out = _linear_residual_p(x2, w, b, r2, seed, float(dropout_p),
                                 int(salt))
    else:
        out = _linear_residual_ref(x2, w, b, r2, seed, float(dropout_p),
                                   int(salt))
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# K3: the FFN half as ONE kernel — LN → GEMM → act(+drop) → GEMM → drop →
# + residual; the (rows, ffn) intermediate exists only as a VMEM tile
# ---------------------------------------------------------------------------
def _ffn_kernel(seed_ref, x_ref, w1_ref, b1_ref, w2_ref, b2_ref, g_ref,
                beta_ref, o_ref, lnx_scr, acc_scr, *, epsilon, activation,
                dropout1, dropout2, block_r, block_f):
    i, j = pl.program_id(0), pl.program_id(1)
    nf = pl.num_programs(1)
    seed = seed_ref[0, 0].astype(jnp.uint32)

    @pl.when(j == 0)
    def _init():
        xf = x_ref[...].astype(jnp.float32)
        mean = jnp.mean(xf, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + epsilon)
        y = (y * g_ref[...].astype(jnp.float32)
             + beta_ref[...].astype(jnp.float32))
        lnx_scr[...] = y.astype(lnx_scr.dtype)
        acc_scr[...] = jnp.zeros_like(acc_scr[...])

    h = (_dot(lnx_scr[...], w1_ref[...], (((1,), (0,)), ((), ())))
         + b1_ref[...].astype(jnp.float32))
    h = jax.nn.gelu(h, approximate=False) if activation == "gelu" \
        else jnp.maximum(h, 0.0)
    if dropout1 > 0.0:
        rows = i * block_r + lax.broadcasted_iota(
            jnp.int32, (block_r, block_f), 0)
        cols = j * block_f + lax.broadcasted_iota(
            jnp.int32, (block_r, block_f), 1)
        keep = _keep_mask(seed, jnp.uint32(_SALT_FFN1), rows, cols, dropout1)
        h = jnp.where(keep, h / (1.0 - dropout1), 0.0)
    acc_scr[...] += _dot(h.astype(w2_ref.dtype), w2_ref[...],
                         (((1,), (0,)), ((), ())))

    @pl.when(j == nf - 1)
    def _finalize():
        y = acc_scr[...] + b2_ref[...].astype(jnp.float32)
        if dropout2 > 0.0:
            hcols = y.shape[1]
            rows = i * block_r + lax.broadcasted_iota(
                jnp.int32, (block_r, hcols), 0)
            cols = lax.broadcasted_iota(jnp.int32, (block_r, hcols), 1)
            keep = _keep_mask(seed, jnp.uint32(_SALT_FFN2), rows, cols,
                              dropout2)
            y = jnp.where(keep, y / (1.0 - dropout2), 0.0)
        o_ref[...] = (x_ref[...].astype(jnp.float32) + y).astype(o_ref.dtype)


def _ffn_pallas(x, w1, b1, w2, b2, g, beta, seed, activation, dropout1,
                dropout2, epsilon):
    from jax.experimental.pallas import tpu as pltpu
    n, h = x.shape
    ffn = w1.shape[1]
    br = min(_pick_rows(n), 128)   # x + lnx + acc + both weight tiles ≤ VMEM
    bf = _pick_cols(ffn)
    return pl.pallas_call(
        functools.partial(_ffn_kernel, epsilon=epsilon,
                          activation=activation, dropout1=dropout1,
                          dropout2=dropout2, block_r=br, block_f=bf),
        grid=(n // br, ffn // bf),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),     # seed
            pl.BlockSpec((br, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bf), lambda i, j: (0, j)),
            pl.BlockSpec((1, bf), lambda i, j: (0, j)),
            pl.BlockSpec((bf, h), lambda i, j: (j, 0)),
            pl.BlockSpec((1, h), lambda i, j: (0, 0)),
            pl.BlockSpec((1, h), lambda i, j: (0, 0)),     # g
            pl.BlockSpec((1, h), lambda i, j: (0, 0)),     # beta
        ],
        # revisited across j; written once at the last ffn tile
        out_specs=pl.BlockSpec((br, h), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((br, h), w1.dtype),                 # LN(x)
            pltpu.VMEM((br, h), jnp.float32),              # W2 accumulator
        ],
        interpret=_interpret(),
    )(seed.reshape(1, 1), x, w1, b1.reshape(1, -1), w2, b2.reshape(1, -1),
      g.reshape(1, -1), beta.reshape(1, -1))


def _ffn_ref(x, w1, b1, w2, b2, g, beta, seed, activation, dropout1,
             dropout2, epsilon):
    lnx = _ln_f32(x, g, beta, epsilon).astype(w1.dtype)
    h = (jnp.matmul(lnx, w1).astype(jnp.float32)
         + b1.astype(jnp.float32))
    h = jax.nn.gelu(h, approximate=False) if activation == "gelu" \
        else jnp.maximum(h, 0.0)
    if dropout1 > 0.0:
        h = _hash_drop(h, seed, _SALT_FFN1, dropout1)
    y = (jnp.matmul(h.astype(w2.dtype), w2).astype(jnp.float32)
         + b2.astype(jnp.float32))
    if dropout2 > 0.0:
        y = _hash_drop(y, seed, _SALT_FFN2, dropout2)
    return (x.astype(jnp.float32) + y).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def _ffn_p(x, w1, b1, w2, b2, g, beta, seed, activation, dropout1,
           dropout2, epsilon):
    return _ffn_pallas(x, w1, b1, w2, b2, g, beta, seed, activation,
                       dropout1, dropout2, epsilon)


def _ffn_p_fwd(x, w1, b1, w2, b2, g, beta, seed, activation, dropout1,
               dropout2, epsilon):
    out = _ffn_p(x, w1, b1, w2, b2, g, beta, seed, activation, dropout1,
                 dropout2, epsilon)
    return out, (x, w1, b1, w2, b2, g, beta, seed)


def _ffn_p_bwd(activation, dropout1, dropout2, epsilon, res, gout):
    x, w1, b1, w2, b2, g, beta, seed = res
    _, vjp = jax.vjp(
        lambda *a: _ffn_ref(*a, seed, activation, dropout1, dropout2,
                            epsilon),
        x, w1, b1, w2, b2, g, beta)
    return vjp(gout) + (np.zeros(seed.shape, jax.dtypes.float0),)


_ffn_p.defvjp(_ffn_p_fwd, _ffn_p_bwd)


def fused_ffn_block(x, w1, b1, w2, b2, ln_scale, ln_bias, *,
                    activation: str = "gelu", dropout1: float = 0.0,
                    dropout2: float = 0.0, epsilon: float = 1e-5,
                    training: bool = True, seed=None):
    """The FFN half of a pre-LN decoder block as one fused op:

        out = x + drop2(W2 · act(drop1(W1 · LN(x) + b1)) + b2)

    One Pallas kernel on TPU (the (rows, ffn) intermediate never touches
    HBM); the jnp composition elsewhere.  ``activation`` ∈ {gelu, relu}."""
    enforce(activation in ("gelu", "relu"),
            f"fused_ffn_block: unsupported activation {activation!r}")
    x = _arr(x)
    w1, b1, w2, b2 = map(_arr, (w1, b1, w2, b2))
    g, beta = _arr(ln_scale), _arr(ln_bias)
    _, w1 = amp_state.cast_for_op("linear", x, w1)
    _, w2 = amp_state.cast_for_op("linear", x, w2)
    if not training:
        dropout1 = dropout2 = 0.0
    seed = _seed_or_draw(seed, dropout1 > 0.0 or dropout2 > 0.0)
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    if (fused_block_route() == "pallas"
            and _pallas_ok(x2.shape[0], w1.shape[1], w2.shape[1])):
        out = _ffn_p(x2, w1, b1, w2, b2, g, beta, seed, activation,
                     float(dropout1), float(dropout2), float(epsilon))
    else:
        out = _ffn_ref(x2, w1, b1, w2, b2, g, beta, seed, activation,
                       float(dropout1), float(dropout2), float(epsilon))
    return out.reshape(shape)


# ---------------------------------------------------------------------------
# The attention half: K1 → rope → flash → K2 under one op call
# ---------------------------------------------------------------------------
def _split_heads(qkv, b, s, num_heads, head_dim):
    """(N, 3h) → q, k, v as (b, s, heads, d) — head-major column order,
    mirroring GPTAttention's fused-dim factorization."""
    qkv = qkv.reshape(b, s, num_heads, 3, head_dim)
    return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]


def _apply_rope(q, k, base: float):
    """GPT-NeoX rope on (b, s, heads, d) from the lru-cached tables —
    two multiplies per tensor at trace time (ops/fused.py satellite)."""
    from .fused import _rope_tables
    s, d = q.shape[1], q.shape[-1]
    cos, sin = _rope_tables(s, d, float(base))
    cs = cos[None, :, None, :]
    sn = sin[None, :, None, :]

    def rot(x):
        d2 = d // 2
        x1 = x[..., :d2].astype(jnp.float32)
        x2 = x[..., d2:].astype(jnp.float32)
        return jnp.concatenate(
            [x1 * cs - x2 * sn, x2 * cs + x1 * sn], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def _attention_ref(q, k, v, scale, causal, dropout_p, seed):
    """jnp attention in (b, s, heads, d) layout — no transposes, hash
    attention-dropout with the flash kernels' exact (bh, row, col)
    indexing so both routes agree given one seed."""
    b, s, nh, _ = q.shape
    scores = (jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale).astype(
        jnp.float32)
    if causal:
        rows = lax.broadcasted_iota(jnp.int32, (s, s), 0)
        cols = lax.broadcasted_iota(jnp.int32, (s, s), 1)
        scores = jnp.where((rows >= cols)[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0:
        bh = lax.broadcasted_iota(jnp.int32, (b, nh, 1, 1), 0) * nh \
            + lax.broadcasted_iota(jnp.int32, (b, nh, 1, 1), 1)
        rows = lax.broadcasted_iota(jnp.int32, (1, 1, s, 1), 2)
        cols = lax.broadcasted_iota(jnp.int32, (1, 1, 1, s), 3)
        keep = _keep_mask(seed.astype(jnp.uint32), bh, rows, cols,
                          dropout_p)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def fused_attention_block(x, qkv_w, qkv_b, out_w, out_b, ln_scale, ln_bias,
                          *, num_heads: int, causal: bool = True,
                          epsilon: float = 1e-5, attn_dropout: float = 0.0,
                          hidden_dropout: float = 0.0, rotary: bool = False,
                          rope_base: float = 10000.0,
                          scale: Optional[float] = None,
                          training: bool = True, seed=None):
    """The attention half of a pre-LN decoder block as one fused op:

        out = x + drop(W_out · FMHA(rope?(split(W_qkv · LN(x) + b))) + b)

    On TPU this chains the K1 ln_linear kernel, the flash-attention Pallas
    kernel (in-kernel attention dropout), and the K2 epilogue kernel; each
    segment's custom_vjp recomputes through the flash bwd kernels, so the
    only saved activations are the residual stream and the flash lse.
    Off-TPU the pure-jnp composition (same hash-dropout streams) runs —
    the tier-1 oracle.  ``qkv_w`` is (h, 3h) in head-major column order
    (head0: q|k|v, head1: …), the GPTAttention layout."""
    x = _arr(x)
    b, s, hidden = x.shape
    enforce(hidden % num_heads == 0,
            f"hidden {hidden} not divisible by num_heads {num_heads}")
    head_dim = hidden // num_heads
    if scale is None:
        scale = head_dim ** -0.5
    if not training:
        attn_dropout = hidden_dropout = 0.0
    seed = _seed_or_draw(seed, attn_dropout > 0.0 or hidden_dropout > 0.0)

    qkv = fused_ln_linear(x, qkv_w, qkv_b, ln_scale, ln_bias,
                          epsilon=epsilon)
    q, k, v = _split_heads(qkv.reshape(b * s, -1), b, s, num_heads,
                           head_dim)
    if rotary:
        q, k = _apply_rope(q, k, rope_base)

    use_flash = (fused_block_route() == "pallas"
                 and head_dim % 8 == 0 and s % 8 == 0)
    if use_flash:
        out = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, scale=scale,
            dropout_p=attn_dropout, training=training, seed=seed)
        out = out.transpose(0, 2, 1, 3)
    else:
        out = _attention_ref(q, k, v, scale, causal, attn_dropout, seed)

    return fused_linear_residual(
        out.reshape(b, s, hidden), out_w, out_b, x,
        dropout_p=hidden_dropout, training=training, seed=seed,
        salt=_SALT_RESID)


def fused_attention_block_kvcache(x, qkv_w, qkv_b, out_w, out_b, ln_scale,
                                  ln_bias, k_buf, v_buf, used, *,
                                  num_heads: int, epsilon: float = 1e-5,
                                  scale: Optional[float] = None,
                                  rotary: bool = False,
                                  rope_base: float = 10000.0):
    """Decode-step rendering of :func:`fused_attention_block` against a
    fixed-shape KV cache (reference CacheKV / fused_multi_transformer
    decode): fused LN→QKV, cache write at ``used``, streaming cache
    attention (the flash decode kernel on TPU — dynamic trip count, one
    compile for every position), fused out-proj+residual.  Inference-only
    (no dropout).  Returns ``(out, k_buf, v_buf)``."""
    x = _arr(x)
    b, s, hidden = x.shape
    head_dim = hidden // num_heads
    if scale is None:
        scale = head_dim ** -0.5
    qkv = fused_ln_linear(x, qkv_w, qkv_b, ln_scale, ln_bias,
                          epsilon=epsilon)
    q, k, v = _split_heads(qkv.reshape(b * s, -1), b, s, num_heads,
                           head_dim)
    if rotary:
        q, k = _apply_rope(q, k, rope_base)
    q = q.transpose(0, 2, 1, 3)                       # (b, heads, s, d)
    k_buf = lax.dynamic_update_slice(
        k_buf, k.transpose(0, 2, 1, 3).astype(k_buf.dtype), (0, 0, used, 0))
    v_buf = lax.dynamic_update_slice(
        v_buf, v.transpose(0, 2, 1, 3).astype(v_buf.dtype), (0, 0, used, 0))
    L = k_buf.shape[2]
    if (fused_block_route() == "pallas" and s == 1 and L % 8 == 0
            and head_dim % 8 == 0):
        out = flash_attention_kvcache(q, k_buf, v_buf, used + 1,
                                      scale=scale)
    else:
        rows = used + jnp.arange(s)
        cols = jnp.arange(L)
        scores = (jnp.einsum("bhqd,bhkd->bhqk", q, k_buf)
                  * scale).astype(jnp.float32)
        valid = cols[None, :] <= rows[:, None]
        scores = jnp.where(valid[None, None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(v_buf.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v_buf)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, hidden)
    y = fused_linear_residual(out, out_w, out_b, x, dropout_p=0.0,
                              training=False)
    return y, k_buf, v_buf
