"""Declarative op-spec registry — the api.yaml analog (component C12).

Reference: python/paddle/utils/code_gen/api.yaml (228 `api:` entries, each
declaring args/output/infer_meta/kernel/backward) feeding api_gen.py and the
eager codegen (SURVEY A6).  On TPU there is no kernel table to generate —
jax.numpy IS the kernel substrate — but the yaml's other role survives: ONE
source of truth for the public op surface that drives parity tests (OpTest
sweep over every entry, tests/test_op_registry.py), the API inventory
(``api_table()``), and grad coverage.

Each OpSpec carries the public callable, a pure-numpy reference, a sample
input generator, and tolerance/grad metadata.  Registering an op here is
what makes it part of the tested API contract.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["OpSpec", "register_op", "registry", "api_table"]


@dataclasses.dataclass
class OpSpec:
    name: str                      # dotted public path under paddle_tpu
    fn: Callable                   # the framework op
    ref: Callable                  # numpy reference implementation
    sample: Callable               # rng -> tuple of np args
    grad_wrt: Tuple[int, ...] = (0,)   # args to grad-check (() = skip)
    rtol: float = 1e-5
    atol: float = 1e-6
    grad_rtol: float = 5e-3
    grad_atol: float = 5e-4


_REGISTRY: List[OpSpec] = []


def register_op(spec: OpSpec) -> OpSpec:
    _REGISTRY.append(spec)
    return spec


def registry() -> List[OpSpec]:
    if not _REGISTRY:
        _populate()
    return list(_REGISTRY)


def api_table() -> str:
    """Markdown inventory of the registered public op surface."""
    lines = ["| op | grad-checked |", "|---|---|"]
    for s in registry():
        lines.append(f"| `paddle_tpu.{s.name}` | "
                     f"{'yes' if s.grad_wrt else 'n/a'} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Registration corpus
# ---------------------------------------------------------------------------
def _r(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


def _pos(rng, *shape):
    return (np.abs(rng.randn(*shape)) + 0.5).astype(np.float32)


def _populate() -> None:
    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F

    def unary(name, fn, ref, sample=lambda rng: (_r(rng, 3, 4),), **kw):
        register_op(OpSpec(name=name, fn=fn, ref=ref, sample=sample, **kw))

    def binary(name, fn, ref, sample=None, **kw):
        sample = sample or (lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)))
        register_op(OpSpec(name=name, fn=fn, ref=ref, sample=sample,
                           grad_wrt=kw.pop("grad_wrt", (0, 1)), **kw))

    # -- math unary (reference tensor/math.py ≙ phi unary kernels) --------
    unary("exp", pt.exp, np.exp)
    unary("log", pt.log, np.log, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("log1p", pt.log1p, np.log1p,
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("sqrt", pt.sqrt, np.sqrt, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("rsqrt", pt.rsqrt, lambda x: 1.0 / np.sqrt(x),
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("square", pt.square, np.square)
    unary("abs", pt.abs, np.abs)
    unary("sin", pt.sin, np.sin)
    unary("cos", pt.cos, np.cos)
    unary("tanh", pt.tanh, np.tanh)
    unary("sigmoid", pt.sigmoid, lambda x: 1 / (1 + np.exp(-x)))
    unary("erf", pt.erf,
          lambda x: np.vectorize(_erf_scalar)(x).astype(np.float64))
    unary("floor", pt.floor, np.floor, grad_wrt=())
    unary("ceil", pt.ceil, np.ceil, grad_wrt=())
    unary("round", pt.round, np.round, grad_wrt=())
    unary("sign", pt.sign, np.sign, grad_wrt=())
    unary("reciprocal", pt.reciprocal, lambda x: 1.0 / x,
          sample=lambda rng: (_pos(rng, 3, 4),))

    # -- math binary (broadcasting included) ------------------------------
    binary("add", pt.add, np.add)
    binary("subtract", pt.subtract, np.subtract)
    binary("multiply", pt.multiply, np.multiply)
    binary("divide", pt.divide, np.divide,
           sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 3, 4)))
    binary("maximum", pt.maximum, np.maximum)
    binary("minimum", pt.minimum, np.minimum)
    binary("pow", pt.pow, np.power,
           sample=lambda rng: (_pos(rng, 3, 4), np.float32(2.0)),
           grad_wrt=(0,))
    binary("atan2", pt.atan2, np.arctan2)
    binary("broadcast_add", pt.add, np.add,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 1, 4)))

    # -- reductions -------------------------------------------------------
    unary("sum", pt.sum, np.sum, sample=lambda rng: (_r(rng, 3, 4),))
    unary("mean", pt.mean, np.mean)
    unary("max", pt.max, np.max, grad_wrt=())
    unary("min", pt.min, np.min, grad_wrt=())
    unary("prod", pt.prod, np.prod,
          sample=lambda rng: (_pos(rng, 2, 3),))
    register_op(OpSpec(
        name="sum.axis", fn=lambda x: __import__("paddle_tpu").sum(
            x, axis=1, keepdim=True),
        ref=lambda x: np.sum(x, axis=1, keepdims=True),
        sample=lambda rng: (_r(rng, 3, 4),)))

    # -- linalg -----------------------------------------------------------
    register_op(OpSpec(
        name="matmul", fn=pt.matmul, ref=np.matmul,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)),
        grad_wrt=(0, 1), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.linear",
        fn=lambda x, w, b: F.linear(x, w, b),
        ref=lambda x, w, b: x @ w + b,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5), _r(rng, 5)),
        grad_wrt=(0, 1, 2), rtol=2e-5, atol=2e-5))

    # -- activations (nn/functional ≙ phi activation kernels) -------------
    unary("nn.functional.relu", F.relu, lambda x: np.maximum(x, 0))
    unary("nn.functional.gelu", F.gelu,
          lambda x: 0.5 * x * (1 + np.vectorize(_erf_scalar)(
              x / np.sqrt(2.0))), rtol=2e-5, atol=2e-5)
    unary("nn.functional.silu", F.silu,
          lambda x: x / (1 + np.exp(-x)))
    unary("nn.functional.softmax",
          lambda x: F.softmax(x, axis=-1), _np_softmax)
    unary("nn.functional.log_softmax",
          lambda x: F.log_softmax(x, axis=-1),
          lambda x: np.log(_np_softmax(x)))
    unary("nn.functional.leaky_relu",
          lambda x: F.leaky_relu(x, negative_slope=0.1),
          lambda x: np.where(x >= 0, x, 0.1 * x))
    unary("nn.functional.hardswish", F.hardswish,
          lambda x: x * np.clip(x + 3, 0, 6) / 6, grad_rtol=2e-2,
          grad_atol=2e-3)

    # -- norm layers (functional form) ------------------------------------
    register_op(OpSpec(
        name="nn.functional.layer_norm",
        fn=lambda x, w, b: F.layer_norm(x, (4,), weight=w, bias=b,
                                        epsilon=1e-5),
        ref=lambda x, w, b: _np_layer_norm(x, w, b, 1e-5),
        sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 4), _r(rng, 4)),
        grad_wrt=(0, 1, 2), rtol=2e-5, atol=2e-5))

    # -- losses -----------------------------------------------------------
    register_op(OpSpec(
        name="nn.functional.cross_entropy",
        fn=lambda lg, lb: F.cross_entropy(lg, lb, reduction="mean"),
        ref=lambda lg, lb: -np.mean(
            np.log(_np_softmax(lg))[np.arange(lg.shape[0]), lb]),
        sample=lambda rng: (_r(rng, 6, 10),
                            rng.randint(0, 10, (6,)).astype(np.int32)),
        grad_wrt=(0,), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.mse_loss",
        fn=lambda a, b: F.mse_loss(a, b),
        ref=lambda a, b: np.mean((a - b) ** 2),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(0, 1)))

    # -- shape ops --------------------------------------------------------
    register_op(OpSpec(
        name="concat", fn=lambda a, b: pt.concat([a, b], axis=1),
        ref=lambda a, b: np.concatenate([a, b], axis=1),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 2)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="transpose", fn=lambda x: pt.transpose(x, (1, 0)),
        ref=lambda x: x.T, sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="reshape", fn=lambda x: pt.reshape(x, (4, 3)),
        ref=lambda x: x.reshape(4, 3),
        sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="clip", fn=lambda x: pt.clip(x, -0.5, 0.5),
        ref=lambda x: np.clip(x, -0.5, 0.5),
        sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="where", fn=lambda c, a, b: pt.where(c, a, b),
        ref=np.where,
        sample=lambda rng: (rng.rand(3, 4) > 0.5, _r(rng, 3, 4),
                            _r(rng, 3, 4)),
        grad_wrt=(1, 2)))
    register_op(OpSpec(
        name="gather",
        fn=lambda x, i: pt.gather(x, i, axis=0),
        ref=lambda x, i: np.take(x, i, axis=0),
        sample=lambda rng: (_r(rng, 5, 4),
                            rng.randint(0, 5, (3,)).astype(np.int32)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="cumsum", fn=lambda x: pt.cumsum(x, axis=1),
        ref=lambda x: np.cumsum(x, axis=1),
        sample=lambda rng: (_r(rng, 3, 4),)))


def _erf_scalar(x: float) -> float:
    import math
    return math.erf(float(x))


def _np_softmax(x):
    e = np.exp(x - np.max(x, axis=-1, keepdims=True))
    return e / np.sum(e, axis=-1, keepdims=True)


def _np_layer_norm(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b
