"""Declarative op-spec registry — the api.yaml analog (component C12).

Reference: python/paddle/utils/code_gen/api.yaml (228 `api:` entries, each
declaring args/output/infer_meta/kernel/backward) feeding api_gen.py and the
eager codegen (SURVEY A6).  On TPU there is no kernel table to generate —
jax.numpy IS the kernel substrate — but the yaml's other role survives: ONE
source of truth for the public op surface that drives parity tests (OpTest
sweep over every entry, tests/test_op_registry.py), the API inventory
(``api_table()``), and grad coverage.

Each OpSpec carries the public callable, a pure-numpy reference, a sample
input generator, and tolerance/grad metadata.  Registering an op here is
what makes it part of the tested API contract.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["OpSpec", "register_op", "registry", "api_table"]


@dataclasses.dataclass
class OpSpec:
    name: str                      # dotted public path under paddle_tpu
    fn: Callable                   # the framework op
    ref: Callable                  # numpy reference implementation
    sample: Callable               # rng -> tuple of np args
    grad_wrt: Tuple[int, ...] = (0,)   # args to grad-check (() = skip)
    rtol: float = 1e-5
    atol: float = 1e-6
    grad_rtol: float = 5e-3
    grad_atol: float = 5e-4


_REGISTRY: List[OpSpec] = []


def register_op(spec: OpSpec) -> OpSpec:
    _REGISTRY.append(spec)
    return spec


def registry() -> List[OpSpec]:
    if not _REGISTRY:
        _populate()
    return list(_REGISTRY)


def api_table() -> str:
    """Markdown inventory of the registered public op surface."""
    lines = ["| op | grad-checked |", "|---|---|"]
    for s in registry():
        lines.append(f"| `paddle_tpu.{s.name}` | "
                     f"{'yes' if s.grad_wrt else 'n/a'} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Registration corpus
# ---------------------------------------------------------------------------
def _r(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


def _pos(rng, *shape):
    return (np.abs(rng.randn(*shape)) + 0.5).astype(np.float32)


def _populate() -> None:
    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F

    def unary(name, fn, ref, sample=lambda rng: (_r(rng, 3, 4),), **kw):
        register_op(OpSpec(name=name, fn=fn, ref=ref, sample=sample, **kw))

    def binary(name, fn, ref, sample=None, **kw):
        sample = sample or (lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)))
        register_op(OpSpec(name=name, fn=fn, ref=ref, sample=sample,
                           grad_wrt=kw.pop("grad_wrt", (0, 1)), **kw))

    # -- math unary (reference tensor/math.py ≙ phi unary kernels) --------
    unary("exp", pt.exp, np.exp)
    unary("log", pt.log, np.log, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("log1p", pt.log1p, np.log1p,
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("sqrt", pt.sqrt, np.sqrt, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("rsqrt", pt.rsqrt, lambda x: 1.0 / np.sqrt(x),
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("square", pt.square, np.square)
    unary("abs", pt.abs, np.abs)
    unary("sin", pt.sin, np.sin)
    unary("cos", pt.cos, np.cos)
    unary("tanh", pt.tanh, np.tanh)
    unary("sigmoid", pt.sigmoid, lambda x: 1 / (1 + np.exp(-x)))
    unary("erf", pt.erf,
          lambda x: np.vectorize(_erf_scalar)(x).astype(np.float64))
    unary("floor", pt.floor, np.floor, grad_wrt=())
    unary("ceil", pt.ceil, np.ceil, grad_wrt=())
    unary("round", pt.round, np.round, grad_wrt=())
    unary("sign", pt.sign, np.sign, grad_wrt=())
    unary("reciprocal", pt.reciprocal, lambda x: 1.0 / x,
          sample=lambda rng: (_pos(rng, 3, 4),))

    # -- math binary (broadcasting included) ------------------------------
    binary("add", pt.add, np.add)
    binary("subtract", pt.subtract, np.subtract)
    binary("multiply", pt.multiply, np.multiply)
    binary("divide", pt.divide, np.divide,
           sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 3, 4)))
    binary("maximum", pt.maximum, np.maximum)
    binary("minimum", pt.minimum, np.minimum)
    binary("pow", pt.pow, np.power,
           sample=lambda rng: (_pos(rng, 3, 4), np.float32(2.0)),
           grad_wrt=(0,))
    binary("atan2", pt.atan2, np.arctan2)
    binary("broadcast_add", pt.add, np.add,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 1, 4)))

    # -- reductions -------------------------------------------------------
    unary("sum", pt.sum, np.sum, sample=lambda rng: (_r(rng, 3, 4),))
    unary("mean", pt.mean, np.mean)
    unary("max", pt.max, np.max, grad_wrt=())
    unary("min", pt.min, np.min, grad_wrt=())
    unary("prod", pt.prod, np.prod,
          sample=lambda rng: (_pos(rng, 2, 3),))
    register_op(OpSpec(
        name="sum.axis", fn=lambda x: __import__("paddle_tpu").sum(
            x, axis=1, keepdim=True),
        ref=lambda x: np.sum(x, axis=1, keepdims=True),
        sample=lambda rng: (_r(rng, 3, 4),)))

    # -- linalg -----------------------------------------------------------
    register_op(OpSpec(
        name="matmul", fn=pt.matmul, ref=np.matmul,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)),
        grad_wrt=(0, 1), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.linear",
        fn=lambda x, w, b: F.linear(x, w, b),
        ref=lambda x, w, b: x @ w + b,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5), _r(rng, 5)),
        grad_wrt=(0, 1, 2), rtol=2e-5, atol=2e-5))

    # -- activations (nn/functional ≙ phi activation kernels) -------------
    unary("nn.functional.relu", F.relu, lambda x: np.maximum(x, 0))
    unary("nn.functional.gelu", F.gelu,
          lambda x: 0.5 * x * (1 + np.vectorize(_erf_scalar)(
              x / np.sqrt(2.0))), rtol=2e-5, atol=2e-5)
    unary("nn.functional.silu", F.silu,
          lambda x: x / (1 + np.exp(-x)))
    unary("nn.functional.softmax",
          lambda x: F.softmax(x, axis=-1), _np_softmax)
    unary("nn.functional.log_softmax",
          lambda x: F.log_softmax(x, axis=-1),
          lambda x: np.log(_np_softmax(x)))
    unary("nn.functional.leaky_relu",
          lambda x: F.leaky_relu(x, negative_slope=0.1),
          lambda x: np.where(x >= 0, x, 0.1 * x))
    unary("nn.functional.hardswish", F.hardswish,
          lambda x: x * np.clip(x + 3, 0, 6) / 6, grad_rtol=2e-2,
          grad_atol=2e-3)

    # -- norm layers (functional form) ------------------------------------
    register_op(OpSpec(
        name="nn.functional.layer_norm",
        fn=lambda x, w, b: F.layer_norm(x, (4,), weight=w, bias=b,
                                        epsilon=1e-5),
        ref=lambda x, w, b: _np_layer_norm(x, w, b, 1e-5),
        sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 4), _r(rng, 4)),
        grad_wrt=(0, 1, 2), rtol=2e-5, atol=2e-5))

    # -- losses -----------------------------------------------------------
    register_op(OpSpec(
        name="nn.functional.cross_entropy",
        fn=lambda lg, lb: F.cross_entropy(lg, lb, reduction="mean"),
        ref=lambda lg, lb: -np.mean(
            np.log(_np_softmax(lg))[np.arange(lg.shape[0]), lb]),
        sample=lambda rng: (_r(rng, 6, 10),
                            rng.randint(0, 10, (6,)).astype(np.int32)),
        grad_wrt=(0,), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.mse_loss",
        fn=lambda a, b: F.mse_loss(a, b),
        ref=lambda a, b: np.mean((a - b) ** 2),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(0, 1)))

    # -- shape ops --------------------------------------------------------
    register_op(OpSpec(
        name="concat", fn=lambda a, b: pt.concat([a, b], axis=1),
        ref=lambda a, b: np.concatenate([a, b], axis=1),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 2)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="transpose", fn=lambda x: pt.transpose(x, (1, 0)),
        ref=lambda x: x.T, sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="reshape", fn=lambda x: pt.reshape(x, (4, 3)),
        ref=lambda x: x.reshape(4, 3),
        sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="clip", fn=lambda x: pt.clip(x, -0.5, 0.5),
        ref=lambda x: np.clip(x, -0.5, 0.5),
        sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="where", fn=lambda c, a, b: pt.where(c, a, b),
        ref=np.where,
        sample=lambda rng: (rng.rand(3, 4) > 0.5, _r(rng, 3, 4),
                            _r(rng, 3, 4)),
        grad_wrt=(1, 2)))
    register_op(OpSpec(
        name="gather",
        fn=lambda x, i: pt.gather(x, i, axis=0),
        ref=lambda x, i: np.take(x, i, axis=0),
        sample=lambda rng: (_r(rng, 5, 4),
                            rng.randint(0, 5, (3,)).astype(np.int32)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="cumsum", fn=lambda x: pt.cumsum(x, axis=1),
        ref=lambda x: np.cumsum(x, axis=1),
        sample=lambda rng: (_r(rng, 3, 4),)))

    # -- extended corpus (tensor_ops.py / linalg.py, round 4) -------------
    unary("logsumexp", lambda x: pt.logsumexp(x, axis=1),
          lambda x: np.log(np.sum(np.exp(x), axis=1)))
    unary("expm1", pt.expm1, np.expm1)
    unary("log2", pt.log2, np.log2, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("log10", pt.log10, np.log10,
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("asin", pt.asin, np.arcsin,
          sample=lambda rng: (rng.uniform(-0.9, 0.9, (3, 4)).astype(
              np.float32),))
    unary("acos", pt.acos, np.arccos,
          sample=lambda rng: (rng.uniform(-0.9, 0.9, (3, 4)).astype(
              np.float32),))
    unary("atan", pt.atan, np.arctan)
    unary("sinh", pt.sinh, np.sinh)
    unary("cosh", pt.cosh, np.cosh)
    unary("tan", pt.tan, np.tan,
          sample=lambda rng: (rng.uniform(-1.0, 1.0, (3, 4)).astype(
              np.float32),))
    unary("deg2rad", pt.deg2rad, np.deg2rad)
    unary("rad2deg", pt.rad2deg, np.rad2deg)
    unary("frac", pt.frac, lambda x: x - np.trunc(x), grad_wrt=())
    unary("erfinv", pt.erfinv,
          sample=lambda rng: (rng.uniform(-0.8, 0.8, (3, 4)).astype(
              np.float32),),
          ref=lambda x: np.vectorize(_erfinv_scalar)(x).astype(np.float64),
          rtol=1e-4, atol=1e-5, grad_rtol=2e-2, grad_atol=2e-3)
    unary("logit", lambda x: pt.logit(x),
          lambda x: np.log(x) - np.log1p(-x),
          sample=lambda rng: (rng.uniform(0.1, 0.9, (3, 4)).astype(
              np.float32),))
    unary("stanh", pt.stanh,
          lambda x: 1.7159 * np.tanh(0.67 * x))
    unary("trace", pt.trace, np.trace,
          sample=lambda rng: (_r(rng, 4, 4),))
    unary("diagonal", lambda x: pt.diagonal(x, offset=1),
          lambda x: np.diagonal(x, offset=1),
          sample=lambda rng: (_r(rng, 4, 4),))
    unary("median", lambda x: pt.median(x, axis=1),
          lambda x: np.median(x, axis=1),
          sample=lambda rng: (_r(rng, 3, 5),), grad_wrt=())
    unary("quantile", lambda x: pt.quantile(x, 0.25, axis=1),
          lambda x: np.quantile(x, 0.25, axis=1),
          sample=lambda rng: (_r(rng, 3, 5),), grad_wrt=())
    unary("amax", lambda x: pt.amax(x, axis=1),
          lambda x: np.max(x, axis=1), grad_wrt=())
    unary("amin", lambda x: pt.amin(x, axis=1),
          lambda x: np.min(x, axis=1), grad_wrt=())
    unary("moveaxis", lambda x: pt.moveaxis(x, 0, 1),
          lambda x: np.moveaxis(x, 0, 1))
    unary("rot90", lambda x: pt.rot90(x),
          lambda x: np.rot90(x), sample=lambda rng: (_r(rng, 3, 4),))
    unary("repeat_interleave",
          lambda x: pt.repeat_interleave(x, 2, axis=1),
          lambda x: np.repeat(x, 2, axis=1))
    def _with_nans(rng):
        x = _r(rng, 3, 5)
        x[0, 1] = np.nan
        x[2, 3] = np.nan
        return (x,)

    unary("nanmean", lambda x: pt.nanmean(x, axis=1),
          lambda x: np.nanmean(x, axis=1), sample=_with_nans, grad_wrt=())
    binary("hypot", pt.hypot, np.hypot)
    binary("copysign", pt.copysign, np.copysign, grad_wrt=(0,))
    binary("lerp", lambda x, y: pt.lerp(x, y, 0.3),
           lambda x, y: x + 0.3 * (y - x))
    binary("kron", pt.kron, np.kron,
           sample=lambda rng: (_r(rng, 2, 3), _r(rng, 3, 2)))
    binary("inner", pt.inner, np.inner,
           sample=lambda rng: (_r(rng, 4), _r(rng, 4)))
    binary("mv", pt.mv, lambda m, v: m @ v,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4)))
    binary("tensordot", lambda a, b: pt.tensordot(a, b, axes=1),
           lambda a, b: np.tensordot(a, b, axes=1),
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)))
    binary("addmm_default",
           lambda i, a: pt.addmm(i, a, np.eye(4, dtype=np.float32)),
           lambda i, a: i + a,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)))
    register_op(OpSpec(
        name="gcd", fn=pt.gcd, ref=np.gcd,
        sample=lambda rng: (rng.randint(1, 40, (6,)).astype(np.int32),
                            rng.randint(1, 40, (6,)).astype(np.int32)),
        grad_wrt=()))
    register_op(OpSpec(
        name="searchsorted",
        fn=lambda e, v: pt.searchsorted(e, v),
        ref=lambda e, v: np.searchsorted(e, v),
        sample=lambda rng: (np.sort(_r(rng, 6)), _r(rng, 4)),
        grad_wrt=()))
    register_op(OpSpec(
        name="linalg.det", fn=pt.linalg.det, ref=np.linalg.det,
        sample=lambda rng: (_r(rng, 3, 3) + 3 * np.eye(3, dtype=np.float32),),
        grad_wrt=(0,), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.inv", fn=pt.linalg.inv, ref=np.linalg.inv,
        sample=lambda rng: (_r(rng, 3, 3) + 3 * np.eye(3, dtype=np.float32),),
        grad_wrt=(0,), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.solve",
        fn=pt.linalg.solve, ref=np.linalg.solve,
        sample=lambda rng: (_r(rng, 3, 3) + 3 * np.eye(3, dtype=np.float32),
                            _r(rng, 3, 2)),
        grad_wrt=(0, 1), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.multi_dot",
        fn=lambda a, b, c: pt.linalg.multi_dot([a, b, c]),
        ref=lambda a, b, c: a @ b @ c,
        sample=lambda rng: (_r(rng, 2, 3), _r(rng, 3, 4), _r(rng, 4, 2)),
        grad_wrt=(0, 1, 2), rtol=1e-4, atol=1e-5))
    unary("nn.functional.relu6", F.relu6, lambda x: np.clip(x, 0, 6),
          grad_rtol=2e-2, grad_atol=2e-3)
    unary("nn.functional.elu", F.elu,
          lambda x: np.where(x > 0, x, np.exp(x) - 1))
    unary("nn.functional.mish", F.mish,
          lambda x: x * np.tanh(np.log1p(np.exp(x))))
    unary("nn.functional.softplus", F.softplus,
          lambda x: np.log1p(np.exp(x)))
    unary("nn.functional.hardsigmoid", F.hardsigmoid,
          lambda x: np.clip(x / 6 + 0.5, 0, 1), grad_rtol=2e-2,
          grad_atol=2e-3)
    unary("nn.functional.glu", lambda x: F.glu(x, axis=-1),
          lambda x: x[..., :x.shape[-1] // 2]
          / (1 + np.exp(-x[..., x.shape[-1] // 2:])),
          sample=lambda rng: (_r(rng, 3, 8),))
    register_op(OpSpec(
        name="nn.functional.cosine_similarity",
        fn=lambda a, b: F.cosine_similarity(a, b, axis=1),
        ref=lambda a, b: np.sum(a * b, 1)
        / (np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)),
        sample=lambda rng: (_r(rng, 3, 8), _r(rng, 3, 8)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="nn.functional.kl_div",
        fn=lambda i, t: F.kl_div(i, t, reduction="mean"),
        ref=lambda i, t: np.mean(np.where(
            t > 0, t * (np.log(np.maximum(t, 1e-30)) - i), 0.0)),
        sample=lambda rng: (np.log(_np_softmax(_r(rng, 4, 5))),
                            _np_softmax(_r(rng, 4, 5))),
        grad_wrt=(0,)))


def _erf_scalar(x: float) -> float:
    import math
    return math.erf(float(x))


def _erfinv_scalar(y: float) -> float:
    # bisection on erf — dependency-free numpy reference
    import math
    lo, hi = -6.0, 6.0
    for _ in range(80):
        mid = (lo + hi) / 2
        if math.erf(mid) < y:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2


def _np_softmax(x):
    e = np.exp(x - np.max(x, axis=-1, keepdims=True))
    return e / np.sum(e, axis=-1, keepdims=True)


def _np_layer_norm(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b
