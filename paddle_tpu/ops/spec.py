"""Declarative op-spec registry — the api.yaml analog (component C12).

Reference: python/paddle/utils/code_gen/api.yaml (228 `api:` entries, each
declaring args/output/infer_meta/kernel/backward) feeding api_gen.py and the
eager codegen (SURVEY A6).  On TPU there is no kernel table to generate —
jax.numpy IS the kernel substrate — but the yaml's other role survives: ONE
source of truth for the public op surface that drives parity tests (OpTest
sweep over every entry, tests/test_op_registry.py), the API inventory
(``api_table()``), and grad coverage.

Each OpSpec carries the public callable, a pure-numpy reference, a sample
input generator, and tolerance/grad metadata.  Registering an op here is
what makes it part of the tested API contract.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["OpSpec", "register_op", "registry", "api_table"]


@dataclasses.dataclass
class OpSpec:
    name: str                      # dotted public path under paddle_tpu
    fn: Callable                   # the framework op
    ref: Callable                  # numpy reference implementation
    sample: Callable               # rng -> tuple of np args
    grad_wrt: Tuple[int, ...] = (0,)   # args to grad-check (() = skip)
    rtol: float = 1e-5
    atol: float = 1e-6
    grad_rtol: float = 5e-3
    grad_atol: float = 5e-4
    bf16: bool = True              # False = dtype-limited (no bf16 kernel)


_REGISTRY: List[OpSpec] = []


def register_op(spec: OpSpec) -> OpSpec:
    _REGISTRY.append(spec)
    return spec


def registry() -> List[OpSpec]:
    if not _REGISTRY:
        _populate()
    return list(_REGISTRY)


def api_table() -> str:
    """Markdown inventory of the registered public op surface."""
    lines = ["| op | grad-checked |", "|---|---|"]
    for s in registry():
        lines.append(f"| `paddle_tpu.{s.name}` | "
                     f"{'yes' if s.grad_wrt else 'n/a'} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Registration corpus
# ---------------------------------------------------------------------------
def _r(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


def _pos(rng, *shape):
    return (np.abs(rng.randn(*shape)) + 0.5).astype(np.float32)


def _populate() -> None:
    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F

    def unary(name, fn, ref, sample=lambda rng: (_r(rng, 3, 4),), **kw):
        register_op(OpSpec(name=name, fn=fn, ref=ref, sample=sample, **kw))

    def binary(name, fn, ref, sample=None, **kw):
        sample = sample or (lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)))
        register_op(OpSpec(name=name, fn=fn, ref=ref, sample=sample,
                           grad_wrt=kw.pop("grad_wrt", (0, 1)), **kw))

    # -- math unary (reference tensor/math.py ≙ phi unary kernels) --------
    unary("exp", pt.exp, np.exp)
    unary("log", pt.log, np.log, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("log1p", pt.log1p, np.log1p,
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("sqrt", pt.sqrt, np.sqrt, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("rsqrt", pt.rsqrt, lambda x: 1.0 / np.sqrt(x),
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("square", pt.square, np.square)
    unary("abs", pt.abs, np.abs)
    unary("sin", pt.sin, np.sin)
    unary("cos", pt.cos, np.cos)
    unary("tanh", pt.tanh, np.tanh)
    unary("sigmoid", pt.sigmoid, lambda x: 1 / (1 + np.exp(-x)))
    unary("erf", pt.erf,
          lambda x: np.vectorize(_erf_scalar)(x).astype(np.float64))
    unary("floor", pt.floor, np.floor, grad_wrt=())
    unary("ceil", pt.ceil, np.ceil, grad_wrt=())
    unary("round", pt.round, np.round, grad_wrt=())
    unary("sign", pt.sign, np.sign, grad_wrt=())
    unary("reciprocal", pt.reciprocal, lambda x: 1.0 / x,
          sample=lambda rng: (_pos(rng, 3, 4),))

    # -- math binary (broadcasting included) ------------------------------
    binary("add", pt.add, np.add)
    binary("subtract", pt.subtract, np.subtract)
    binary("multiply", pt.multiply, np.multiply)
    binary("divide", pt.divide, np.divide,
           sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 3, 4)))
    binary("maximum", pt.maximum, np.maximum)
    binary("minimum", pt.minimum, np.minimum)
    binary("pow", pt.pow, np.power,
           sample=lambda rng: (_pos(rng, 3, 4), np.float32(2.0)),
           grad_wrt=(0,))
    binary("atan2", pt.atan2, np.arctan2)
    binary("broadcast_add", pt.add, np.add,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 1, 4)))

    # -- reductions -------------------------------------------------------
    unary("sum", pt.sum, np.sum, sample=lambda rng: (_r(rng, 3, 4),))
    unary("mean", pt.mean, np.mean)
    unary("max", pt.max, np.max, grad_wrt=())
    unary("min", pt.min, np.min, grad_wrt=())
    unary("prod", pt.prod, np.prod,
          sample=lambda rng: (_pos(rng, 2, 3),))
    register_op(OpSpec(
        name="sum.axis", fn=lambda x: __import__("paddle_tpu").sum(
            x, axis=1, keepdim=True),
        ref=lambda x: np.sum(x, axis=1, keepdims=True),
        sample=lambda rng: (_r(rng, 3, 4),)))

    # -- linalg -----------------------------------------------------------
    register_op(OpSpec(
        name="matmul", fn=pt.matmul, ref=np.matmul,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)),
        grad_wrt=(0, 1), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.linear",
        fn=lambda x, w, b: F.linear(x, w, b),
        ref=lambda x, w, b: x @ w + b,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5), _r(rng, 5)),
        grad_wrt=(0, 1, 2), rtol=2e-5, atol=2e-5))

    # -- activations (nn/functional ≙ phi activation kernels) -------------
    unary("nn.functional.relu", F.relu, lambda x: np.maximum(x, 0))
    unary("nn.functional.gelu", F.gelu,
          lambda x: 0.5 * x * (1 + np.vectorize(_erf_scalar)(
              x / np.sqrt(2.0))), rtol=2e-5, atol=2e-5)
    unary("nn.functional.silu", F.silu,
          lambda x: x / (1 + np.exp(-x)))
    unary("nn.functional.softmax",
          lambda x: F.softmax(x, axis=-1), _np_softmax)
    unary("nn.functional.log_softmax",
          lambda x: F.log_softmax(x, axis=-1),
          lambda x: np.log(_np_softmax(x)))
    unary("nn.functional.leaky_relu",
          lambda x: F.leaky_relu(x, negative_slope=0.1),
          lambda x: np.where(x >= 0, x, 0.1 * x))
    unary("nn.functional.hardswish", F.hardswish,
          lambda x: x * np.clip(x + 3, 0, 6) / 6, grad_rtol=2e-2,
          grad_atol=2e-3)

    # -- norm layers (functional form) ------------------------------------
    register_op(OpSpec(
        name="nn.functional.layer_norm",
        fn=lambda x, w, b: F.layer_norm(x, (4,), weight=w, bias=b,
                                        epsilon=1e-5),
        ref=lambda x, w, b: _np_layer_norm(x, w, b, 1e-5),
        sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 4), _r(rng, 4)),
        grad_wrt=(0, 1, 2), rtol=2e-5, atol=2e-5))

    # -- losses -----------------------------------------------------------
    register_op(OpSpec(
        name="nn.functional.cross_entropy",
        fn=lambda lg, lb: F.cross_entropy(lg, lb, reduction="mean"),
        ref=lambda lg, lb: -np.mean(
            np.log(_np_softmax(lg))[np.arange(lg.shape[0]), lb]),
        sample=lambda rng: (_r(rng, 6, 10),
                            rng.randint(0, 10, (6,)).astype(np.int32)),
        grad_wrt=(0,), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.mse_loss",
        fn=lambda a, b: F.mse_loss(a, b),
        ref=lambda a, b: np.mean((a - b) ** 2),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(0, 1)))

    # -- shape ops --------------------------------------------------------
    register_op(OpSpec(
        name="concat", fn=lambda a, b: pt.concat([a, b], axis=1),
        ref=lambda a, b: np.concatenate([a, b], axis=1),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 2)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="transpose", fn=lambda x: pt.transpose(x, (1, 0)),
        ref=lambda x: x.T, sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="reshape", fn=lambda x: pt.reshape(x, (4, 3)),
        ref=lambda x: x.reshape(4, 3),
        sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="clip", fn=lambda x: pt.clip(x, -0.5, 0.5),
        ref=lambda x: np.clip(x, -0.5, 0.5),
        sample=lambda rng: (_r(rng, 3, 4),)))
    register_op(OpSpec(
        name="where", fn=lambda c, a, b: pt.where(c, a, b),
        ref=np.where,
        sample=lambda rng: (rng.rand(3, 4) > 0.5, _r(rng, 3, 4),
                            _r(rng, 3, 4)),
        grad_wrt=(1, 2)))
    register_op(OpSpec(
        name="gather",
        fn=lambda x, i: pt.gather(x, i, axis=0),
        ref=lambda x, i: np.take(x, i, axis=0),
        sample=lambda rng: (_r(rng, 5, 4),
                            rng.randint(0, 5, (3,)).astype(np.int32)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="cumsum", fn=lambda x: pt.cumsum(x, axis=1),
        ref=lambda x: np.cumsum(x, axis=1),
        sample=lambda rng: (_r(rng, 3, 4),)))

    # -- extended corpus (tensor_ops.py / linalg.py, round 4) -------------
    unary("logsumexp", lambda x: pt.logsumexp(x, axis=1),
          lambda x: np.log(np.sum(np.exp(x), axis=1)))
    unary("expm1", pt.expm1, np.expm1)
    unary("log2", pt.log2, np.log2, sample=lambda rng: (_pos(rng, 3, 4),))
    unary("log10", pt.log10, np.log10,
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("asin", pt.asin, np.arcsin,
          sample=lambda rng: (rng.uniform(-0.9, 0.9, (3, 4)).astype(
              np.float32),))
    unary("acos", pt.acos, np.arccos,
          sample=lambda rng: (rng.uniform(-0.9, 0.9, (3, 4)).astype(
              np.float32),))
    unary("atan", pt.atan, np.arctan)
    unary("sinh", pt.sinh, np.sinh)
    unary("cosh", pt.cosh, np.cosh)
    unary("tan", pt.tan, np.tan,
          sample=lambda rng: (rng.uniform(-1.0, 1.0, (3, 4)).astype(
              np.float32),))
    unary("deg2rad", pt.deg2rad, np.deg2rad)
    unary("rad2deg", pt.rad2deg, np.rad2deg)
    unary("frac", pt.frac, lambda x: x - np.trunc(x), grad_wrt=())
    unary("erfinv", pt.erfinv,
          sample=lambda rng: (rng.uniform(-0.8, 0.8, (3, 4)).astype(
              np.float32),),
          ref=lambda x: np.vectorize(_erfinv_scalar)(x).astype(np.float64),
          rtol=1e-4, atol=1e-5, grad_rtol=2e-2, grad_atol=2e-3)
    unary("logit", lambda x: pt.logit(x),
          lambda x: np.log(x) - np.log1p(-x),
          sample=lambda rng: (rng.uniform(0.1, 0.9, (3, 4)).astype(
              np.float32),))
    unary("stanh", pt.stanh,
          lambda x: 1.7159 * np.tanh(0.67 * x))
    unary("trace", pt.trace, np.trace,
          sample=lambda rng: (_r(rng, 4, 4),))
    unary("diagonal", lambda x: pt.diagonal(x, offset=1),
          lambda x: np.diagonal(x, offset=1),
          sample=lambda rng: (_r(rng, 4, 4),))
    unary("median", lambda x: pt.median(x, axis=1),
          lambda x: np.median(x, axis=1),
          sample=lambda rng: (_r(rng, 3, 5),), grad_wrt=())
    unary("quantile", lambda x: pt.quantile(x, 0.25, axis=1),
          lambda x: np.quantile(x, 0.25, axis=1),
          sample=lambda rng: (_r(rng, 3, 5),), grad_wrt=())
    unary("amax", lambda x: pt.amax(x, axis=1),
          lambda x: np.max(x, axis=1), grad_wrt=())
    unary("amin", lambda x: pt.amin(x, axis=1),
          lambda x: np.min(x, axis=1), grad_wrt=())
    unary("moveaxis", lambda x: pt.moveaxis(x, 0, 1),
          lambda x: np.moveaxis(x, 0, 1))
    unary("rot90", lambda x: pt.rot90(x),
          lambda x: np.rot90(x), sample=lambda rng: (_r(rng, 3, 4),))
    unary("repeat_interleave",
          lambda x: pt.repeat_interleave(x, 2, axis=1),
          lambda x: np.repeat(x, 2, axis=1))
    def _with_nans(rng):
        x = _r(rng, 3, 5)
        x[0, 1] = np.nan
        x[2, 3] = np.nan
        return (x,)

    unary("nanmean", lambda x: pt.nanmean(x, axis=1),
          lambda x: np.nanmean(x, axis=1), sample=_with_nans, grad_wrt=())
    binary("hypot", pt.hypot, np.hypot)
    binary("copysign", pt.copysign, np.copysign, grad_wrt=(0,))
    binary("lerp", lambda x, y: pt.lerp(x, y, 0.3),
           lambda x, y: x + 0.3 * (y - x))
    binary("kron", pt.kron, np.kron,
           sample=lambda rng: (_r(rng, 2, 3), _r(rng, 3, 2)))
    binary("inner", pt.inner, np.inner,
           sample=lambda rng: (_r(rng, 4), _r(rng, 4)))
    binary("mv", pt.mv, lambda m, v: m @ v,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4)))
    binary("tensordot", lambda a, b: pt.tensordot(a, b, axes=1),
           lambda a, b: np.tensordot(a, b, axes=1),
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)))
    binary("addmm_default",
           lambda i, a: pt.addmm(i, a, np.eye(4, dtype=np.float32)),
           lambda i, a: i + a,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)))
    register_op(OpSpec(
        name="gcd", fn=pt.gcd, ref=np.gcd,
        sample=lambda rng: (rng.randint(1, 40, (6,)).astype(np.int32),
                            rng.randint(1, 40, (6,)).astype(np.int32)),
        grad_wrt=()))
    register_op(OpSpec(
        name="searchsorted",
        fn=lambda e, v: pt.searchsorted(e, v),
        ref=lambda e, v: np.searchsorted(e, v),
        sample=lambda rng: (np.sort(_r(rng, 6)), _r(rng, 4)),
        grad_wrt=()))
    register_op(OpSpec(
        name="linalg.det", fn=pt.linalg.det, ref=np.linalg.det,
        sample=lambda rng: (_r(rng, 3, 3) + 3 * np.eye(3, dtype=np.float32),),
        grad_wrt=(0,), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.inv", fn=pt.linalg.inv, ref=np.linalg.inv,
        sample=lambda rng: (_r(rng, 3, 3) + 3 * np.eye(3, dtype=np.float32),),
        grad_wrt=(0,), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.solve",
        fn=pt.linalg.solve, ref=np.linalg.solve,
        sample=lambda rng: (_r(rng, 3, 3) + 3 * np.eye(3, dtype=np.float32),
                            _r(rng, 3, 2)),
        grad_wrt=(0, 1), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.multi_dot",
        fn=lambda a, b, c: pt.linalg.multi_dot([a, b, c]),
        ref=lambda a, b, c: a @ b @ c,
        sample=lambda rng: (_r(rng, 2, 3), _r(rng, 3, 4), _r(rng, 4, 2)),
        grad_wrt=(0, 1, 2), rtol=1e-4, atol=1e-5))
    unary("nn.functional.relu6", F.relu6, lambda x: np.clip(x, 0, 6),
          grad_rtol=2e-2, grad_atol=2e-3)
    unary("nn.functional.elu", F.elu,
          lambda x: np.where(x > 0, x, np.exp(x) - 1))
    unary("nn.functional.mish", F.mish,
          lambda x: x * np.tanh(np.log1p(np.exp(x))))
    unary("nn.functional.softplus", F.softplus,
          lambda x: np.log1p(np.exp(x)))
    unary("nn.functional.hardsigmoid", F.hardsigmoid,
          lambda x: np.clip(x / 6 + 0.5, 0, 1), grad_rtol=2e-2,
          grad_atol=2e-3)
    unary("nn.functional.glu", lambda x: F.glu(x, axis=-1),
          lambda x: x[..., :x.shape[-1] // 2]
          / (1 + np.exp(-x[..., x.shape[-1] // 2:])),
          sample=lambda rng: (_r(rng, 3, 8),))
    register_op(OpSpec(
        name="nn.functional.cosine_similarity",
        fn=lambda a, b: F.cosine_similarity(a, b, axis=1),
        ref=lambda a, b: np.sum(a * b, 1)
        / (np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)),
        sample=lambda rng: (_r(rng, 3, 8), _r(rng, 3, 8)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="nn.functional.kl_div",
        fn=lambda i, t: F.kl_div(i, t, reduction="mean"),
        ref=lambda i, t: np.mean(np.where(
            t > 0, t * (np.log(np.maximum(t, 1e-30)) - i), 0.0)),
        sample=lambda rng: (np.log(_np_softmax(_r(rng, 4, 5))),
                            _np_softmax(_r(rng, 4, 5))),
        grad_wrt=(0,)))

    _populate_round5(unary, binary)


def _populate_round5(unary, binary) -> None:
    """Round-5 corpus: the already-implemented tensor/linalg/fft/functional
    ops, registered so the numpy-parity + numeric-grad + bf16 sweeps cover
    them (VERDICT r4 #4; closes most of the api.yaml registration gap)."""
    import scipy.special as sps

    import paddle_tpu as pt
    from paddle_tpu.nn import functional as F

    def _ints(rng, lo, hi, *shape):
        return rng.randint(lo, hi, shape).astype(np.int32)

    def _bools(rng, *shape):
        return rng.rand(*shape) > 0.5

    # -- comparisons / logicals (grad-free) -------------------------------
    for name, npf in [("equal", np.equal), ("not_equal", np.not_equal),
                      ("greater_than", np.greater),
                      ("greater_equal", np.greater_equal),
                      ("less_than", np.less), ("less_equal", np.less_equal)]:
        binary(name, getattr(pt, name), npf, grad_wrt=())
    for name, npf in [("logical_and", np.logical_and),
                      ("logical_or", np.logical_or),
                      ("logical_xor", np.logical_xor)]:
        register_op(OpSpec(
            name=name, fn=getattr(pt, name), ref=npf,
            sample=lambda rng: (_bools(rng, 3, 4), _bools(rng, 3, 4)),
            grad_wrt=()))
    register_op(OpSpec(
        name="logical_not", fn=pt.logical_not, ref=np.logical_not,
        sample=lambda rng: (_bools(rng, 3, 4),), grad_wrt=()))
    for name, npf in [("bitwise_and", np.bitwise_and),
                      ("bitwise_or", np.bitwise_or),
                      ("bitwise_xor", np.bitwise_xor)]:
        register_op(OpSpec(
            name=name, fn=getattr(pt, name), ref=npf,
            sample=lambda rng: (_ints(rng, 0, 16, 3, 4),
                                _ints(rng, 0, 16, 3, 4)),
            grad_wrt=()))
    register_op(OpSpec(
        name="isclose", fn=pt.isclose, ref=np.isclose,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)), grad_wrt=()))

    # -- predicates / reductions over bool --------------------------------
    def _specials(rng):
        x = _r(rng, 3, 4)
        x[0, 0], x[1, 1], x[2, 2] = np.nan, np.inf, -np.inf
        return (x,)

    unary("isnan", pt.isnan, np.isnan, sample=_specials, grad_wrt=())
    unary("isinf", pt.isinf, np.isinf, sample=_specials, grad_wrt=())
    unary("isfinite", pt.isfinite, np.isfinite, sample=_specials,
          grad_wrt=())
    register_op(OpSpec(
        name="all", fn=lambda x: pt.all(x, axis=1),
        ref=lambda x: np.all(x, axis=1),
        sample=lambda rng: (_bools(rng, 3, 4),), grad_wrt=()))
    register_op(OpSpec(
        name="any", fn=lambda x: pt.any(x, axis=1),
        ref=lambda x: np.any(x, axis=1),
        sample=lambda rng: (_bools(rng, 3, 4),), grad_wrt=()))

    # -- index / argsort family (grad-free) -------------------------------
    unary("argmax", lambda x: pt.argmax(x, axis=1),
          lambda x: np.argmax(x, axis=1), grad_wrt=())
    unary("argmin", lambda x: pt.argmin(x, axis=1),
          lambda x: np.argmin(x, axis=1), grad_wrt=())
    unary("argsort", lambda x: pt.argsort(x, axis=1),
          lambda x: np.argsort(x, axis=1, kind="stable"), grad_wrt=())
    unary("sort", lambda x: pt.sort(x, axis=1),
          lambda x: np.sort(x, axis=1))
    unary("topk", lambda x: pt.topk(x, 3, axis=1)[0],
          lambda x: -np.sort(-x, axis=1)[:, :3],
          sample=lambda rng: (_r(rng, 3, 6),), grad_wrt=())
    unary("kthvalue", lambda x: pt.kthvalue(x, 2, axis=1)[0],
          lambda x: np.sort(x, axis=1)[:, 1],
          sample=lambda rng: (_r(rng, 3, 6),), grad_wrt=())
    register_op(OpSpec(
        name="mode", fn=lambda x: pt.mode(x, axis=1)[0],
        ref=_np_mode_rows,
        sample=lambda rng: (_ints(rng, 0, 3, 4, 7).astype(np.float32),),
        grad_wrt=()))
    register_op(OpSpec(
        name="bincount", fn=pt.bincount, ref=np.bincount,
        sample=lambda rng: (_ints(rng, 0, 8, 20),), grad_wrt=()))
    register_op(OpSpec(
        name="histogram",
        fn=lambda x: pt.histogram(x, bins=5, min=-2.0, max=2.0),
        ref=lambda x: np.histogram(x, bins=5, range=(-2.0, 2.0))[0],
        sample=lambda rng: (_r(rng, 20),), grad_wrt=()))
    register_op(OpSpec(
        name="bucketize",
        fn=lambda x, e: pt.bucketize(x, e),
        ref=lambda x, e: np.searchsorted(e, x),
        sample=lambda rng: (_r(rng, 8), np.sort(_r(rng, 5))),
        grad_wrt=()))
    register_op(OpSpec(
        name="index_select",
        fn=lambda x, i: pt.index_select(x, i, axis=1),
        ref=lambda x, i: np.take(x, i, axis=1),
        sample=lambda rng: (_r(rng, 3, 6), _ints(rng, 0, 6, 4)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="index_sample",
        fn=pt.index_sample,
        ref=lambda x, i: np.take_along_axis(x, i, axis=1),
        sample=lambda rng: (_r(rng, 3, 6), _ints(rng, 0, 6, 3, 2)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="take_along_axis",
        fn=lambda x, i: pt.take_along_axis(x, i, axis=1),
        ref=lambda x, i: np.take_along_axis(x, i, axis=1),
        sample=lambda rng: (_r(rng, 3, 6), _ints(rng, 0, 6, 3, 2)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="put_along_axis",
        fn=lambda x, i, v: pt.put_along_axis(x, i, v, axis=1),
        ref=_np_put_along_axis,
        sample=lambda rng: (_r(rng, 3, 6), _ints(rng, 0, 6, 3, 2),
                            _r(rng, 3, 2)),
        grad_wrt=(0, 2)))
    register_op(OpSpec(
        name="gather_nd", fn=pt.gather_nd,
        ref=lambda x, i: x[tuple(np.moveaxis(i, -1, 0))],
        sample=lambda rng: (_r(rng, 4, 5),
                            np.stack([_ints(rng, 0, 4, 3),
                                      _ints(rng, 0, 5, 3)], -1)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="scatter",
        fn=lambda x, i, u: pt.scatter(x, i, u),
        ref=_np_scatter_overwrite,
        sample=lambda rng: (_r(rng, 5, 3), np.asarray([0, 2], np.int32),
                            _r(rng, 2, 3)),
        grad_wrt=(0, 2)))
    register_op(OpSpec(
        name="multiplex",
        fn=lambda a, b, i: pt.multiplex([a, b], i),
        ref=lambda a, b, i: np.where((i == 0)[:, None] if i.ndim == 1
                                     else (i == 0), a, b),
        sample=lambda rng: (_r(rng, 4, 3), _r(rng, 4, 3),
                            _ints(rng, 0, 2, 4)),
        grad_wrt=()))

    # -- shape manipulation ------------------------------------------------
    unary("flip", lambda x: pt.flip(x, axis=0), lambda x: np.flip(x, 0))
    unary("roll", lambda x: pt.roll(x, 2, axis=1),
          lambda x: np.roll(x, 2, axis=1))
    unary("tile", lambda x: pt.tile(x, (2, 3)),
          lambda x: np.tile(x, (2, 3)))
    unary("broadcast_to", lambda x: pt.broadcast_to(x, (3, 4)),
          lambda x: np.broadcast_to(x, (3, 4)),
          sample=lambda rng: (_r(rng, 1, 4),))
    unary("expand", lambda x: pt.expand(x, (3, 4)),
          lambda x: np.broadcast_to(x, (3, 4)),
          sample=lambda rng: (_r(rng, 1, 4),))
    unary("squeeze", lambda x: pt.squeeze(x, axis=1),
          lambda x: np.squeeze(x, 1),
          sample=lambda rng: (_r(rng, 3, 1, 4),))
    unary("unsqueeze", lambda x: pt.unsqueeze(x, axis=1),
          lambda x: np.expand_dims(x, 1))
    unary("stack_pair", lambda x: pt.stack([x, x], axis=0),
          lambda x: np.stack([x, x], 0))
    unary("split", lambda x: pt.split(x, 2, axis=1)[0],
          lambda x: np.split(x, 2, axis=1)[0])
    unary("chunk", lambda x: pt.chunk(x, 2, axis=1)[1],
          lambda x: np.array_split(x, 2, axis=1)[1])
    unary("unbind", lambda x: pt.unbind(x, axis=0)[1],
          lambda x: x[1])
    unary("t", pt.t, lambda x: x.T)
    unary("tril", pt.tril, np.tril, sample=lambda rng: (_r(rng, 4, 4),))
    unary("triu", pt.triu, np.triu, sample=lambda rng: (_r(rng, 4, 4),))
    unary("diag", pt.diag, np.diag, sample=lambda rng: (_r(rng, 4),))
    unary("diagflat", pt.diagflat, np.diagflat,
          sample=lambda rng: (_r(rng, 2, 3),))

    # -- more math ---------------------------------------------------------
    unary("neg", pt.neg, np.negative)
    unary("trunc", pt.trunc, np.trunc, grad_wrt=())
    unary("digamma", pt.digamma, sps.digamma,
          sample=lambda rng: (_pos(rng, 3, 4),), rtol=1e-4, atol=1e-5,
          grad_rtol=2e-2, grad_atol=2e-3)
    unary("cumprod", lambda x: pt.cumprod(x, 1),
          lambda x: np.cumprod(x, axis=1),
          sample=lambda rng: (_pos(rng, 2, 4),))
    unary("logcumsumexp", lambda x: pt.logcumsumexp(x, axis=1),
          lambda x: np.log(np.cumsum(np.exp(x), axis=1)),
          rtol=2e-5, atol=2e-5, grad_rtol=2e-2, grad_atol=2e-3)
    unary("diff", lambda x: pt.diff(x, axis=1),
          lambda x: np.diff(x, axis=1))
    unary("nansum", lambda x: pt.nansum(x, axis=1),
          lambda x: np.nansum(x, axis=1), sample=_nan_sample, grad_wrt=())
    unary("nanmedian", lambda x: pt.nanmedian(x, axis=1),
          lambda x: np.nanmedian(x, axis=1), sample=_nan_sample,
          grad_wrt=())
    unary("std", lambda x: pt.std(x, axis=1),
          lambda x: np.std(x, axis=1, ddof=1))
    unary("var", lambda x: pt.var(x, axis=1),
          lambda x: np.var(x, axis=1, ddof=1))
    unary("norm_fro", pt.norm,
          lambda x: np.linalg.norm(x.reshape(-1)))
    unary("scale", lambda x: pt.scale(x, scale=2.0, bias=1.0),
          lambda x: 2.0 * x + 1.0)
    unary("renorm", lambda x: pt.renorm(x, p=2.0, axis=0, max_norm=1.0),
          _np_renorm, sample=lambda rng: (_r(rng, 3, 4) * 2,))
    binary("mod", pt.mod, np.mod,
           sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 3, 4)),
           grad_wrt=(0,))
    binary("floor_divide", pt.floor_divide, np.floor_divide,
           sample=lambda rng: (_r(rng, 3, 4), _pos(rng, 3, 4)),
           grad_wrt=())
    binary("fmax", pt.fmax, np.fmax)
    binary("fmin", pt.fmin, np.fmin)
    binary("ldexp", pt.ldexp, np.ldexp,
           sample=lambda rng: (_r(rng, 3, 4), _ints(rng, -3, 4, 3, 4)),
           grad_wrt=(0,))
    register_op(OpSpec(
        name="lcm", fn=pt.lcm, ref=np.lcm,
        sample=lambda rng: (_ints(rng, 1, 20, 6), _ints(rng, 1, 20, 6)),
        grad_wrt=()))
    binary("dot", pt.dot, np.dot,
           sample=lambda rng: (_r(rng, 5), _r(rng, 5)))
    binary("outer", pt.outer, np.outer,
           sample=lambda rng: (_r(rng, 3), _r(rng, 4)))
    binary("cross", pt.cross, np.cross,
           sample=lambda rng: (_r(rng, 4, 3), _r(rng, 4, 3)))
    binary("mm", pt.mm, np.matmul,
           sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)),
           rtol=2e-5, atol=2e-5)
    binary("bmm", pt.bmm, np.matmul,
           sample=lambda rng: (_r(rng, 2, 3, 4), _r(rng, 2, 4, 5)),
           rtol=2e-5, atol=2e-5, grad_rtol=2e-2, grad_atol=2e-3)
    binary("dist", pt.dist,
           lambda a, b: np.linalg.norm((a - b).reshape(-1)))
    register_op(OpSpec(
        name="einsum_ij_jk",
        fn=lambda a, b: pt.einsum("ij,jk->ik", a, b),
        ref=lambda a, b: np.einsum("ij,jk->ik", a, b),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 4, 5)),
        grad_wrt=(0, 1), rtol=2e-5, atol=2e-5))

    # -- linalg (decompositions compared invariantly) ----------------------
    def _spd(rng, n=3):
        a = _r(rng, n, n)
        return (a @ a.T + n * np.eye(n, dtype=np.float32),)

    register_op(OpSpec(
        name="linalg.cholesky", fn=pt.linalg.cholesky,
        ref=np.linalg.cholesky, sample=_spd, grad_wrt=(0,),
        rtol=1e-4, atol=1e-4, grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.qr_reconstruct",
        fn=lambda x: (lambda qr: qr[0] @ qr[1])(pt.linalg.qr(x)),
        ref=lambda x: x, sample=lambda rng: (_r(rng, 4, 3),),
        grad_wrt=(), rtol=1e-4, atol=1e-4))
    register_op(OpSpec(
        name="linalg.svdvals",
        fn=lambda x: pt.linalg.svd(x)[1],
        ref=lambda x: np.linalg.svd(x, compute_uv=False),
        sample=lambda rng: (_r(rng, 4, 3),), grad_wrt=(),
        rtol=1e-4, atol=1e-4))
    register_op(OpSpec(
        name="linalg.eigvalsh",
        fn=lambda x: pt.linalg.eigvalsh((x + x.T) / 2),
        ref=lambda x: np.linalg.eigvalsh((x + x.T) / 2),
        sample=lambda rng: (_r(rng, 4, 4),), grad_wrt=(),
        rtol=1e-4, atol=1e-4))
    register_op(OpSpec(
        name="linalg.matrix_power",
        fn=lambda x: pt.linalg.matrix_power(x, 3),
        ref=lambda x: np.linalg.matrix_power(x, 3),
        sample=_spd, grad_wrt=(0,), rtol=1e-3, atol=1e-3,
        grad_rtol=5e-2, grad_atol=5e-2))
    register_op(OpSpec(
        name="linalg.matrix_rank",
        fn=lambda x: pt.linalg.matrix_rank(x, tol=1e-4),
        ref=lambda x: np.linalg.matrix_rank(x, tol=1e-4),
        sample=lambda rng: (np.outer(_r(rng, 4), _r(rng, 4)),),
        grad_wrt=()))
    register_op(OpSpec(
        name="linalg.pinv", fn=pt.linalg.pinv, ref=np.linalg.pinv,
        sample=_spd, grad_wrt=(), rtol=1e-3, atol=1e-3))
    register_op(OpSpec(
        name="linalg.slogdet_logabs",
        fn=lambda x: pt.linalg.slogdet(x)[1],
        ref=lambda x: np.linalg.slogdet(x)[1],
        sample=_spd, grad_wrt=(0,), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.cond", fn=pt.linalg.cond, ref=np.linalg.cond,
        sample=_spd, grad_wrt=(), rtol=1e-3, atol=1e-3))
    register_op(OpSpec(
        name="linalg.lstsq_solution",
        fn=lambda a, b: pt.linalg.lstsq(a, b)[0],
        ref=lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0],
        sample=lambda rng: (_r(rng, 6, 3), _r(rng, 6, 2)),
        grad_wrt=(), rtol=1e-3, atol=1e-3))
    register_op(OpSpec(
        name="linalg.triangular_solve",
        fn=lambda a, b: pt.linalg.triangular_solve(a, b, upper=False),
        ref=lambda a, b: np.linalg.solve(np.tril(a), b),
        sample=lambda rng: (np.tril(_r(rng, 3, 3))
                            + 3 * np.eye(3, dtype=np.float32),
                            _r(rng, 3, 2)),
        grad_wrt=(0, 1), rtol=1e-4, atol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="linalg.norm_axis",
        fn=lambda x: pt.linalg.norm(x, p=2, axis=1),
        ref=lambda x: np.linalg.norm(x, ord=2, axis=1),
        sample=lambda rng: (_r(rng, 3, 5),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="linalg.cov", fn=pt.linalg.cov, ref=np.cov,
        sample=lambda rng: (_r(rng, 3, 8),), grad_wrt=(0,),
        rtol=1e-4, atol=1e-5))

    # -- fft (complex outputs compared directly; grads n/a) ----------------
    for name in ["fft", "ifft", "fft2", "fftshift", "ifftshift"]:
        register_op(OpSpec(
            name=f"fft.{name}", fn=getattr(pt.fft, name),
            ref=getattr(np.fft, name),
            sample=lambda rng: (_r(rng, 4, 8),),
            grad_wrt=(), rtol=1e-4, atol=1e-4, bf16=False))
    register_op(OpSpec(
        name="fft.rfft", fn=pt.fft.rfft, ref=np.fft.rfft,
        sample=lambda rng: (_r(rng, 8),), grad_wrt=(),
        rtol=1e-4, atol=1e-4, bf16=False))
    register_op(OpSpec(
        name="fft.irfft", fn=pt.fft.irfft,
        ref=lambda x: np.fft.irfft(x),
        sample=lambda rng: (np.fft.rfft(_r(rng, 8)),), grad_wrt=(),
        rtol=1e-4, atol=1e-4))

    # -- nn.functional: pooling / conv / resampling ------------------------
    register_op(OpSpec(
        name="nn.functional.avg_pool2d",
        fn=lambda x: F.avg_pool2d(x, 2),
        ref=lambda x: x.reshape(2, 3, 2, 2, 2, 2).mean((3, 5)),
        sample=lambda rng: (_r(rng, 2, 3, 4, 4),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.max_pool2d",
        fn=lambda x: F.max_pool2d(x, 2),
        ref=lambda x: x.reshape(2, 3, 2, 2, 2, 2).max((3, 5)),
        sample=lambda rng: (_r(rng, 2, 3, 4, 4),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.adaptive_avg_pool2d",
        fn=lambda x: F.adaptive_avg_pool2d(x, (3, 3)),
        ref=lambda x: _np_adaptive_pool(x, 3, np.mean),
        sample=lambda rng: (_r(rng, 2, 2, 5, 5),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.adaptive_max_pool2d",
        fn=lambda x: F.adaptive_max_pool2d(x, (3, 3)),
        ref=lambda x: _np_adaptive_pool(x, 3, np.max),
        sample=lambda rng: (_r(rng, 2, 2, 5, 5),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.conv2d",
        fn=lambda x, w: F.conv2d(x, w, padding=1),
        ref=lambda x, w: _np_conv2d(x, w, pad=1),
        sample=lambda rng: (_r(rng, 1, 2, 4, 4), _r(rng, 3, 2, 3, 3)),
        grad_wrt=(0, 1), rtol=2e-5, atol=2e-5, grad_rtol=2e-2,
        grad_atol=2e-3))
    register_op(OpSpec(
        name="nn.functional.interpolate_nearest",
        fn=lambda x: F.interpolate(x, scale_factor=2, mode="nearest"),
        ref=lambda x: x.repeat(2, axis=2).repeat(2, axis=3),
        sample=lambda rng: (_r(rng, 1, 2, 3, 3),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.pad",
        fn=lambda x: F.pad(x, [1, 2], value=0.5),
        ref=lambda x: np.pad(x, ((0, 0), (0, 0), (0, 0), (1, 2)),
                             constant_values=0.5),
        sample=lambda rng: (_r(rng, 1, 2, 3, 3),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.pixel_shuffle",
        fn=lambda x: F.pixel_shuffle(x, 2),
        ref=_np_pixel_shuffle,
        sample=lambda rng: (_r(rng, 1, 8, 3, 3),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.pixel_unshuffle",
        fn=lambda x: F.pixel_unshuffle(x, 2),
        ref=lambda x: _np_pixel_unshuffle(x, 2),
        sample=lambda rng: (_r(rng, 1, 2, 4, 4),), grad_wrt=(0,)))

    # -- nn.functional: embeddings / norms / misc --------------------------
    register_op(OpSpec(
        name="nn.functional.embedding",
        fn=lambda i, w: F.embedding(i, w),
        ref=lambda i, w: w[i],
        sample=lambda rng: (_ints(rng, 0, 6, 3, 4), _r(rng, 6, 5)),
        grad_wrt=(1,)))
    register_op(OpSpec(
        name="nn.functional.one_hot",
        fn=lambda i: F.one_hot(i, 6),
        ref=lambda i: np.eye(6, dtype=np.float32)[i],
        sample=lambda rng: (_ints(rng, 0, 6, 7),), grad_wrt=()))
    unary("nn.functional.normalize",
          lambda x: F.normalize(x, axis=1),
          lambda x: x / np.maximum(
              np.linalg.norm(x, axis=1, keepdims=True), 1e-12))
    # cubed so sum-reduction grads are nonzero: both sum(y) and sum(y^2)
    # of a normalized group are constants, leaving only fd noise
    register_op(OpSpec(
        name="nn.functional.group_norm",
        fn=lambda x: F.group_norm(x, 2) ** 3,
        ref=lambda x: _np_group_norm(x, 2, 1e-5) ** 3,
        sample=lambda rng: (_r(rng, 2, 4, 3, 3),), grad_wrt=(0,),
        rtol=2e-5, atol=2e-5, grad_rtol=2e-2, grad_atol=2e-3))
    unary("nn.functional.rms_norm", F.rms_norm,
          lambda x: x / np.sqrt(np.mean(x * x, -1, keepdims=True) + 1e-6),
          rtol=2e-5, atol=2e-5)
    register_op(OpSpec(
        name="nn.functional.batch_norm_eval",
        fn=lambda x, m, v: F.batch_norm(x, m, v, training=False)[0],
        ref=lambda x, m, v: (x - m[None, :, None, None])
        / np.sqrt(v[None, :, None, None] + 1e-5),
        sample=lambda rng: (_r(rng, 2, 3, 4, 4), _r(rng, 3),
                            _pos(rng, 3)),
        grad_wrt=(0,), rtol=2e-5, atol=2e-5, grad_rtol=2e-2,
        grad_atol=2e-3))
    register_op(OpSpec(
        name="nn.functional.dropout_eval",
        fn=lambda x: F.dropout(x, 0.5, training=False),
        ref=lambda x: x, sample=lambda rng: (_r(rng, 3, 4),)))
    unary("nn.functional.swish", F.swish,
          lambda x: x / (1 + np.exp(-x)))
    register_op(OpSpec(
        name="nn.functional.prelu",
        fn=F.prelu,
        ref=lambda x, w: np.where(x >= 0, x, x * w[None, :, None, None]),
        # keep |x| away from the kink so finite differences are valid
        sample=lambda rng: (np.sign(_r(rng, 2, 3, 4, 4))
                            * (np.abs(_r(rng, 2, 3, 4, 4)) * 0.5 + 0.3),
                            _pos(rng, 3) * 0.1),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="nn.functional.softmax_mask_fuse_upper_triangle",
        fn=F.softmax_mask_fuse_upper_triangle,
        ref=_np_causal_softmax,
        sample=lambda rng: (_r(rng, 2, 2, 4, 4),),
        grad_wrt=(0,), rtol=2e-5, atol=2e-5))
    register_op(OpSpec(
        name="nn.functional.label_smooth",
        fn=lambda x: F.label_smooth(x, epsilon=0.1),
        ref=lambda x: x * 0.9 + 0.1 / x.shape[-1],
        sample=lambda rng: (np.eye(4, dtype=np.float32)[
            np.random.RandomState(0).randint(0, 4, 5)],)))

    # -- nn.functional: losses ---------------------------------------------
    register_op(OpSpec(
        name="nn.functional.l1_loss",
        fn=lambda a, b: F.l1_loss(a, b),
        ref=lambda a, b: np.mean(np.abs(a - b)),
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.smooth_l1_loss",
        fn=lambda a, b: F.smooth_l1_loss(a, b),
        ref=lambda a, b: np.mean(np.where(
            np.abs(a - b) < 1.0, 0.5 * (a - b) ** 2,
            np.abs(a - b) - 0.5)),
        sample=lambda rng: (_r(rng, 3, 4) * 2, _r(rng, 3, 4)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.square_error_cost",
        fn=F.square_error_cost,
        ref=lambda a, b: (a - b) ** 2,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="nn.functional.binary_cross_entropy_with_logits",
        fn=lambda lg, lb: F.binary_cross_entropy_with_logits(lg, lb),
        ref=lambda lg, lb: np.mean(
            np.maximum(lg, 0) - lg * lb + np.log1p(np.exp(-np.abs(lg)))),
        sample=lambda rng: (_r(rng, 3, 4),
                            (rng.rand(3, 4) > 0.5).astype(np.float32)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.nll_loss",
        fn=lambda lp, lb: F.nll_loss(lp, lb),
        ref=lambda lp, lb: -np.mean(lp[np.arange(lp.shape[0]), lb]),
        sample=lambda rng: (np.log(_np_softmax(_r(rng, 5, 6))),
                            _ints(rng, 0, 6, 5)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.pairwise_distance",
        fn=lambda a, b: F.pairwise_distance(a, b),
        ref=lambda a, b: np.linalg.norm(a - b + 1e-6, axis=1),
        sample=lambda rng: (_r(rng, 3, 5), _r(rng, 3, 5)),
        grad_wrt=(0, 1), rtol=1e-4, atol=1e-4))
    register_op(OpSpec(
        name="nn.functional.margin_ranking_loss",
        fn=lambda a, b, y: F.margin_ranking_loss(a, b, y, margin=0.2),
        ref=lambda a, b, y: np.mean(np.maximum(0, -y * (a - b) + 0.2)),
        sample=lambda rng: (_r(rng, 6), _r(rng, 6),
                            np.sign(_r(rng, 6)).astype(np.float32)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="nn.functional.hinge_embedding_loss",
        fn=lambda x, y: F.hinge_embedding_loss(x, y),
        ref=lambda x, y: np.mean(np.where(
            y == 1, x, np.maximum(0, 1.0 - x))),
        sample=lambda rng: (_pos(rng, 6),
                            np.where(rng.rand(6) > 0.5, 1.0,
                                     -1.0).astype(np.float32)),
        grad_wrt=(0,)))
    register_op(OpSpec(
        name="nn.functional.triplet_margin_loss",
        fn=lambda a, p, n: F.triplet_margin_loss(a, p, n),
        ref=lambda a, p, n: np.mean(np.maximum(
            np.sqrt(np.sum((a - p) ** 2, 1) + 1e-6)
            - np.sqrt(np.sum((a - n) ** 2, 1) + 1e-6) + 1.0, 0)),
        sample=lambda rng: (_r(rng, 4, 5), _r(rng, 4, 5), _r(rng, 4, 5)),
        grad_wrt=(0,), rtol=1e-4, atol=1e-4))
    register_op(OpSpec(
        name="nn.functional.cosine_embedding_loss",
        fn=lambda a, b, y: F.cosine_embedding_loss(a, b, y),
        ref=_np_cosine_embedding_loss,
        sample=lambda rng: (_r(rng, 4, 5), _r(rng, 4, 5),
                            np.where(np.random.RandomState(3).rand(4) > 0.5,
                                     1.0, -1.0).astype(np.float32)),
        grad_wrt=(0, 1), rtol=1e-4, atol=1e-4))

    # -- signal (reference python/paddle/signal.py) ------------------------
    register_op(OpSpec(
        name="signal.frame",
        fn=lambda x: pt.signal.frame(x, 4, 2),
        ref=lambda x: np.stack([x[..., i * 2:i * 2 + 4]
                                for i in range((x.shape[-1] - 4) // 2 + 1)],
                               axis=-1),
        sample=lambda rng: (_r(rng, 2, 12),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="signal.overlap_add",
        fn=lambda x: pt.signal.overlap_add(x, 2),
        ref=_np_overlap_add_hop2,
        sample=lambda rng: (_r(rng, 4, 3),), grad_wrt=(0,)))
    register_op(OpSpec(
        name="signal.stft",
        fn=lambda x: pt.signal.stft(x, n_fft=16, hop_length=8),
        ref=lambda x: _np_stft(x, 16, 8),
        sample=lambda rng: (_r(rng, 64),), grad_wrt=(),
        rtol=1e-4, atol=1e-4, bf16=False))

    # -- complex-number surface -------------------------------------------
    register_op(OpSpec(
        name="complex", fn=pt.complex,
        ref=lambda re, im: re + 1j * im,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(), bf16=False))
    register_op(OpSpec(
        name="real", fn=pt.real, ref=np.real,
        sample=lambda rng: (_r(rng, 3, 4) + 1j * _r(rng, 3, 4),),
        grad_wrt=()))
    register_op(OpSpec(
        name="imag", fn=pt.imag, ref=np.imag,
        sample=lambda rng: (_r(rng, 3, 4) + 1j * _r(rng, 3, 4),),
        grad_wrt=()))
    register_op(OpSpec(
        name="conj", fn=pt.conj, ref=np.conj,
        sample=lambda rng: (_r(rng, 3, 4) + 1j * _r(rng, 3, 4),),
        grad_wrt=()))
    register_op(OpSpec(
        name="angle", fn=pt.angle, ref=np.angle,
        sample=lambda rng: (_r(rng, 3, 4) + 1j * _r(rng, 3, 4),),
        grad_wrt=(), rtol=1e-4, atol=1e-5))

    def _np_linear_ce(hid, table, lab):
        logits = np.einsum("bsh,vh->bsv", hid.astype(np.float64),
                           table.astype(np.float64))
        m = logits.max(-1, keepdims=True)
        lse = (m[..., 0] + np.log(np.exp(logits - m).sum(-1)))
        picked = np.take_along_axis(logits, lab[..., None], -1)[..., 0]
        return np.mean(lse - picked)

    def _lce_sample(rng):
        hid = (_r(rng, 1, 128, 4) * 0.5).astype(np.float32)
        table = (_r(rng, 17, 4) * 0.5).astype(np.float32)
        lab = rng.randint(0, 17, (1, 128)).astype(np.int32)
        return (hid, table, lab)

    from .fused import linear_softmax_cross_entropy as _lce
    register_op(OpSpec(
        name="ops.fused.linear_softmax_cross_entropy",
        fn=lambda h, w, l: _lce(h, w, l),       # s=128 -> fused chunked path
        ref=_np_linear_ce,
        sample=_lce_sample,
        # numeric-grad only the small table arg (finite differences over the
        # [1,128,4] hidden would dominate the sweep's wall-clock); the
        # hidden gradient is analytically parity-checked against the
        # unfused reference in tests/test_ops.py::TestLinearCrossEntropy
        grad_wrt=(1,), rtol=1e-4, atol=1e-5))

    _populate_session3(unary, binary)


def _populate_session3(unary, binary) -> None:
    """Round-5 session-3 corpus: the __all__-parity ops (activation tail,
    N-D pools, unfold/fold, loss family, segment ops) join the tested
    contract.  grid_sample/affine_grid are covered by the identity/flip
    parity tests in tests/test_nn_ext.py (their numpy oracle is the
    op itself, so an OpSpec entry would be circular)."""
    import scipy.special as sps

    import paddle_tpu as pt
    import paddle_tpu.incubate as inc
    from paddle_tpu.nn import functional as F

    # -- activation tail ---------------------------------------------------
    unary("nn.functional.celu", lambda x: F.celu(x, 1.0),
          lambda x: np.maximum(x, 0) + np.minimum(np.expm1(x), 0))
    unary("nn.functional.selu", F.selu,
          lambda x: 1.0507009873554805 * np.where(
              x > 0, x, 1.6732632423543772 * np.expm1(x)))
    unary("nn.functional.softsign", F.softsign,
          lambda x: x / (1 + np.abs(x)))
    unary("nn.functional.softshrink", lambda x: F.softshrink(x, 0.5),
          lambda x: np.where(x > 0.5, x - 0.5,
                             np.where(x < -0.5, x + 0.5, 0.0)))
    unary("nn.functional.hardshrink", F.hardshrink,
          lambda x: np.where(np.abs(x) > 0.5, x, 0.0))
    unary("nn.functional.hardtanh", F.hardtanh,
          lambda x: np.clip(x, -1, 1))
    unary("nn.functional.tanhshrink", F.tanhshrink,
          lambda x: x - np.tanh(x))
    unary("nn.functional.thresholded_relu", F.thresholded_relu,
          lambda x: np.where(x > 1.0, x, 0.0))
    unary("nn.functional.log_sigmoid", F.log_sigmoid,
          lambda x: -np.log1p(np.exp(-x)))
    unary("nn.functional.maxout", lambda x: F.maxout(x, 2),
          lambda x: x.reshape(3, 2, 2, 4).max(axis=2),
          sample=lambda rng: (_r(rng, 3, 4, 4),))

    # -- math tail ---------------------------------------------------------
    unary("lgamma", pt.lgamma, sps.gammaln,
          sample=lambda rng: (_pos(rng, 3, 4),))
    unary("asinh", pt.asinh, np.arcsinh)
    unary("acosh", pt.acosh, np.arccosh,
          sample=lambda rng: (_pos(rng, 3, 4) + 1.0,))
    unary("atanh", pt.atanh, np.arctanh,
          # tanh-bounded sample keeps every draw inside arctanh's (-1, 1)
          # domain for any harness seed
          sample=lambda rng: (np.tanh(_r(rng, 3, 4)) * 0.95,))
    binary("floor_mod", pt.floor_mod, np.mod,
           sample=lambda rng: (_pos(rng, 3, 4), _pos(rng, 3, 4)),
           grad_wrt=())
    register_op(OpSpec(
        name="add_n",
        fn=lambda a, b, c: pt.add_n([a, b, c]),
        ref=lambda a, b, c: a + b + c,
        sample=lambda rng: (_r(rng, 3, 4), _r(rng, 3, 4), _r(rng, 3, 4)),
        grad_wrt=(0, 1, 2)))

    # -- manipulation tail -------------------------------------------------
    unary("reverse", lambda x: pt.reverse(x, [1]),
          lambda x: x[:, ::-1], sample=lambda rng: (_r(rng, 3, 4),))
    unary("slice", lambda x: pt.slice(x, [1], [1], [3]),
          lambda x: x[:, 1:3], sample=lambda rng: (_r(rng, 3, 4),))
    unary("strided_slice", lambda x: pt.strided_slice(x, [1], [0], [4], [2]),
          lambda x: x[:, 0:4:2], sample=lambda rng: (_r(rng, 3, 4),))
    unary("crop", lambda x: pt.crop(x, shape=[2, -1], offsets=[1, 0]),
          lambda x: x[1:3], sample=lambda rng: (_r(rng, 4, 3),))
    register_op(OpSpec(
        name="scatter_nd_add",
        fn=lambda x, u: pt.scatter_nd_add(
            x, np.array([[1], [1], [3]]), u),
        ref=lambda x, u: _np_scatter_nd_add(x, np.array([[1], [1], [3]]), u),
        sample=lambda rng: (_r(rng, 5), _r(rng, 3)),
        grad_wrt=(0, 1)))
    register_op(OpSpec(
        name="shard_index",
        fn=lambda: pt.shard_index(
            np.array([1, 9, 10, 19], np.int64), 20, 2, 0),
        ref=lambda: np.array([1, 9, -1, -1], np.int64),
        sample=lambda rng: (),
        grad_wrt=(), bf16=False))

    # -- pooling / shape ---------------------------------------------------
    register_op(OpSpec(
        name="nn.functional.max_pool3d",
        fn=lambda x: F.max_pool3d(x, 2),
        ref=lambda x: x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7)),
        sample=lambda rng: (_r(rng, 1, 2, 4, 4, 4),)))
    register_op(OpSpec(
        name="nn.functional.avg_pool3d",
        fn=lambda x: F.avg_pool3d(x, 2),
        ref=lambda x: x.reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(axis=(3, 5, 7)),
        sample=lambda rng: (_r(rng, 1, 2, 4, 4, 4),)))
    register_op(OpSpec(
        name="nn.functional.adaptive_avg_pool1d",
        fn=lambda x: F.adaptive_avg_pool1d(x, 5),
        ref=lambda x: x.reshape(2, 3, 5, 2).mean(-1),
        sample=lambda rng: (_r(rng, 2, 3, 10),)))
    register_op(OpSpec(
        name="nn.functional.unfold",
        fn=lambda x: F.unfold(x, 2, 2),
        ref=_np_unfold_2x2,
        sample=lambda rng: (_r(rng, 2, 3, 4, 4),)))
    register_op(OpSpec(
        name="nn.functional.fold",
        fn=lambda u: F.fold(u, (4, 4), 2, 2),
        ref=_np_fold_2x2,
        sample=lambda rng: (_r(rng, 2, 12, 4),)))
    register_op(OpSpec(
        name="nn.functional.zeropad2d",
        fn=lambda x: F.zeropad2d(x, [1, 2, 0, 1]),
        ref=lambda x: np.pad(x, ((0, 0), (0, 0), (0, 1), (1, 2))),
        sample=lambda rng: (_r(rng, 2, 2, 3, 3),)))

    # -- norm / vision -----------------------------------------------------
    register_op(OpSpec(
        name="nn.functional.local_response_norm",
        fn=lambda x: F.local_response_norm(x, size=3, alpha=1e-2,
                                           beta=0.5, k=1.0),
        ref=lambda x: _np_lrn(x, 3, 1e-2, 0.5, 1.0),
        # keep samples off 0: |x| kinks there and the centered numeric
        # grad picks up the kink noise
        sample=lambda rng: (_pos(rng, 2, 5, 3, 3),),
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="nn.functional.instance_norm",
        fn=F.instance_norm,
        ref=lambda x: (x - x.mean(axis=(2, 3), keepdims=True))
        / np.sqrt(x.var(axis=(2, 3), keepdims=True) + 1e-5),
        sample=lambda rng: (_r(rng, 2, 3, 5, 5),), rtol=1e-4,
        grad_rtol=2e-2, grad_atol=2e-3))
    register_op(OpSpec(
        name="nn.functional.bilinear",
        fn=F.bilinear,
        ref=lambda a, b, w: np.einsum("ni,oij,nj->no", a, w, b),
        sample=lambda rng: (_r(rng, 3, 2), _r(rng, 3, 4), _r(rng, 5, 2, 4)),
        grad_wrt=(0, 1, 2)))
    register_op(OpSpec(
        name="nn.functional.temporal_shift",
        fn=lambda x: F.temporal_shift(x, 2, 0.25),
        ref=lambda x: _np_temporal_shift(x, 2, 0.25),
        sample=lambda rng: (_r(rng, 4, 8, 2, 2),)))

    # -- losses ------------------------------------------------------------
    register_op(OpSpec(
        name="nn.functional.binary_cross_entropy",
        fn=F.binary_cross_entropy,
        ref=lambda p, y: float(np.mean(
            -(y * np.log(p) + (1 - y) * np.log(1 - p)))),
        sample=lambda rng: (
            (rng.rand(8) * 0.8 + 0.1).astype(np.float32),
            rng.randint(0, 2, 8).astype(np.float32)),
        grad_wrt=(0,), rtol=1e-4))
    register_op(OpSpec(
        name="nn.functional.log_loss",
        fn=lambda p, y: F.log_loss(p, y, 1e-4),
        ref=lambda p, y: -(y * np.log(p + 1e-4)
                           + (1 - y) * np.log(1 - p + 1e-4)),
        sample=lambda rng: (
            (rng.rand(8) * 0.8 + 0.1).astype(np.float32),
            rng.randint(0, 2, 8).astype(np.float32)),
        grad_wrt=(0,), rtol=1e-4))
    register_op(OpSpec(
        name="nn.functional.sigmoid_focal_loss",
        fn=lambda x, y: F.sigmoid_focal_loss(x, y, reduction="sum"),
        ref=_np_focal,
        sample=lambda rng: (_r(rng, 8), rng.randint(0, 2, 8).astype(
            np.float32)),
        grad_wrt=(0,), rtol=1e-4))
    register_op(OpSpec(
        name="nn.functional.softmax_with_cross_entropy",
        fn=lambda x, y: F.softmax_with_cross_entropy(x, y),
        ref=lambda x, y: -np.log(
            _np_softmax(x))[np.arange(4), y][:, None],
        sample=lambda rng: (_r(rng, 4, 7),
                            rng.randint(0, 7, 4).astype(np.int32)),
        grad_wrt=(0,), rtol=1e-4))

    # -- segment ops (incubate) --------------------------------------------
    seg_ids = np.array([0, 0, 1, 2, 2], np.int32)
    register_op(OpSpec(
        name="incubate.segment_sum",
        fn=lambda x: inc.segment_sum(x, seg_ids),
        ref=lambda x: np.stack([x[:2].sum(0), x[2], x[3:].sum(0)]),
        sample=lambda rng: (_r(rng, 5, 3),)))
    register_op(OpSpec(
        name="incubate.segment_mean",
        fn=lambda x: inc.segment_mean(x, seg_ids),
        ref=lambda x: np.stack([x[:2].mean(0), x[2], x[3:].mean(0)]),
        sample=lambda rng: (_r(rng, 5, 3),)))
    register_op(OpSpec(
        name="incubate.segment_max",
        fn=lambda x: inc.segment_max(x, seg_ids),
        ref=lambda x: np.stack([x[:2].max(0), x[2], x[3:].max(0)]),
        sample=lambda rng: (_r(rng, 5, 3),), grad_wrt=()))
    register_op(OpSpec(
        name="incubate.segment_min",
        fn=lambda x: inc.segment_min(x, seg_ids),
        ref=lambda x: np.stack([x[:2].min(0), x[2], x[3:].min(0)]),
        sample=lambda rng: (_r(rng, 5, 3),), grad_wrt=()))



def _nan_sample(rng):
    x = _r(rng, 3, 5)
    x[0, 1] = np.nan
    x[2, 3] = np.nan
    return (x,)


def _np_overlap_add_hop2(x):
    fl, nf = x.shape
    out = np.zeros((nf - 1) * 2 + fl, x.dtype)
    for j in range(nf):
        out[j * 2:j * 2 + fl] += x[:, j]
    return out


def _np_stft(x, n_fft, hop):
    pad = n_fft // 2
    xp = np.pad(x, (pad, pad), mode="reflect")
    nf = 1 + (len(xp) - n_fft) // hop
    frames = np.stack([xp[i * hop:i * hop + n_fft] for i in range(nf)], -1)
    return np.fft.rfft(frames, axis=0)


def _np_mode_rows(x):
    """Most frequent value per row; ties resolve to the LARGEST value
    (mode_op semantics, matching tensor_ops.mode)."""
    out = []
    for r in x:
        vals, counts = np.unique(r, return_counts=True)
        best = vals[counts == counts.max()]
        out.append(best.max())
    return np.asarray(out, x.dtype)


def _np_put_along_axis(x, i, v):
    out = x.copy()
    np.put_along_axis(out, i, v, axis=1)
    return out


def _np_scatter_overwrite(x, index, updates):
    out = x.copy()
    out[index] = updates
    return out


def _np_renorm(x, p=2.0, axis=0, max_norm=1.0):
    # slice i along `axis` scaled so its p-norm is <= max_norm
    out = x.copy()
    norms = np.linalg.norm(x.reshape(x.shape[0], -1) if axis == 0 else x,
                           axis=1 if axis == 0 else axis)
    for i in range(x.shape[axis]):
        n = norms[i]
        if n > max_norm:
            out[i] = x[i] * (max_norm / n)
    return out


def _np_adaptive_pool(x, out, reduce):
    n, c, h, w = x.shape
    res = np.zeros((n, c, out, out), x.dtype)
    for i in range(out):
        for j in range(out):
            hs, he = (i * h) // out, -(-((i + 1) * h) // out)
            ws, we = (j * w) // out, -(-((j + 1) * w) // out)
            res[:, :, i, j] = reduce(x[:, :, hs:he, ws:we], axis=(2, 3))
    return res


def _np_conv2d(x, w, pad=0):
    n, cin, h, wd = x.shape
    cout, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh, ow = h + 2 * pad - kh + 1, wd + 2 * pad - kw + 1
    out = np.zeros((n, cout, oh, ow), np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i:i + kh, j:j + kw]
            out[:, :, i, j] = np.einsum("ncij,ocij->no", patch, w)
    return out


def _np_pixel_shuffle(x):
    n, c, h, w = x.shape
    r = 2
    y = x.reshape(n, c // (r * r), r, r, h, w)
    return y.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r),
                                                 h * r, w * r)


def _np_pixel_unshuffle(x, r):
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // r, r, w // r, r)
    return y.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r,
                                                 h // r, w // r)


def _np_group_norm(x, groups, eps):
    n, c, h, w = x.shape
    y = x.reshape(n, groups, c // groups, h, w)
    mu = y.mean(axis=(2, 3, 4), keepdims=True)
    var = y.var(axis=(2, 3, 4), keepdims=True)
    return ((y - mu) / np.sqrt(var + eps)).reshape(x.shape)


def _np_causal_softmax(x):
    s, t = x.shape[-2], x.shape[-1]
    mask = np.triu(np.ones((s, t), bool), k=1)
    xm = np.where(mask, -1e9, x)
    return _np_softmax(xm)


def _np_cosine_embedding_loss(a, b, y, margin=0.0):
    cos = np.sum(a * b, 1) / np.maximum(
        np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1), 1e-12)
    loss = np.where(y == 1, 1 - cos, np.maximum(0, cos - margin))
    return np.mean(loss)


def _erf_scalar(x: float) -> float:
    import math
    return math.erf(float(x))


def _erfinv_scalar(y: float) -> float:
    # bisection on erf — dependency-free numpy reference
    import math
    lo, hi = -6.0, 6.0
    for _ in range(80):
        mid = (lo + hi) / 2
        if math.erf(mid) < y:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2


def _np_softmax(x):
    e = np.exp(x - np.max(x, axis=-1, keepdims=True))
    return e / np.sum(e, axis=-1, keepdims=True)


def _np_layer_norm(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b


def _np_scatter_nd_add(x, idx, u):
    out = x.copy()
    for i, j in enumerate(idx[:, 0]):
        out[j] += u[i]
    return out


def _np_unfold_2x2(x):
    n, c, h, w = x.shape
    cols = []
    for i in range(0, h, 2):
        for j in range(0, w, 2):
            cols.append(x[:, :, i:i + 2, j:j + 2].reshape(n, c * 4))
    return np.stack(cols, axis=-1)


def _np_fold_2x2(u):
    n, ckk, L = u.shape
    c = ckk // 4
    hw = int(np.sqrt(L)) * 2
    out = np.zeros((n, c, hw, hw), u.dtype)
    col = 0
    for i in range(0, hw, 2):
        for j in range(0, hw, 2):
            out[:, :, i:i + 2, j:j + 2] += u[:, :, col].reshape(n, c, 2, 2)
            col += 1
    return out


def _np_lrn(x, size, alpha, beta, k):
    n, c, h, w = x.shape
    acc = np.zeros_like(x)
    lo = (size - 1) // 2
    for ci in range(c):
        a, b = max(0, ci - lo), min(c, ci + (size - 1 - lo) + 1)
        acc[:, ci] = (x[:, a:b] ** 2).sum(1)
    return x / (k + alpha / size * acc) ** beta


def _np_temporal_shift(x, seg, ratio):
    nt, c, h, w = x.shape
    n = nt // seg
    xr = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    out = np.zeros_like(xr)
    out[:, :-1, :c1] = xr[:, 1:, :c1]
    out[:, 1:, c1:c2] = xr[:, :-1, c1:c2]
    out[:, :, c2:] = xr[:, :, c2:]
    return out.reshape(nt, c, h, w)


def _np_focal(x, y, alpha=0.25, gamma=2.0):
    p = 1 / (1 + np.exp(-x))
    ce = np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    return float(np.sum(a_t * (1 - p_t) ** gamma * ce))
