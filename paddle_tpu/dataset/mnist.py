"""paddle.dataset.mnist (reference dataset/mnist.py): train()/test()
reader factories yielding (image [28,28] float32 in [0,1], int label)."""
from ._common import img_label, make_readers


def _mk(mode):
    from ..vision.datasets import MNIST
    return MNIST(mode=mode)


train, test = make_readers(lambda: _mk("train"), lambda: _mk("test"),
                           img_label)
