"""paddle.dataset.cifar (reference dataset/cifar.py): train10()/test10()
(+ train/test aliases) over the Cifar10 corpus."""
from ._common import img_label, make_readers


def _mk(mode):
    from ..vision.datasets import Cifar10
    return Cifar10(mode=mode)


train10, test10 = make_readers(lambda: _mk("train"), lambda: _mk("test"),
                               img_label)
train, test = train10, test10
