"""paddle.dataset.uci_housing (reference dataset/uci_housing.py):
yields (features float32[13], target float32[1])."""
import numpy as np

from ._common import make_readers


def _mk(mode):
    from ..text.datasets import UCIHousing
    return UCIHousing(mode=mode)


train, test = make_readers(
    lambda: _mk("train"), lambda: _mk("test"),
    lambda s: (np.asarray(s[0], np.float32),
               np.asarray(s[1], np.float32)))
