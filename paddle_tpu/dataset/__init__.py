"""Legacy paddle.dataset facade (reference python/paddle/dataset/*):
real submodules exposing the reference's ``train()``/``test()`` reader
factories (so ``import paddle_tpu.dataset.mnist`` works, the dominant
idiom in ported tutorial code) over the same corpora the modern
``vision.datasets`` / ``text.datasets`` classes serve (zero-egress
synthetic-learnable defaults)."""
from . import (cifar, flowers, imdb, imikolov,  # noqa: F401
               mnist, uci_housing)

__all__ = ["mnist", "cifar", "flowers", "uci_housing", "imdb",
           "imikolov"]
