"""paddle.dataset.flowers (reference dataset/flowers.py)."""
from ._common import img_label, make_readers


def _mk(mode):
    from ..vision.datasets import Flowers
    return Flowers(mode=mode)


train, test = make_readers(lambda: _mk("train"), lambda: _mk("test"),
                           img_label)
