"""paddle.dataset.imdb (reference dataset/imdb.py): (word ids, 0/1)."""
import numpy as np

from ._common import make_readers


def _mk(mode):
    from ..text.datasets import Imdb
    return Imdb(mode=mode)


train, test = make_readers(
    lambda: _mk("train"), lambda: _mk("test"),
    lambda s: (np.asarray(s[0]), int(np.asarray(s[1]))))
