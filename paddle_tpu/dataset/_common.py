"""Shared reader-factory plumbing for the legacy dataset facade."""
from __future__ import annotations

import numpy as np


def make_readers(make_train, make_test, to_tuple):
    """(train, test) reader factories over Dataset constructors."""
    def _reader(mk):
        def factory():
            def reader():
                ds = mk()
                for i in range(len(ds)):
                    yield to_tuple(ds[i])
            return reader
        return factory
    return _reader(make_train), _reader(make_test)


def img_label(sample):
    img, label = sample
    return (np.asarray(img, np.float32) / 255.0,
            int(np.asarray(label).reshape(-1)[0]))
