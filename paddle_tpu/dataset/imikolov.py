"""paddle.dataset.imikolov (reference dataset/imikolov.py): n-gram
tuples."""
import numpy as np

from ._common import make_readers


def _mk(mode):
    from ..text.datasets import Imikolov
    return Imikolov(mode=mode)


train, test = make_readers(
    lambda: _mk("train"), lambda: _mk("test"),
    lambda s: tuple(np.asarray(x) for x in s))
