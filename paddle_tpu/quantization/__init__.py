"""Quantization: QAT (fake-quant) + PTQ (observer/calibration) + int8 convert.

Reference surface (E7):
- QAT layer swap: fluid/contrib/slim/quantization/imperative/qat.py:42
  ``ImperativeQuantAware`` (knobs :50-54 weight/activation type, bits,
  moving_rate) — walks the model and replaces Linear/Conv2D with quantized
  wrappers.
- Fake-quant layers: python/paddle/nn/quant/quant_layers.py:46
  ``FakeQuantAbsMax``, :128 ``FakeQuantMovingAverageAbsMax``, :226
  ``FakeQuantChannelWiseAbsMax``, :309 ``MovingAverageAbsMaxScale``, :396/:591
  ``QuantizedConv2D``/``QuantizedLinear``.
- PTQ: post_training_quantization.py:97 ``PostTrainingQuantization``
  (calibrate → scales → int8 weights; :1101 quantize_weight_to_int).

TPU-first design:
- fake quant-dequant is a pure function with a straight-through estimator
  (``x + stop_gradient(qdq(x) - x)``) — no custom kernels needed, XLA fuses
  the round/clip chain into neighbors.
- moving-average scales are Layer buffers, so they ride the same
  mutable-buffer path as BN running stats (trace-safe under ``apply``).
- converted int8 inference runs the matmul on the MXU in int8 via
  ``lax.dot_general(..., preferred_element_type=int32)`` then rescales —
  the TPU-native analog of the reference's cuDNN/MKL int8 engines.
"""
from __future__ import annotations

from typing import Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.errors import enforce
from ..nn import functional as F
from ..nn.layer import Layer, Parameter
from ..nn.layers import Conv2D, Linear

__all__ = [
    "quant_dequant", "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
    "FakeQuantChannelWiseAbsMax", "MovingAverageAbsMaxScale",
    "QuantizedLinear", "QuantizedConv2D", "ImperativeQuantAware",
    "PostTrainingQuantization", "quantize_weight_to_int", "Int8Linear",
    "Int8Conv2D",
]


# ---------------------------------------------------------------------------
# functional core
# ---------------------------------------------------------------------------
def _qdq(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def quant_dequant(x, scale, bits: int = 8):
    """Symmetric fake quantization with a straight-through gradient."""
    qmax = float(2 ** (bits - 1) - 1)
    return x + lax.stop_gradient(_qdq(x, scale, qmax) - x)


# ---------------------------------------------------------------------------
# fake-quant layers (QAT building blocks)
# ---------------------------------------------------------------------------
class FakeQuantAbsMax(Layer):
    """Per-tensor abs-max scale computed on the fly (weights)."""

    def __init__(self, bits: int = 8):
        super().__init__()
        self.bits = bits

    def forward(self, x):
        return quant_dequant(x, jnp.max(jnp.abs(x)), self.bits)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel abs-max scale (conv/linear weights)."""

    def __init__(self, bits: int = 8, channel_axis: int = 0):
        super().__init__()
        self.bits = bits
        self.channel_axis = channel_axis

    def forward(self, x):
        axes = tuple(i for i in range(x.ndim) if i != self.channel_axis)
        scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
        return quant_dequant(x, scale, self.bits)


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quant with an EMA abs-max scale buffer.

    Training updates ``scale ← r*scale + (1-r)*absmax(x)`` through the
    mutable-buffer path; eval uses the frozen scale.  ``mode="max"`` turns
    the EMA into a running max — the reference PTQ's abs_max calibration
    algorithm (post_training_quantization.py algo='abs_max')."""

    def __init__(self, bits: int = 8, moving_rate: float = 0.9,
                 mode: str = "ema"):
        super().__init__()
        self.bits = bits
        self.moving_rate = moving_rate
        self.mode = mode
        # None → follow self.training (QAT); True/False force collection
        # on/off independent of train mode (PTQ calibrates with the model
        # in eval so BN stats and dropout stay frozen)
        self.observe = None
        init = 1.0 if mode == "ema" else 0.0
        self.register_buffer("scale", jnp.asarray(init, jnp.float32))

    def forward(self, x):
        scale = self._buffers["scale"]
        if self.training if self.observe is None else self.observe:
            batch = jnp.max(jnp.abs(lax.stop_gradient(x))).astype(jnp.float32)
            if self.mode == "max":
                scale = jnp.maximum(scale, batch)
            else:
                scale = (self.moving_rate * scale
                         + (1 - self.moving_rate) * batch)
            self._update_buffer("scale", scale)
        return quant_dequant(x, scale, self.bits)


class MovingAverageAbsMaxScale(Layer):
    """Observer only: tracks the EMA abs-max scale without quantizing
    (quant_layers.py:309 — used to record output scales for deployment)."""

    def __init__(self, moving_rate: float = 0.9):
        super().__init__()
        self.moving_rate = moving_rate
        self.register_buffer("scale", jnp.asarray(1.0, jnp.float32))

    def forward(self, x):
        if self.training:
            batch = jnp.max(jnp.abs(lax.stop_gradient(x))).astype(jnp.float32)
            scale = (self.moving_rate * self._buffers["scale"]
                     + (1 - self.moving_rate) * batch)
            self._update_buffer("scale", scale)
        return x


def _weight_quanter(kind: str, bits: int) -> Layer:
    if kind == "abs_max":
        return FakeQuantAbsMax(bits)
    if kind == "channel_wise_abs_max":
        return FakeQuantChannelWiseAbsMax(bits)
    raise ValueError(f"unsupported weight_quantize_type {kind!r}")


def _act_quanter(kind: str, bits: int, moving_rate: float) -> Optional[Layer]:
    if kind == "moving_average_abs_max":
        return FakeQuantMovingAverageAbsMax(bits, moving_rate)
    if kind == "abs_max":
        return FakeQuantAbsMax(bits)
    if kind == "none":
        return None
    raise ValueError(f"unsupported activation_quantize_type {kind!r}")


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------
class QuantizedLinear(Layer):
    """Linear with fake-quantized weight + input (quant_layers.py:591)."""

    def __init__(self, layer: Linear, weight_quantize_type: str,
                 activation_quantize_type: str, weight_bits: int,
                 activation_bits: int, moving_rate: float):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        # linear weight is (in, out): output channel axis = 1
        self.weight_quanter = _weight_quanter(weight_quantize_type,
                                              weight_bits)
        if isinstance(self.weight_quanter, FakeQuantChannelWiseAbsMax):
            self.weight_quanter.channel_axis = 1
        self.input_quanter = _act_quanter(activation_quantize_type,
                                          activation_bits, moving_rate)

    def forward(self, x):
        if self.input_quanter is not None:
            x = self.input_quanter(x)
        w = self.weight_quanter(self.weight.value
                                if isinstance(self.weight, Parameter)
                                else self.weight)
        return F.linear(x, w, self.bias)


class QuantizedConv2D(Layer):
    """Conv2D with fake-quantized weight + input (quant_layers.py:396)."""

    def __init__(self, layer: Conv2D, weight_quantize_type: str,
                 activation_quantize_type: str, weight_bits: int,
                 activation_bits: int, moving_rate: float):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._stride = layer.stride
        self._padding = layer.padding
        self._dilation = layer.dilation
        self._groups = layer.groups
        self._data_format = layer.data_format
        self.weight_quanter = _weight_quanter(weight_quantize_type,
                                              weight_bits)  # OIHW: axis 0
        self.input_quanter = _act_quanter(activation_quantize_type,
                                          activation_bits, moving_rate)

    def forward(self, x):
        if self.input_quanter is not None:
            x = self.input_quanter(x)
        w = self.weight_quanter(self.weight.value
                                if isinstance(self.weight, Parameter)
                                else self.weight)
        return F.conv2d(x, w, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


# ---------------------------------------------------------------------------
# QAT driver
# ---------------------------------------------------------------------------
_SWAP = {Linear: QuantizedLinear, Conv2D: QuantizedConv2D}


class ImperativeQuantAware:
    """QAT layer-swap driver (imperative/qat.py:42).

    ``quantize(model)`` rewrites the model in place: every Linear/Conv2D
    becomes its fake-quant wrapper sharing the original Parameters, so the
    optimizer state and state_dict keys keep working."""

    def __init__(self, weight_quantize_type: str = "abs_max",
                 activation_quantize_type: str = "moving_average_abs_max",
                 weight_bits: int = 8, activation_bits: int = 8,
                 moving_rate: float = 0.9):
        enforce(1 < weight_bits <= 16, "weight_bits must be in (1, 16]")
        enforce(1 < activation_bits <= 16,
                "activation_bits must be in (1, 16]")
        self._kw = dict(weight_quantize_type=weight_quantize_type,
                        activation_quantize_type=activation_quantize_type,
                        weight_bits=weight_bits,
                        activation_bits=activation_bits,
                        moving_rate=moving_rate)

    def quantize(self, model: Layer) -> Layer:
        for name, sub in list(model._sub_layers.items()):
            wrapper = _SWAP.get(type(sub))
            if wrapper is not None:
                model._sub_layers[name] = wrapper(sub, **self._kw)
            else:
                self.quantize(sub)
        return model


# ---------------------------------------------------------------------------
# PTQ + int8 conversion
# ---------------------------------------------------------------------------
def _int_dtype(bits: int):
    return jnp.int8 if bits <= 8 else (jnp.int16 if bits <= 16 else jnp.int32)


def quantize_weight_to_int(w, bits: int = 8,
                           channel_axis: Optional[int] = None
                           ) -> Tuple[jax.Array, jax.Array]:
    """(int weight, float scale) — post_training_quantization.py:1101.
    Storage dtype follows ``bits`` (int8 up to 8 bits, else int16/int32)."""
    qmax = float(2 ** (bits - 1) - 1)
    if channel_axis is None:
        scale = jnp.max(jnp.abs(w))
    else:
        axes = tuple(i for i in range(w.ndim) if i != channel_axis)
        scale = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    scale = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(w / scale * qmax), -qmax, qmax
                 ).astype(_int_dtype(bits))
    return q, scale / qmax


class Int8Linear(Layer):
    """Converted int8 inference Linear: int8×int8 MXU matmul with int32
    accumulation, then a per-channel rescale (the TPU-native deployment
    form of the reference's quantized inference engines)."""

    def __init__(self, layer, bits: int = 8):
        """``layer``: anything exposing ``.weight``/``.bias`` with a (in,
        out) weight — a plain Linear or a QuantizedLinear wrapper."""
        super().__init__()
        w = layer.weight.value if isinstance(layer.weight, Parameter) \
            else layer.weight
        q, s = quantize_weight_to_int(w, bits, channel_axis=1)
        self.register_buffer("qweight", q)
        self.register_buffer("wscale", s)        # (1, out)
        self.bias = layer.bias
        self.bits = bits
        self.register_buffer("in_scale", jnp.asarray(1.0, jnp.float32))

    def forward(self, x):
        qmax = float(2 ** (self.bits - 1) - 1)
        in_scale = jnp.maximum(self._buffers["in_scale"], 1e-9)
        xq = jnp.clip(jnp.round(x / in_scale * qmax), -qmax, qmax
                      ).astype(_int_dtype(self.bits))
        acc = lax.dot_general(
            xq, self._buffers["qweight"],
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * self._buffers["wscale"] \
            * (in_scale / qmax)
        if self.bias is not None:
            b = self.bias.value if isinstance(self.bias, Parameter) \
                else self.bias
            y = y + b
        return y


class Int8Conv2D(Layer):
    """Converted int8 inference Conv2D: int8 conv with int32 accumulation
    (``lax.conv_general_dilated`` + preferred_element_type), per-output-
    channel weight rescale."""

    def __init__(self, layer: QuantizedConv2D, bits: int = 8):
        super().__init__()
        w = layer.weight.value if isinstance(layer.weight, Parameter) \
            else layer.weight
        self._data_format = layer._data_format
        # weight layout is OIHW for both data formats (paddle contract:
        # data_format describes x only) — output channels are axis 0
        q, s = quantize_weight_to_int(w, bits, channel_axis=0)
        self.register_buffer("qweight", q)
        self.bias = layer.bias
        self.bits = bits
        self._stride = layer._stride
        self._padding = layer._padding
        self._dilation = layer._dilation
        self._groups = layer._groups
        if self._data_format == "NCHW":
            self.register_buffer("wscale", s.reshape(1, -1, 1, 1))
        else:  # NHWC: channels last
            self.register_buffer("wscale", s.reshape(1, 1, 1, -1))
        self.register_buffer("in_scale", jnp.asarray(1.0, jnp.float32))

    def forward(self, x):
        qmax = float(2 ** (self.bits - 1) - 1)
        in_scale = jnp.maximum(self._buffers["in_scale"], 1e-9)
        xq = jnp.clip(jnp.round(x / in_scale * qmax), -qmax, qmax
                      ).astype(_int_dtype(self.bits))
        stride = (self._stride, self._stride) \
            if isinstance(self._stride, int) else tuple(self._stride)
        dil = (self._dilation, self._dilation) \
            if isinstance(self._dilation, int) else tuple(self._dilation)
        if isinstance(self._padding, str):
            pad = self._padding.upper()
        else:
            p = (self._padding, self._padding) \
                if isinstance(self._padding, int) else tuple(self._padding)
            pad = [(p[0], p[0]), (p[1], p[1])]
        dn = lax.conv_dimension_numbers(
            x.shape, self._buffers["qweight"].shape,
            ("NCHW", "OIHW", "NCHW") if self._data_format == "NCHW"
            else ("NHWC", "OIHW", "NHWC"))
        acc = lax.conv_general_dilated(
            xq, self._buffers["qweight"], window_strides=stride,
            padding=pad, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=self._groups,
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * self._buffers["wscale"] \
            * (in_scale / qmax)
        if self.bias is not None:
            b = self.bias.value if isinstance(self.bias, Parameter) \
                else self.bias
            y = y + (b[None, :, None, None]
                     if self._data_format == "NCHW" else b)
        return y


class PostTrainingQuantization:
    """Calibration-based PTQ (post_training_quantization.py:97).

    1. ``quantize(model, calibration_batches)``: attach moving-average
       observers to every Linear/Conv2D input, run the batches, freeze
       scales (the abs_max calibration algo).
    2. ``convert(model)``: swap observed Linears/Conv2Ds to
       Int8Linear/Int8Conv2D carrying the calibrated input scale.
    """

    def __init__(self, activation_bits: int = 8, weight_bits: int = 8,
                 moving_rate: float = 0.9):
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self.moving_rate = moving_rate

    def quantize(self, model: Layer, calibration_data: Iterable) -> Layer:
        qat = ImperativeQuantAware(
            weight_quantize_type="channel_wise_abs_max",
            activation_quantize_type="moving_average_abs_max",
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            moving_rate=self.moving_rate)
        qat.quantize(model)
        observers = [l for l in model.sublayers()
                     if isinstance(l, FakeQuantMovingAverageAbsMax)]
        for obs in observers:        # abs_max calibration: running max
            obs.mode = "max"
            obs.observe = True
            obs._buffers["scale"] = jnp.asarray(0.0, jnp.float32)
        # model stays in eval: BN running stats and dropout must see
        # inference conditions — only the observers collect
        model.eval()
        for batch in calibration_data:
            model(batch)             # eager: scale buffers update in place
        for obs in observers:
            obs.observe = False
        return model

    def convert(self, model: Layer) -> Layer:
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, QuantizedLinear):
                int8 = Int8Linear(sub, self.weight_bits)
            elif isinstance(sub, QuantizedConv2D):
                int8 = Int8Conv2D(sub, self.weight_bits)
            else:
                self.convert(sub)
                continue
            if not isinstance(sub.input_quanter,
                              FakeQuantMovingAverageAbsMax):
                raise ValueError(
                    "convert() needs a calibrated input observer on every "
                    "quantized layer; run PostTrainingQuantization."
                    "quantize(model, calibration_data) first (got "
                    f"{type(sub.input_quanter).__name__} on {name!r})")
            scale = sub.input_quanter._buffers["scale"]
            if float(scale) <= 0.0:
                raise ValueError(
                    f"input observer on {name!r} was never calibrated "
                    "(scale=0); pass at least one calibration batch to "
                    "quantize() before convert()")
            int8._buffers["in_scale"] = scale
            model._sub_layers[name] = int8
        return model
