"""paddle.linalg namespace (reference: python/paddle/tensor/linalg.py —
cholesky:*, det, slogdet, eig/eigh/eigvals/eigvalsh, inv, lstsq, lu,
matrix_power, matrix_rank, multi_dot, norm, pinv, qr, solve, svd,
triangular_solve, cov, corrcoef).

TPU notes: decompositions (svd/qr/eig/cholesky) lower to LAPACK-style XLA
custom calls — supported on TPU but not MXU-bound; the GEMM-shaped members
(multi_dot, matrix_power, solve via factorization) are.  All functions
accept batched inputs per jnp.linalg broadcasting rules, matching the
reference's batched-op semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "inv", "lstsq", "lu", "matrix_power",
    "matrix_rank", "multi_dot", "norm", "pinv", "qr", "slogdet", "solve",
    "svd", "triangular_solve",
]


def _arr(x):
    return x if isinstance(x, jax.Array) else jnp.asarray(x)


def cholesky(x, upper: bool = False):
    l = jnp.linalg.cholesky(_arr(x))
    return jnp.swapaxes(l, -1, -2).conj() if upper else l


def cholesky_solve(x, y, upper: bool = False):
    """Solve A @ out = x given y = chol factor of A."""
    y = _arr(y)
    l = jnp.swapaxes(y, -1, -2).conj() if upper else y
    z = jax.scipy.linalg.solve_triangular(l, _arr(x), lower=True)
    return jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(l, -1, -2).conj(), z, lower=False)


def det(x):
    return jnp.linalg.det(_arr(x))


def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(_arr(x))
    return jnp.stack([sign, logabs])     # paddle returns one stacked tensor


def eig(x):
    return jnp.linalg.eig(_arr(x))


def eigh(x, UPLO: str = "L"):
    return jnp.linalg.eigh(_arr(x), UPLO=UPLO)


def eigvals(x):
    return jnp.linalg.eigvals(_arr(x))


def eigvalsh(x, UPLO: str = "L"):
    return jnp.linalg.eigvalsh(_arr(x), UPLO=UPLO)


def inv(x):
    return jnp.linalg.inv(_arr(x))


def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(_arr(x), _arr(y), rcond=rcond)
    return sol, res, rank, sv


def lu(x, pivot: bool = True, get_infos: bool = False):
    """Packed LU + pivots (paddle.linalg.lu semantics)."""
    lu_mat, piv = jax.scipy.linalg.lu_factor(_arr(x))
    info = jnp.zeros((), jnp.int32)
    # paddle pivots are 1-based
    if get_infos:
        return lu_mat, piv + 1, info
    return lu_mat, piv + 1


def matrix_power(x, n: int):
    return jnp.linalg.matrix_power(_arr(x), n)


def matrix_rank(x, tol=None, hermitian: bool = False):
    x = _arr(x)
    if not hermitian:
        return jnp.linalg.matrix_rank(x, tol=tol)
    # hermitian path: rank from |eigenvalues| (handles negative eigvals,
    # which a plain SVD-threshold via matrix_rank would also count, but
    # the reference computes eigvalsh explicitly — match it)
    w = jnp.abs(jnp.linalg.eigvalsh(x))
    if tol is None:
        tol = (w.max(axis=-1, keepdims=True)
               * max(x.shape[-2], x.shape[-1])
               * jnp.finfo(x.dtype).eps)
    else:
        tol = jnp.asarray(tol)
        if tol.ndim > 0:
            tol = tol[..., None]
    return jnp.sum(w > tol, axis=-1)


def multi_dot(xs):
    return jnp.linalg.multi_dot([_arr(x) for x in xs])


def norm(x, p=None, axis=None, keepdim: bool = False):
    x = _arr(x)
    if axis is None:
        # paddle: Frobenius norm of the flattened tensor for any rank
        flat = x.reshape(-1)
        out = jnp.linalg.norm(flat, ord=2 if p in (None, "fro") else p)
        if keepdim:
            out = out.reshape((1,) * x.ndim)
        return out
    if p is None:
        p = 2 if isinstance(axis, int) else "fro"
    if isinstance(axis, int):
        return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


def pinv(x, rcond=1e-15, hermitian: bool = False):
    return jnp.linalg.pinv(_arr(x), rtol=rcond, hermitian=hermitian)


def qr(x, mode: str = "reduced"):
    return jnp.linalg.qr(_arr(x), mode=mode)


def solve(x, y):
    return jnp.linalg.solve(_arr(x), _arr(y))


def svd(x, full_matrices: bool = False):
    return jnp.linalg.svd(_arr(x), full_matrices=full_matrices)


def triangular_solve(x, y, upper: bool = True, transpose: bool = False,
                     unitriangular: bool = False):
    return jax.scipy.linalg.solve_triangular(
        _arr(x), _arr(y), lower=not upper,
        trans=1 if transpose else 0, unit_diagonal=unitriangular)


def cond(x, p=None):
    """Condition number (paddle.linalg.cond)."""
    x = _arr(x)
    if p is None:
        p = 2
    if p in (2, -2):
        s = jnp.linalg.svd(x, compute_uv=False)
        return (s[..., 0] / s[..., -1]) if p == 2 else (s[..., -1] / s[..., 0])
    return (jnp.linalg.norm(x, ord=p, axis=(-2, -1))
            * jnp.linalg.norm(jnp.linalg.inv(x), ord=p, axis=(-2, -1)))


def cov(x, rowvar: bool = True, ddof: bool = True, fweights=None,
        aweights=None):
    return jnp.cov(_arr(x), rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar: bool = True):
    return jnp.corrcoef(_arr(x), rowvar=rowvar)


def lu_unpack(lu_data, lu_pivots, unpack_ludata: bool = True,
              unpack_pivots: bool = True):
    """Unpack paddle.linalg.lu output into (P, L, U) (reference
    lu_unpack op).  ``lu_pivots`` are 1-based row swaps as returned by
    :func:`lu`."""
    lu_data = _arr(lu_data)
    m, n = lu_data.shape[-2], lu_data.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(lu_data[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_data.dtype)
        U = jnp.triu(lu_data[..., :k, :])
    if unpack_pivots:
        piv = jnp.asarray(lu_pivots) - 1          # back to 0-based swaps

        def perm_one(pv):
            perm = jnp.arange(m)

            def body(i, perm):
                j = pv[i]
                pi, pj = perm[i], perm[j]
                return perm.at[i].set(pj).at[j].set(pi)

            return jax.lax.fori_loop(0, pv.shape[0], body, perm)

        flat = piv.reshape((-1, piv.shape[-1]))
        perms = jax.vmap(perm_one)(flat)
        perms = perms.reshape(piv.shape[:-1] + (m,))
        P = jax.nn.one_hot(perms, m, dtype=lu_data.dtype)
        # rows of P: P[perm[i], i] = 1 → P @ L @ U == A
        P = jnp.swapaxes(P, -1, -2)
    return P, L, U
