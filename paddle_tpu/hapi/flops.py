"""Model FLOPs / summary utilities (reference: hapi/dynamic_flops.py
``paddle.flops`` and hapi/model_summary.py ``paddle.summary``).

TPU-first: instead of the reference's per-layer-type FLOP formulas (a hook
table over Conv2D/Linear/...), the count comes from XLA itself —
``jit(forward).lower(...).compile().cost_analysis()`` — so every op the
compiler actually emits is counted, fusions included.  A formula-based
estimate would drift from the real program; the compiler's own analysis
cannot.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype

__all__ = ["flops", "summary"]


def _example_input(input_size, dtype):
    dt = convert_dtype(dtype) if dtype else jnp.float32
    if jnp.issubdtype(dt, jnp.integer):
        return jnp.zeros(tuple(input_size), dt)
    return jnp.ones(tuple(input_size), dt)


def flops(net, input_size: Sequence[int], custom_ops=None,
          print_detail: bool = False, dtype=None) -> int:
    """Total forward FLOPs of ``net`` on ``input_size`` (paddle.flops).

    custom_ops is accepted for API parity; XLA's cost analysis already
    covers every op so it is unused."""
    # save per-sublayer modes: a blanket train() afterwards would unfreeze
    # sublayers deliberately left in eval (e.g. a frozen BN backbone)
    modes = [(l, l.training) for l in net.sublayers(include_self=True)]
    net.eval()
    try:
        params = net.state_dict()
        x = _example_input(input_size, dtype)

        def fwd(p, x):
            return net.apply(p, x)

        compiled = jax.jit(fwd).lower(params, x).compile()
        analyses = compiled.cost_analysis()
        ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
        total = int(ca.get("flops", 0))
        if print_detail:
            by_bytes = {k: v for k, v in ca.items()
                        if k.startswith("bytes accessed")}
            print(f"FLOPs: {total}")  # noqa: print
            for k, v in sorted(by_bytes.items()):
                print(f"  {k}: {int(v)}")  # noqa: print
        return total
    finally:
        for layer, mode in modes:
            object.__setattr__(layer, "training", mode)


def summary(net, input_size=None, dtypes=None) -> dict:
    """Layer-wise parameter summary (paddle.summary shape).

    Returns {'total_params': N, 'trainable_params': N}; prints a table."""
    total, trainable = 0, 0
    lines = []
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape))
        total += n
        if getattr(p, "trainable", True):
            trainable += n
        lines.append(f"  {name:48s} {str(tuple(p.shape)):24s} {n:>12,}")
    header = f"{'Layer (param)':50s} {'Shape':24s} {'Param #':>12s}"
    print(header)  # noqa: print
    print("-" * len(header))  # noqa: print
    print("\n".join(lines))  # noqa: print
    print("-" * len(header))  # noqa: print
    print(f"Total params: {total:,}")  # noqa: print
    print(f"Trainable params: {trainable:,}")  # noqa: print
    if input_size is not None:
        try:
            f = flops(net, input_size,
                      dtype=dtypes[0] if dtypes else None)
            print(f"Forward FLOPs @ {tuple(input_size)}: {f:,}")  # noqa: print
        except Exception as e:  # cost analysis unavailable on some backends
            print(f"(FLOPs unavailable: {e})")  # noqa: print
    return {"total_params": total, "trainable_params": trainable}
