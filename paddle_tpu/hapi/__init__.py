"""High-level API (reference: python/paddle/hapi)."""
from . import callbacks  # noqa: F401
from .flops import flops, summary  # noqa: F401
from .model import Model  # noqa: F401
from .callbacks import (Callback, CallbackList,  # noqa: F401
                        ProgBarLogger, ModelCheckpoint,
                        EarlyStopping, LRScheduler)
