"""paddle.callbacks parity (reference python/paddle/hapi/callbacks.py:
Callback/CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler) — the hook surface Model.fit drives."""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler"]


class Callback:
    def __init__(self):
        self.model = None
        self.params: Dict[str, Any] = {}

    def set_model(self, model) -> None:
        self.model = model

    def set_params(self, params: Dict[str, Any]) -> None:
        self.params = params

    # -- hooks (reference callback signature set) -------------------------
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb: Callback) -> None:
        self.callbacks.append(cb)

    def set_model(self, model) -> None:
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params) -> None:
        for cb in self.callbacks:
            cb.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Per-step console logging (reference ProgBarLogger, simplified to
    line logging — terminal progress bars add nothing under a driver)."""

    def __init__(self, log_freq: int = 10, verbose: int = 1):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            extras = " ".join(f"{k}: {v:.4f}" for k, v in logs.items()
                              if isinstance(v, (int, float)))
            epochs = self.params.get("epochs", "?")
            print(f"Epoch {self._epoch + 1}/{epochs} step {step} {extras}")  # noqa: print

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch + 1} done in {time.time() - self._t0:.1f}s")  # noqa: print


class ModelCheckpoint(Callback):
    """Periodic save (reference ModelCheckpoint: save_freq in epochs)."""

    def __init__(self, save_freq: int = 1, save_dir: Optional[str] = None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, f"epoch_{epoch}"))

    def on_train_end(self, logs=None):
        if self.save_dir:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference
    EarlyStopping: monitor/patience/min_delta/mode/baseline)."""

    def __init__(self, monitor: str = "loss", mode: str = "auto",
                 patience: int = 0, min_delta: float = 0.0,
                 baseline: Optional[float] = None,
                 save_best_model: bool = False):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = baseline if baseline is not None else (
            -np.inf if mode == "max" else np.inf)
        self.save_best_model = save_best_model
        self.wait = 0
        self.stopped_epoch = -1

    def _improved(self, value: float) -> bool:
        if self.mode == "max":
            return value > self.best + self.min_delta
        return value < self.best - self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        value = float(value[0] if isinstance(value, (list, tuple))
                      else value)
        if self._improved(value):
            self.best = value
            self.wait = 0
            save_dir = self.params.get("save_dir")
            if self.save_best_model and save_dir:
                os.makedirs(save_dir, exist_ok=True)
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True


class LRScheduler(Callback):
    """Step the optimizer's LRScheduler (reference LRScheduler callback:
    by_step or by_epoch)."""

    def __init__(self, by_step: bool = True, by_epoch: bool = False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_lr", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()
