"""High-level Model API (reference: python/paddle/hapi/model.py — Model:907,
fit:1045, evaluate, predict, save/load; Keras-style train loop).

TPU-native: `prepare()` builds ONE jitted train step (forward+backward+update)
— the whole-program compilation that replaces the reference's dual
dygraph/static execution paths.
"""
from __future__ import annotations

import math
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from .. import observability as obs
from ..framework import debug
from ..framework import random as fw_random
from ..framework.errors import enforce
from ..framework.log import vlog
from ..io import DataLoader
from ..metric import Metric

__all__ = ["Model"]


def _tuplify(x):
    return x if isinstance(x, (tuple, list)) else (x,)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._loss = None
        self._optimizer = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._eval_fn = None
        self._opt_state = None
        self._amp_level = None
        self._nonfinite_budget: Optional[int] = None
        self._nonfinite_skipped = 0
        self._supervisor = None  # set by RunSupervisor.attach / fit()
        # -- telemetry (ISSUE 3): last train_batch's dispatch/readback
        # split + cached MFU accounting inputs
        self._last_batch_timing: Optional[dict] = None
        self._obs_n_params: Optional[int] = None
        self._obs_flops_token: Optional[float] = None
        self._obs_seq_len: Optional[int] = None
        self._obs_peak: Optional[float] = None
        self._obs_step = 0

    # -- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, nonfinite_skip_budget: Optional[int] = None):
        """``nonfinite_skip_budget``: when set, a train batch whose loss
        comes back nan/inf is SKIPPED (no parameter/optimizer update)
        instead of poisoning the run — up to that many times, counted in
        ``nonfinite_skipped`` (surfaced in fit() batch logs); one more
        raises ``FloatingPointError``.  ``None`` (default) keeps the
        historical behavior: the update applies whatever the loss."""
        self._optimizer = optimizer
        # opt-in persistent compile cache (PTPU_COMPILE_CACHE_DIR): the
        # train step built below is the most expensive program the
        # framework compiles — a warm process loads it from disk
        obs.maybe_enable_persistent_cache()
        # ISSUE 8: a ZeRO-1 ShardedOptimizer (or a fleet wrapper over
        # one) resolves its mesh/axis/shard-count binding NOW, so the
        # fleet mesh active at prepare time is the one the jitted step's
        # sharding constraints are laid out against
        if hasattr(optimizer, "bind_mesh"):
            optimizer.bind_mesh()
        self._loss = loss
        self._metrics = _tuplify(metrics) if metrics is not None else []
        self._nonfinite_budget = (None if nonfinite_skip_budget is None
                                  else int(nonfinite_skip_budget))
        self._nonfinite_skipped = 0
        self._amp_level = (amp_configs or {}).get("level") if isinstance(
            amp_configs, dict) else amp_configs

        net, opt, loss_fn = self.network, self._optimizer, self._loss
        amp_level = self._amp_level

        def train_step(trainable, rest, opt_state, key, lr_override, *data):
            """Differentiate w.r.t. trainable params only; buffers (`rest`)
            flow through mutable apply.  ``lr_override``: traced scalar (or
            None) — set when the optimizer's lr is a stateful LRScheduler,
            whose .step() the LRScheduler callback drives (paddle
            convention)."""
            *inputs, label = data

            def compute_loss(tp):
                variables = {**rest, **tp}
                with fw_random.key_scope(key):
                    if amp_level:
                        from .. import amp as amp_mod
                        with amp_mod.auto_cast(level=amp_level):
                            out, newv = net.apply(variables, *inputs,
                                                  mutable=True)
                    else:
                        out, newv = net.apply(variables, *inputs, mutable=True)
                return loss_fn(out, label), (out, newv)

            (loss_v, (out, new_vars)), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(trainable)
            new_trainable, new_opt_state = opt.apply_gradients(
                grads, trainable, opt_state, lr=lr_override)
            merged = dict(new_vars)
            merged.update(new_trainable)
            # always traced (a few fused scalar reductions, ≙ the
            # operator.cc:1252 per-op scans) so FLAGS_check_nan_inf stays
            # runtime-togglable — the host only LOOKS at these when the
            # flag is set at call time (train_batch)
            finite = debug.finite_flags({"loss": loss_v, "grads": grads})
            # grad global norm: one fused reduction, fed to the run
            # supervisor's divergence guard (f32 accumulate so a bf16
            # overflow can't hide inside the statistic itself)
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32)))
                for g in jax.tree_util.tree_leaves(grads)) + 0.0)
            return loss_v, out, merged, new_opt_state, finite, gnorm

        def eval_fn(params, *data):
            *inputs, label = data
            out = net.apply(params, *inputs)
            return loss_fn(out, label) if loss_fn is not None else 0.0, out

        # compile/retrace accounting (ISSUE 4): every trace of the step
        # lands on the telemetry timeline as a `compile` record, and a
        # shape-churning argument is named by the retrace-storm detector
        self._train_step = obs.track_jit(
            jax.jit(train_step), name="hapi.train_step",
            arg_names=("trainable", "rest", "opt_state", "key",
                       "lr_override", "data[0]", "data[1]", "data[2]",
                       "data[3]", "data[4]", "data[5]"))
        self._eval_fn = obs.track_jit(jax.jit(eval_fn),
                                      name="hapi.eval_fn")

    # -- per-batch --------------------------------------------------------
    def _variables(self):
        return self.network.state_dict()

    def train_batch(self, inputs, labels=None):
        enforce(self._train_step is not None, "call prepare() first")
        self.network.train()
        variables = self._variables()
        trainable = self.network.trainable_variables()
        rest = {k: v for k, v in variables.items() if k not in trainable}
        if self._opt_state is None:
            self._opt_state = self._optimizer.init(trainable)
        data = [jnp.asarray(np.asarray(x)) for x in
                (*_tuplify(inputs), *_tuplify(labels))]
        key = fw_random.next_key()
        from ..optimizer import lr as lr_mod
        sup = self._supervisor
        lr_override = None
        if isinstance(getattr(self._optimizer, "_lr", None),
                      lr_mod.LRScheduler):
            # stateful scheduler: the current value applies until someone
            # (the LRScheduler callback, or the user) calls .step()
            lr_override = jnp.asarray(self._optimizer._lr.get_lr(),
                                      jnp.float32)
        if sup is not None and sup.guard.lr_scale != 1.0:
            # divergence guard's LOWER_LR escalation: sticky backoff on
            # top of whatever schedule is active
            lr_override = jnp.asarray(
                self._optimizer.get_lr() * sup.guard.lr_scale, jnp.float32)
        if (sup is not None and sup.integrity is not None
                and sup.integrity.enabled):
            # replay-audit stash (ISSUE 11): references to this step's
            # pre-state and exact inputs (jax arrays are immutable, so
            # this is pointer assignment, not a copy)
            if sup.integrity.replay_fn is None:
                sup.integrity.replay_fn = self._integrity_replay
            sup.integrity.stash_replay(sup.gstep + 1,
                                       self._supervised_state(),
                                       (data, key, lr_override))
        try:
            if sup is not None:
                # the armed region covers the jitted step AND the host
                # sync on its results — where a hung collective actually
                # blocks
                with sup.watchdog.armed("train_batch"):
                    with obs.span("dispatch") as sp_d:
                        loss, out, new_params, new_opt_state, finite, \
                            gnorm = self._train_step(
                                trainable, rest, self._opt_state,
                                key, lr_override, *data)
                    # the readback IS the device sync (bench.py
                    # methodology: on tunneled TPUs dispatch returns
                    # before completion, so this span absorbs the device
                    # compute)
                    with obs.span("readback") as sp_r:
                        loss_v = sup.filter_loss(float(loss))
                        gnorm_v = float(gnorm)
                self._last_batch_timing = {"dispatch_s": sp_d.elapsed,
                                           "readback_s": sp_r.elapsed}
                action = sup.guard_step(loss_v, gnorm_v,
                                        amp_active=bool(self._amp_level))
                from ..supervisor.guard import GuardAction
                if action != GuardAction.OK:
                    # SKIP / LOWER_LR / ROLLBACK all drop this batch's
                    # update (params AND optimizer state); ROLLBACK is
                    # latched on the supervisor for the driving loop to
                    # execute
                    return loss_v, [m.accumulate() for m in self._metrics]
            else:
                with obs.span("dispatch") as sp_d:
                    loss, out, new_params, new_opt_state, finite, _gnorm = \
                        self._train_step(trainable, rest, self._opt_state,
                                         key, lr_override, *data)
                with obs.span("readback") as sp_r:
                    loss_v = float(loss)
                self._last_batch_timing = {"dispatch_s": sp_d.elapsed,
                                           "readback_s": sp_r.elapsed}
        except Exception as e:
            # an allocator OOM kills the step AND the evidence — dump the
            # last-known per-device watermark table first (ISSUE 4)
            if obs.is_oom_error(e):
                obs.oom_postmortem(error=e, step=(
                    sup.gstep if sup is not None else self._obs_step))
            raise
        if debug.check_nan_inf_enabled():
            debug.assert_all_finite(finite, context="train_batch")
        if self._nonfinite_budget is not None and not math.isfinite(loss_v):
            # skip-step: drop this batch's update entirely (params AND
            # optimizer state) so one bad batch degrades gracefully;
            # exhausting the budget fails loudly — a persistent nan is a
            # bug, not noise
            self._nonfinite_skipped += 1
            if self._nonfinite_skipped > self._nonfinite_budget:
                raise FloatingPointError(
                    f"non-finite loss ({loss_v}) exceeded the skip budget "
                    f"of {self._nonfinite_budget}")
            vlog(0, "hapi: non-finite loss (%s) — skipping update (%d/%d)",
                 loss_v, self._nonfinite_skipped, self._nonfinite_budget)
            return loss_v, [m.accumulate() for m in self._metrics]
        self._opt_state = new_opt_state
        self.network.set_state_dict(new_params, strict=False)
        metrics = []
        for m in self._metrics:
            r = m.compute(np.asarray(out), np.asarray(data[-1]))
            m.update(*(r if isinstance(r, tuple) else (r,)))
            metrics.append(m.accumulate())
        return loss_v, metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        params = self._variables()
        data = [jnp.asarray(np.asarray(x)) for x in
                (*_tuplify(inputs), *_tuplify(labels))]
        loss, out = self._eval_fn(params, *data)
        return float(loss), out

    def predict_batch(self, inputs):
        self.network.eval()
        params = self._variables()
        return self.network.apply(
            params, *[jnp.asarray(np.asarray(x)) for x in _tuplify(inputs)])

    # -- loops ------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size: int = 1,
            epochs: int = 1, eval_freq: int = 1, log_freq: int = 10,
            save_dir: Optional[str] = None, shuffle: bool = True,
            num_workers: int = 0, verbose: int = 1, drop_last: bool = False,
            callbacks=None, supervisor=None):
        """``supervisor``: a :class:`paddle_tpu.supervisor.RunSupervisor`
        wrapping this run in the full health loop — watchdog around every
        batch, heartbeats, divergence guard (skip → lower-LR → rollback),
        and budget-bounded auto-rollback to the last committed
        checkpoint.  See docs/ARCHITECTURE.md "Run supervision"."""
        from ..optimizer import lr as lr_mod
        from .callbacks import (CallbackList, LRScheduler as LRSchedulerCB,
                                ModelCheckpoint, ProgBarLogger)
        if not isinstance(train_data, DataLoader):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        cbs = CallbackList(list(callbacks or []))
        if not any(isinstance(c, ProgBarLogger) for c in cbs.callbacks):
            cbs.append(ProgBarLogger(log_freq=log_freq, verbose=verbose))
        if save_dir and not any(isinstance(c, ModelCheckpoint)
                                for c in cbs.callbacks):
            cbs.append(ModelCheckpoint(save_dir=save_dir))
        if (isinstance(getattr(self._optimizer, "_lr", None),
                       lr_mod.LRScheduler)
                and not any(isinstance(c, LRSchedulerCB)
                            for c in cbs.callbacks)):
            # paddle convention: fit drives per-step scheduling by default
            cbs.append(LRSchedulerCB(by_step=True))
        cbs.set_model(self)
        cbs.set_params({"epochs": epochs, "batch_size": batch_size,
                        "verbose": verbose, "save_dir": save_dir})
        self.stop_training = False
        history = {"loss": []}
        sup = supervisor
        if sup is not None:
            from ..supervisor.guard import GuardAction
            from ..supervisor.watchdog import StepTimeout
            sup.attach(self)
            if self._optimizer is not None and self._opt_state is None:
                # warm the optimizer state so every supervised checkpoint
                # (including the rollback templates) has one stable pytree
                self._opt_state = self._optimizer.init(
                    self.network.trainable_variables())
            sup.begin_run(initial_state=self._supervised_state())
        cbs.on_train_begin()
        try:
            for epoch in range(epochs):
                for m in self._metrics:
                    m.reset()
                cbs.on_epoch_begin(epoch)
                epoch_losses = []
                for step, (batch, data_s) in enumerate(
                        self._timed_batches(train_loader)):
                    cbs.on_train_batch_begin(step)
                    *inputs, label = batch
                    if sup is not None:
                        try:
                            with obs.span("step") as sp_step:
                                loss, metrics = self.train_batch(inputs,
                                                                 label)
                        except StepTimeout:
                            # watchdog fired: the step is dead, not the
                            # run — skip it, roll back when they repeat
                            if (sup.note_step_failure("step-timeout")
                                    == GuardAction.ROLLBACK):
                                self._supervised_rollback(sup)
                            cbs.on_train_batch_end(
                                step, {"loss": float("nan"),
                                       "supervisor": "step-timeout"})
                            if self.stop_training:
                                break
                            continue
                        good = sup.last_action in (None, GuardAction.OK)
                        if sup.pending_rollback:
                            self._supervised_rollback(sup)
                        elif sup.pending_resize is not None:
                            # elastic resize (ISSUE 9): lost worker or a
                            # scale signal — re-form the mesh at the new
                            # width and resume from last_good_step
                            self._supervised_resize(sup)
                        elif sup.pending_integrity is not None:
                            # state-integrity heal (ISSUE 11): a desync
                            # verdict — majority members publish the
                            # resync offer, suspects climb the
                            # resync → rollback → evict ladder
                            self._supervised_integrity_heal(sup)
                        else:
                            # checkpoint only states a good update built
                            sup.note_step_ok(
                                self._supervised_state() if good else None)
                    else:
                        good = True
                        with obs.span("step") as sp_step:
                            loss, metrics = self.train_batch(inputs, label)
                    self._record_step_telemetry(data_s, sp_step.elapsed,
                                                label, loss)
                    history["loss"].append(loss)
                    if good:
                        epoch_losses.append(loss)
                    logs = {"loss": loss}
                    if sup is not None and not good:
                        logs["supervisor"] = sup.last_action
                    if self._nonfinite_budget is not None:
                        logs["nonfinite_skipped"] = self._nonfinite_skipped
                    for m, v in zip(self._metrics, metrics):
                        logs[m.name()] = v[0] if isinstance(v, list) else v
                    cbs.on_train_batch_end(step, logs)
                    if self.stop_training:
                        break
                # with a skip guard on, skipped batches' nan losses are
                # excluded from the epoch mean (they applied no update)
                _mean = (np.nanmean if self._nonfinite_budget is not None
                         else np.mean)
                epoch_logs = {"loss": float(_mean(epoch_losses))
                              if epoch_losses else float("nan")}
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    cbs.on_eval_begin()
                    eval_res = self.evaluate(eval_data,
                                             batch_size=batch_size,
                                             verbose=verbose)
                    cbs.on_eval_end(eval_res)
                    # eval metrics reach on_epoch_end (EarlyStopping
                    # monitors)
                    epoch_logs.update({f"eval_{k}" if k == "loss" else k: v
                                       for k, v in eval_res.items()})
                cbs.on_epoch_end(epoch, epoch_logs)
                if self.stop_training:
                    break
        except BaseException:
            if sup is not None:
                sup.end_run("failed")
                self._supervisor = None
            raise
        if sup is not None:
            sup.end_run("completed")
            self._supervisor = None
        cbs.on_train_end()
        return history

    # -- telemetry plumbing (ISSUE 3) -------------------------------------
    @staticmethod
    def _timed_batches(loader):
        """Iterate ``loader`` yielding ``(batch, data_wait_seconds)`` —
        the data-wait half of the per-step breakdown."""
        it = iter(loader)
        while True:
            t0 = time.perf_counter()
            try:
                with obs.span("data_load"):
                    batch = next(it)
            except StopIteration:
                return
            yield batch, time.perf_counter() - t0

    def _record_step_telemetry(self, data_s: float, step_s: float, label,
                               loss) -> None:
        """One ``step`` record per train batch: wall time split into
        data-wait / dispatch (compute) / host-readback, tokens/sec, and
        live MFU against the chip's peak (``observability.mfu``) —
        emitted to whatever sinks are attached, accumulated in the
        registry's histograms either way."""
        try:
            reg = obs.get_registry()
            timing = self._last_batch_timing or {}
            lab = np.asarray(label)
            tokens = max(1, int(lab.size))
            seq_len = int(lab.shape[-1]) if lab.ndim >= 2 else None
            if self._obs_n_params is None:
                self._obs_n_params = obs.param_count(
                    self.network.state_dict())
                self._obs_peak = obs.peak_flops_per_sec()
            if self._obs_flops_token is None or seq_len != self._obs_seq_len:
                cfg = getattr(self.network, "config", None)
                self._obs_flops_token = obs.flops_per_token(
                    self._obs_n_params,
                    num_layers=getattr(cfg, "num_layers", None),
                    hidden_size=getattr(cfg, "hidden_size", None),
                    seq_len=seq_len)
                self._obs_seq_len = seq_len
            total_s = max(1e-9, data_s + step_s)
            tps = tokens / total_s
            mfu_v = obs.mfu(tps, self._obs_flops_token, self._obs_peak)
            compute_ms = timing.get("dispatch_s", 0.0) * 1e3
            readback_ms = timing.get("readback_s", 0.0) * 1e3
            reg.histogram("step.time_ms").observe(total_s * 1e3)
            reg.histogram("step.data_ms").observe(data_s * 1e3)
            reg.histogram("step.compute_ms").observe(compute_ms)
            reg.histogram("step.readback_ms").observe(readback_ms)
            reg.counter("step.count").inc()
            reg.counter("step.tokens").inc(tokens)
            reg.gauge("step.tokens_per_sec").set(tps)
            reg.gauge("step.mfu").set(mfu_v)
            sup = self._supervisor
            cur_step = sup.gstep if sup is not None else self._obs_step
            # where-is-it-now gauges for the live monitor's /statusz
            # page (ISSUE 5)
            reg.gauge("step.current").set(cur_step)
            reg.gauge("step.loss").set(float(loss))
            # HBM watermark sample on its PTPU_MEM_SAMPLE_EVERY cadence
            # (no-op off cadence / on backends without allocator stats)
            obs.get_sampler().sample(cur_step)
            reg.emit("step",
                     step=cur_step,
                     step_time_ms=total_s * 1e3, data_ms=data_s * 1e3,
                     compute_ms=compute_ms, readback_ms=readback_ms,
                     tokens=tokens, tokens_per_sec=tps, mfu=mfu_v,
                     loss=float(loss))
            self._obs_step += 1
        except Exception as e:
            # telemetry must never take the training loop down with it
            vlog(1, "hapi: step telemetry failed: %r", e)

    # -- supervision plumbing (ISSUE 2) -----------------------------------
    def _supervised_state(self):
        """The pytree the run supervisor checkpoints and rolls back —
        parameters + buffers, plus optimizer state once it exists."""
        state = {"params": dict(self.network.state_dict())}
        if self._opt_state is not None:
            state["opt"] = self._opt_state
        return state

    def _load_supervised_state(self, state) -> None:
        self.network.set_state_dict(state["params"], strict=False)
        if "opt" in state:
            self._opt_state = state["opt"]

    def _supervised_rollback(self, sup, reason: Optional[str] = None
                             ) -> None:
        """Restore the last committed good step into the live model (the
        pristine t0 state when nothing has been committed yet)."""
        state, _start = sup.perform_rollback(
            lambda: (sup.initial_state if sup.initial_state is not None
                     else self._supervised_state()),
            lambda: self._supervised_state(), reason)
        self._load_supervised_state(state)

    def _supervised_integrity_heal(self, sup) -> None:
        """Execute a latched state-integrity heal (ISSUE 11); the live
        model adopts whatever state the healing ladder lands on — the
        majority state (resync), a digest-verified checkpoint
        (rollback), or the re-formed fleet's state (evict)."""
        state, _start = sup.perform_integrity_heal(
            lambda: (sup.initial_state if sup.initial_state is not None
                     else self._supervised_state()),
            lambda: self._supervised_state(),
            self._supervised_state())
        self._load_supervised_state(state)

    def _integrity_replay(self, state, stashed):
        """Deterministic re-run of one stashed microbatch for the replay
        audit: same inputs, same RNG key, same LR — the jitted step is
        pure, so two replays that disagree indict software
        nondeterminism and a replay that disagrees with the live state
        indicts the hardware (state damaged outside the computed path)."""
        data, key, lr_override = stashed
        params = state["params"]
        tv = self.network.trainable_variables()
        # same container type + order as the live step — the optimizer
        # state's treedef is structural, not just keyed
        trainable = type(tv)((k, params[k]) for k in tv)
        rest = {k: v for k, v in params.items() if k not in tv}
        _loss, _out, merged, new_opt_state, _finite, _g = self._train_step(
            trainable, rest, state["opt"], key, lr_override, *data)
        return {"params": dict(merged), "opt": new_opt_state}

    def _supervised_resize(self, sup) -> None:
        """Execute a latched elastic resize (ISSUE 9): the coordinator
        re-forms the mesh at the new width and re-shards the last
        committed state onto it; the live model adopts the restored
        (rewound) state and training continues — the jitted step simply
        retraces against the new placements."""
        state, _start = sup.perform_resize(
            lambda: (sup.initial_state if sup.initial_state is not None
                     else self._supervised_state()),
            lambda: self._supervised_state())
        self._load_supervised_state(state)

    def evaluate(self, eval_data, batch_size: int = 1, log_freq: int = 10,
                 verbose: int = 1, num_workers: int = 0):
        if not isinstance(eval_data, DataLoader):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            *inputs, label = batch
            loss, out = self.eval_batch(inputs, label)
            losses.append(loss)
            for m in self._metrics:
                r = m.compute(np.asarray(out), np.asarray(label))
                m.update(*(r if isinstance(r, tuple) else (r,)))
        result = {"loss": float(np.mean(losses)) if losses else 0.0}
        for m in self._metrics:
            result[m.name()] = m.accumulate()
        if verbose:
            print("Eval:", result)  # noqa: print
        return result

    def predict(self, test_data, batch_size: int = 1, num_workers: int = 0):
        if not isinstance(test_data, DataLoader):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outs = []
        for batch in loader:
            inputs = batch[:-1] if isinstance(batch, (tuple, list)) and \
                len(batch) > 1 else _tuplify(batch)
            outs.append(np.asarray(self.predict_batch(list(inputs))))
        return outs

    # -- io ---------------------------------------------------------------
    def save(self, path: str):
        framework.save(self.network.state_dict(), path + ".pdparams")
        if self._opt_state is not None:
            framework.save(self._opt_state, path + ".pdopt")

    def load(self, path: str, reset_optimizer: bool = False):
        self.network.set_state_dict(framework.load(path + ".pdparams"))
        if not reset_optimizer:
            import os
            if os.path.exists(path + ".pdopt"):
                self._opt_state = framework.load(path + ".pdopt")

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        lines, total = [], 0
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape))
            total += n
            lines.append(f"  {name:40s} {str(p.shape):20s} {n}")
        out = "\n".join(lines) + f"\nTotal params: {total}"
        print(out)  # noqa: print
        return {"total_params": total}
