"""paddle.signal parity (reference python/paddle/signal.py: frame:32,
overlap_add:154, stft:237, istft:391).

TPU-native: framing is a static gather (indices built at trace time, one
vectorized take), overlap-add is a segment-sum scatter, and the DFTs ride
``jnp.fft`` — everything jittable with static shapes, batched over
leading dims.  Output layout matches the reference: stft returns
``[..., n_fft(/2+1), num_frames]`` (frequency-major)."""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from .framework.errors import enforce

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else jnp.asarray(x)


def frame(x, frame_length: int, hop_length: int, axis: int = -1):
    """Slice overlapping frames: ``[..., seq]`` → ``[..., frame_length,
    num_frames]`` for axis=-1 (reference signal.py:32; axis=0 puts frames
    first)."""
    x = _arr(x)
    enforce(axis in (0, -1), "frame: axis must be 0 or -1")
    enforce(hop_length > 0, f"frame: hop_length={hop_length} must be > 0")
    seq = x.shape[axis]
    enforce(frame_length <= seq,
            f"frame: frame_length={frame_length} > seq_length={seq}")
    n_frames = 1 + (seq - frame_length) // hop_length
    idx = (np.arange(frame_length)[:, None]
           + hop_length * np.arange(n_frames)[None, :])   # (fl, nf)
    if axis == -1:
        return jnp.take(x, jnp.asarray(idx), axis=-1)
    return jnp.take(x, jnp.asarray(idx.T), axis=0)


def overlap_add(x, hop_length: int, axis: int = -1):
    """Inverse of :func:`frame`: ``[..., frame_length, num_frames]`` →
    ``[..., seq]`` summing overlaps (reference signal.py:154)."""
    x = _arr(x)
    enforce(axis in (0, -1), "overlap_add: axis must be 0 or -1")
    if axis == 0:
        # (num_frames, frame_length, ...) → move to (..., fl, nf)
        x = jnp.moveaxis(jnp.moveaxis(x, 0, -1), 0, -2)
    fl, nf = x.shape[-2], x.shape[-1]
    out_len = (nf - 1) * hop_length + fl
    pos = (np.arange(fl)[:, None]
           + hop_length * np.arange(nf)[None, :]).reshape(-1)
    flat = x.reshape(x.shape[:-2] + (fl * nf,))
    out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
    out = out.at[..., jnp.asarray(pos)].add(flat)
    if axis == 0:
        out = jnp.moveaxis(out, -1, 0)
    return out


def _resolve_window(window, win_length: int, n_fft: int, dtype):
    if window is None:
        w = jnp.ones((win_length,), dtype)
    else:
        w = _arr(window).astype(dtype)
        enforce(w.shape == (win_length,),
                f"window must have shape ({win_length},), got {w.shape}")
    if win_length < n_fft:     # center-pad to n_fft (reference behavior)
        lpad = (n_fft - win_length) // 2
        w = jnp.pad(w, (lpad, n_fft - win_length - lpad))
    return w


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None,
         center: bool = True, pad_mode: str = "reflect",
         normalized: bool = False, onesided: bool = True):
    """Short-time Fourier transform (reference signal.py:237): returns
    ``[..., n_fft//2 + 1 (or n_fft), num_frames]`` complex frames."""
    x = _arr(x)
    hop_length = n_fft // 4 if hop_length is None else hop_length
    enforce(hop_length > 0, f"stft: hop_length={hop_length} must be > 0")
    win_length = n_fft if win_length is None else win_length
    enforce(0 < win_length <= n_fft,
            f"stft: need 0 < win_length={win_length} <= n_fft={n_fft}")
    enforce(not (onesided and jnp.iscomplexobj(x)),
            "stft: onesided is not supported for complex inputs")
    w = _resolve_window(window, win_length, n_fft,
                        jnp.float32 if not jnp.iscomplexobj(x)
                        else jnp.complex64)
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    frames = frame(x, n_fft, hop_length, axis=-1)      # (..., n_fft, nf)
    frames = frames * w[:, None]
    spec = (jnp.fft.rfft(frames, axis=-2) if onesided
            else jnp.fft.fft(frames, axis=-2))
    if normalized:
        spec = spec * (n_fft ** -0.5)
    return spec


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None,
          center: bool = True, normalized: bool = False,
          onesided: bool = True, length: Optional[int] = None,
          return_complex: bool = False):
    """Inverse STFT, least-squares/NOLA form (reference signal.py:391)."""
    x = _arr(x)
    hop_length = n_fft // 4 if hop_length is None else hop_length
    enforce(hop_length > 0, f"istft: hop_length={hop_length} must be > 0")
    win_length = n_fft if win_length is None else win_length
    enforce(0 < win_length <= n_fft,
            f"istft: need 0 < win_length={win_length} <= n_fft={n_fft}")
    enforce(x.ndim >= 2, "istft: input must be [..., n_fft(/2+1), frames]")
    enforce(not (return_complex and onesided),
            "istft: return_complex=True requires onesided=False")
    w = _resolve_window(window, win_length, n_fft, jnp.float32)
    if normalized:
        x = x * (n_fft ** 0.5)
    frames = (jnp.fft.irfft(x, n=n_fft, axis=-2) if onesided
              else jnp.fft.ifft(x, axis=-2))
    if not return_complex:
        frames = jnp.real(frames)
    frames = frames * w[:, None]
    y = overlap_add(frames, hop_length, axis=-1)
    # NOLA check + normalization by the summed squared window envelope.
    # The window is concrete at trace time, so the envelope minimum over
    # the center region is checkable with numpy (reference/torch raise
    # likewise on zero overlap-add coverage)
    wsq_np = np.asarray(w, np.float64) ** 2
    nf = int(x.shape[-1])
    env_np = np.zeros((nf - 1) * hop_length + n_fft)
    for j in range(nf):
        env_np[j * hop_length:j * hop_length + n_fft] += wsq_np
    chk = env_np[n_fft // 2:len(env_np) - n_fft // 2] if center else env_np
    enforce(chk.size == 0 or chk.min() > 1e-11,
            "istft: window fails the NOLA condition (zero overlap-add "
            f"coverage with hop_length={hop_length})")
    y = y / jnp.maximum(jnp.asarray(env_np, y.dtype), 1e-11)
    if center:
        pad = n_fft // 2
        # drop the left padding; the right crop depends on `length`: an
        # explicit length keeps real samples from the last frames' tails
        # (torch semantics) instead of cropping pad then zero-padding
        y = y[..., pad:] if length is not None \
            else y[..., pad:y.shape[-1] - pad]
    if length is not None:
        if y.shape[-1] < length:   # zero-pad past frame coverage
            y = jnp.pad(y, [(0, 0)] * (y.ndim - 1)
                        + [(0, length - y.shape[-1])])
        y = y[..., :length]
    return y
