"""paddle.profiler parity (SURVEY §5 tracing/profiling, component E8).

Reference: the new-generation profiler — python/paddle/profiler/profiler.py
``Profiler``:264 with scheduler states (:33 ProfilerState CLOSED/READY/
RECORD/RECORD_AND_RETURN), ``make_scheduler``, chrome-trace export (:154),
``RecordEvent`` host annotations (platform/profiler/event_tracing.h) and
``profiler_statistic.py`` summaries.

TPU-native: the device side is XLA's XPlane tracer via jax.profiler — we
wrap start/stop/step scheduling and keep the reference API shape
(``Profiler(targets, scheduler, on_trace_ready)``, ``RecordEvent``,
``profiler.step()``).  Traces land in TensorBoard/XPlane format (the TPU
ecosystem's chrome://tracing analog); host annotations become
TraceAnnotation ranges inside the same timeline, exactly the role
RecordEvent plays inside OperatorWithKernel::RunImpl.  A lightweight host
statistic table (op name → count/total ms) is kept for
``summary()`` parity without parsing XPlane."""
from __future__ import annotations

import contextlib
import enum
import functools
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax

__all__ = ["ProfilerTarget", "ProfilerState", "Profiler", "RecordEvent",
           "make_scheduler", "record_function", "profiler_summary"]


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1          # accepted for source compat; maps to the device tracer
    TPU = 2


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """≙ paddle.profiler.make_scheduler: step → state cycle
    [skip_first | (closed, ready, record)*repeat]."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle, pos = divmod(s, period)
        if repeat > 0 and cycle >= repeat:
            return ProfilerState.CLOSED
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


# --------------------------------------------------------------------------
# Host-side event stats (RecordEvent analog)
# --------------------------------------------------------------------------
_stats_lock = threading.Lock()
_stats: Dict[str, Tuple[int, float]] = {}


def _record_stat(name: str, dt: float) -> None:
    with _stats_lock:
        n, total = _stats.get(name, (0, 0.0))
        _stats[name] = (n + 1, total + dt)


def profiler_summary(reset: bool = False) -> Dict[str, Tuple[int, float]]:
    """{event name: (count, total seconds)} for every RecordEvent so far
    (the profiler_statistic.py table, host side)."""
    with _stats_lock:
        out = dict(_stats)
        if reset:
            _stats.clear()
    return out


class RecordEvent:
    """Host annotation visible in the device timeline
    (≙ paddle.profiler.RecordEvent / platform RecordEvent instrumentation).

    Usable as a context manager or via explicit begin()/end()."""

    def __init__(self, name: str, event_type: Any = None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self) -> None:
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()

    def end(self) -> None:
        if self._ann is not None:
            _record_stat(self.name, time.perf_counter() - self._t0)
            self._ann.__exit__(None, None, None)
            self._ann = None

    def __enter__(self) -> "RecordEvent":
        self.begin()
        return self

    def __exit__(self, *exc) -> None:
        self.end()


def record_function(name: Optional[str] = None):
    """Decorator form of RecordEvent."""
    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with RecordEvent(label):
                return fn(*a, **kw)
        return wrapped
    return deco


class Profiler:
    """≙ paddle.profiler.Profiler(targets, scheduler, on_trace_ready).

    >>> p = Profiler(scheduler=make_scheduler(closed=1, ready=1, record=2,
    ...                                       repeat=1))
    >>> p.start()
    >>> for batch in loader:
    ...     train_step(...)
    ...     p.step()
    >>> p.stop()

    Traces are written per recording window to ``log_dir/plugins/profile``
    (TensorBoard XPlane — open with the TensorBoard profile plugin or
    xprof; this is the TPU ecosystem's chrome-trace export)."""

    def __init__(self, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler: Optional[Callable[[int], ProfilerState]] = None,
                 on_trace_ready: Optional[Callable[["Profiler"], None]] = None,
                 log_dir: Optional[str] = None, timer_only: bool = False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU,
                                                      ProfilerTarget.TPU]
        self.scheduler = scheduler or (lambda step: ProfilerState.RECORD)
        self.on_trace_ready = on_trace_ready
        self.log_dir = log_dir or os.path.join(tempfile.gettempdir(),
                                               "paddle_tpu_profile")
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._tracing = False
        self._step_ann = None
        self._step_t0 = None
        self._step_times = []

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.current_state = self.scheduler(self.step_num)
        self._apply_state(self.current_state)
        self._begin_step_annotation()

    def stop(self) -> None:
        self._end_step_annotation()
        if self._tracing:
            self._stop_trace(trigger_callback=True)
        self.current_state = ProfilerState.CLOSED

    def step(self) -> None:
        """Advance the step scheduler (call once per train iteration)."""
        self._end_step_annotation()
        if self._step_t0 is not None:
            self._step_times.append(time.perf_counter() - self._step_t0)
        next_state = self.scheduler(self.step_num + 1)
        self._transition(self.current_state, next_state)
        self.step_num += 1
        self.current_state = next_state
        self._begin_step_annotation()

    # -- internals ---------------------------------------------------------
    def _begin_step_annotation(self) -> None:
        if self._tracing and not self.timer_only:
            self._step_ann = jax.profiler.StepTraceAnnotation(
                "train_step", step_num=self.step_num)
            self._step_ann.__enter__()
        self._step_t0 = time.perf_counter()

    def _end_step_annotation(self) -> None:
        if self._step_ann is not None:
            self._step_ann.__exit__(None, None, None)
            self._step_ann = None

    def _apply_state(self, state: ProfilerState) -> None:
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_trace()

    def _transition(self, cur: ProfilerState, new: ProfilerState) -> None:
        recording = cur in (ProfilerState.RECORD,
                            ProfilerState.RECORD_AND_RETURN)
        will_record = new in (ProfilerState.RECORD,
                              ProfilerState.RECORD_AND_RETURN)
        if recording and (not will_record
                          or cur == ProfilerState.RECORD_AND_RETURN):
            self._stop_trace(
                trigger_callback=cur == ProfilerState.RECORD_AND_RETURN)
        if will_record and (not recording
                            or cur == ProfilerState.RECORD_AND_RETURN):
            self._start_trace()

    def _start_trace(self) -> None:
        if self._tracing or self.timer_only:
            return
        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir)
        self._tracing = True

    def _stop_trace(self, trigger_callback: bool) -> None:
        if not self._tracing:
            return
        jax.profiler.stop_trace()
        self._tracing = False
        if trigger_callback and self.on_trace_ready is not None:
            self.on_trace_ready(self)

    # -- reporting ---------------------------------------------------------
    def summary(self, sorted_by: str = "total", reset: bool = False) -> str:
        """Host-side table: RecordEvent stats + step timing (the
        profiler_statistic.py report analog), followed by the
        observability layer's span TREE — path → count / total / self ms,
        where self excludes child spans — so nested regions
        (``step/dispatch`` under ``step``) read as a hierarchy instead of
        a flat list (ISSUE 3)."""
        rows = [(name, n, tot) for name, (n, tot) in
                profiler_summary(reset=reset).items()]
        rows.sort(key=lambda r: r[2], reverse=True)
        lines = [f"{'event':40s} {'count':>8s} {'total ms':>10s} "
                 f"{'avg ms':>10s}"]
        for name, n, tot in rows:
            lines.append(f"{name[:40]:40s} {n:8d} {tot * 1e3:10.2f} "
                         f"{tot / n * 1e3:10.2f}")
        if self._step_times:
            ts = self._step_times
            lines.append(f"steps: {len(ts)}  avg "
                         f"{sum(ts) / len(ts) * 1e3:.2f} ms")
        from ..observability.tracing import span_tree_totals
        tree = span_tree_totals(reset=reset)
        if tree:
            lines.append("")
            lines.append(f"{'span':40s} {'count':>8s} {'total ms':>10s} "
                         f"{'self ms':>10s}")
            for path, row in tree.items():
                lines.append(f"{path[:40]:40s} {row['count']:8d} "
                             f"{row['total_ms']:10.2f} "
                             f"{row['self_ms']:10.2f}")
        return "\n".join(lines)

    def __enter__(self) -> "Profiler":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# reference paddle.profiler __all__ parity: exporter helpers + SortedKeys
class SortedKeys(enum.Enum):
    """Reference profiler.SortedKeys: summary-table sort orders."""
    CPUTotal = "total"
    CPUAvg = "avg"
    CPUMax = "max"
    CPUMin = "min"
    GPUTotal = "device_total"
    GPUAvg = "device_avg"


def _copy_trace_handler(dir_name: str):
    def handler(prof):
        import shutil
        os.makedirs(dir_name, exist_ok=True)
        if os.path.isdir(prof.log_dir):
            shutil.copytree(prof.log_dir, dir_name, dirs_exist_ok=True)
    return handler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """on_trace_ready factory (reference profiler.export_chrome_tracing):
    the Profiler's trace machinery already emits chrome/XPlane files into
    its log_dir; the handler lands a copy in ``dir_name``."""
    return _copy_trace_handler(dir_name)


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    return _copy_trace_handler(dir_name)


def load_profiler_result(file_name: str):
    """Load an exported chrome trace back (reference
    load_profiler_result)."""
    import json
    with open(file_name) as f:
        return json.load(f)


__all__ += ["SortedKeys", "export_chrome_tracing", "export_protobuf",
            "load_profiler_result"]
