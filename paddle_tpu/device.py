"""paddle.device parity: device introspection + memory stats (L0/C1).

Reference: phi::Place/DeviceContext device identity plus the memory-stat
surface (memory/stats.cc backing paddle.device.cuda.max_memory_allocated /
memory_allocated / device_count / get_device_properties).

TPU-native: device identity is jax.Device; memory numbers come from
PJRT's per-device ``memory_stats()`` (bytes_in_use, peak_bytes_in_use,
bytes_limit — XLA's allocator telemetry, the stats.cc analog).  The cuda.*
names are aliased so ported monitoring code keeps working against the TPU.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from .framework.dtype import get_device, set_device  # noqa: F401

__all__ = ["device_count", "get_all_devices", "get_device_properties",
           "memory_stats", "memory_allocated", "max_memory_allocated",
           "memory_reserved", "local_device_memory_stats",
           "local_memory_stats", "largest_alloc_size", "set_device",
           "get_device", "cuda", "tpu"]


def device_count() -> int:
    return jax.device_count()


def get_all_devices() -> List[str]:
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def _dev(device: Optional[int] = None) -> jax.Device:
    # index the GLOBAL device list, consistent with device_count(); stats
    # for a non-addressable device raise from PJRT with a clear message
    devs = jax.devices()
    i = 0 if device is None else int(device)
    if not 0 <= i < len(devs):
        raise IndexError(f"device index {i} out of range "
                         f"[0, {len(devs)})")
    return devs[i]


def get_device_properties(device: Optional[int] = None) -> Dict[str, Any]:
    d = _dev(device)
    stats = memory_stats(device)
    return {
        "name": getattr(d, "device_kind", d.platform),
        "platform": d.platform,
        "id": d.id,
        "process_index": d.process_index,
        "total_memory": stats.get("bytes_limit", 0),
        "coords": getattr(d, "coords", None),
    }


def memory_stats(device: Optional[int] = None) -> Dict[str, int]:
    """Raw PJRT allocator stats (≙ memory/stats.cc registry); {} only for
    backends that genuinely have no stats (CPU) — real PJRT errors (e.g.
    non-addressable device) propagate."""
    d = _dev(device)
    try:
        stats = d.memory_stats()
    except NotImplementedError:  # backend without allocator telemetry
        return {}
    return dict(stats or {})


def memory_allocated(device: Optional[int] = None) -> int:
    """Live bytes on the device (paddle.device.cuda.memory_allocated)."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device: Optional[int] = None) -> int:
    """Peak live bytes (paddle.device.cuda.max_memory_allocated)."""
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device: Optional[int] = None) -> int:
    """Allocator pool size; PJRT reports the usable limit."""
    return int(memory_stats(device).get("bytes_limit", 0))


def largest_alloc_size(device: Optional[int] = None) -> int:
    """Largest single live allocation — the number that explains "the
    limit says there's room but the arena is fragmented"."""
    return int(memory_stats(device).get("largest_alloc_size", 0))


def local_device_memory_stats(d: "jax.Device") -> Dict[str, int]:
    """PJRT allocator stats for one concrete (addressable) jax.Device;
    {} for backends without allocator telemetry (CPU)."""
    try:
        stats = d.memory_stats()
    except NotImplementedError:
        return {}
    return dict(stats or {})


def local_memory_stats() -> Dict[str, Dict[str, int]]:
    """{``platform:id``: stats} for every device addressable from this
    process — the per-worker HBM watermark table
    (``observability.memory`` samples this on a step cadence)."""
    return {f"{d.platform}:{d.id}": stats
            for d in jax.local_devices()
            if (stats := local_device_memory_stats(d))}


class _Namespace:
    """paddle.device.cuda / paddle.device.tpu alias namespaces."""

    device_count = staticmethod(device_count)
    memory_stats = staticmethod(memory_stats)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    get_device_properties = staticmethod(get_device_properties)


cuda = _Namespace()   # source compat for ported monitoring code
tpu = _Namespace()


# reference paddle.device __all__ parity: vendor-probe surface.  On this
# stack there is exactly one accelerator vendor (TPU via XLA); the CUDA/
# XPU/IPU/MLU probes answer honestly (False / N/A) so ported
# capability-detection code takes its CPU-or-accelerator branches
# correctly (docs/MIGRATION.md device table).
def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # the XLA compiler plays CINN's role; answer False to the literal
    # "is CINN present" probe (scripts branch to plain execution)
    return False


def get_cudnn_version():
    return None      # reference returns None when CUDA is absent


def XPUPlace(index: int = 0):
    from .framework import TPUPlace
    return TPUPlace(index)


def IPUPlace(index: int = 0):
    from .framework import TPUPlace
    return TPUPlace(index)


def MLUPlace(index: int = 0):
    from .framework import TPUPlace
    return TPUPlace(index)


def get_all_device_type() -> List[str]:
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type() -> List[str]:
    return []


def get_available_device() -> List[str]:
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device() -> List[str]:
    return []


__all__ += ["is_compiled_with_cuda", "is_compiled_with_rocm",
            "is_compiled_with_xpu", "is_compiled_with_ipu",
            "is_compiled_with_npu", "is_compiled_with_mlu",
            "is_compiled_with_cinn", "get_cudnn_version", "XPUPlace",
            "IPUPlace", "MLUPlace", "get_all_device_type",
            "get_all_custom_device_type", "get_available_device",
            "get_available_custom_device"]
