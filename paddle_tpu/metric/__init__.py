"""Metrics (reference: python/paddle/metric/metrics.py — Metric:83,
Accuracy:193, Precision:302, Recall:397, Auc:477)."""
from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(pred, label, k: int = 1):
    """Top-k accuracy of softmax outputs (reference metric/metrics.py:22)."""
    pred = np.asarray(pred)
    label = np.asarray(label)
    if label.ndim == pred.ndim:
        label = label.squeeze(-1)
    topk = np.argsort(-pred, axis=-1)[..., :k]
    correct = (topk == label[..., None]).any(axis=-1)
    return float(correct.mean())


class Metric:
    def reset(self):
        raise NotImplementedError

    def compute(self, *args):
        """Pass-through by default (reference metric/metrics.py:158): the
        trainer calls ``m.update(*to_tuple(m.compute(out, label)))``;
        subclasses override compute to preprocess on the accelerator side."""
        return args

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk))
        self.total = 0

    def compute(self, pred, label):
        """Returns per-sample correctness for each k (paddle compute/update
        split)."""
        pred = np.asarray(pred)
        label = np.asarray(label)
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        maxk = max(self.topk)
        topk = np.argsort(-pred, axis=-1)[..., :maxk]
        return (topk == label[..., None])

    def update(self, correct):
        correct = np.asarray(correct)
        for i, k in enumerate(self.topk):
            self.correct[i] += correct[..., :k].any(axis=-1).sum()
        self.total += correct.shape[0]
        return self.correct / max(self.total, 1)

    def accumulate(self):
        acc = (self.correct / max(self.total, 1)).tolist()
        return acc[0] if len(acc) == 1 else acc

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Bucketed ROC-AUC (reference metrics.py:477 — same thresholded-bucket
    algorithm as the C++ auc op)."""

    def __init__(self, num_thresholds: int = 4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]  # P(class=1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[labels == 1], 1)
        np.add.at(self._neg, idx[labels == 0], 1)

    def accumulate(self):
        tot_pos = self._pos[::-1].cumsum()[::-1]
        tot_neg = self._neg[::-1].cumsum()[::-1]
        tp = np.concatenate([tot_pos, [0]])
        fp = np.concatenate([tot_neg, [0]])
        area = -np.trapezoid(tp, fp) if hasattr(np, "trapezoid") else -np.trapz(tp, fp)
        denom = tot_pos[0] * tot_neg[0]
        return float(area / denom) if denom else 0.0

    def name(self):
        return self._name
