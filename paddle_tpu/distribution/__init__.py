"""paddle.distribution parity (reference python/paddle/distribution/ — the
torch.distributions-style API: Normal/Uniform/Categorical/Beta/Dirichlet/
Bernoulli + kl_divergence, SURVEY A14).

TPU-native: sampling draws keys from the framework RNG stream (eager) or an
explicit key (jit); densities are jnp compositions that fuse into the
surrounding program."""
from __future__ import annotations

import math

import numpy as np
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammaln

from ..framework import random as fw_random
from ..framework.errors import enforce

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Multinomial", "Independent",
           "TransformedDistribution", "kl_divergence", "register_kl",
           "Transform", "AffineTransform", "ExpTransform", "PowerTransform",
           "SigmoidTransform", "TanhTransform", "AbsTransform",
           "ChainTransform"]


def _key(key):
    return key if key is not None else fw_random.next_key()


def _arr(x):
    return jnp.asarray(x, jnp.float32) if not isinstance(
        x, jnp.ndarray) else x


class Distribution:
    def sample(self, shape: Sequence[int] = (), key=None):
        raise NotImplementedError

    def rsample(self, shape: Sequence[int] = (), key=None):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    """Reference distribution/normal.py."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return jnp.square(self.scale)

    def sample(self, shape=(), key=None):
        return self.rsample(shape, key)

    def rsample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_key(key), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        var = jnp.square(self.scale)
        return (-jnp.square(_arr(value) - self.loc) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
            jnp.broadcast_to(self.scale, jnp.broadcast_shapes(
                self.loc.shape, self.scale.shape)))

    def kl_divergence(self, other: "Normal"):
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


class Uniform(Distribution):
    """Reference distribution/uniform.py: U[low, high)."""

    def __init__(self, low, high):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=(), key=None):
        return self.rsample(shape, key)

    def rsample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_key(key), shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        value = _arr(value)
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)


class Categorical(Distribution):
    """Reference distribution/categorical.py (logits parameterization)."""

    def __init__(self, logits=None, probs=None):
        if logits is None:
            logits = jnp.log(jnp.clip(_arr(probs), 1e-30))
        self.logits = _arr(logits)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(_key(key), self.logits,
                                      shape=tuple(shape)
                                      + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, jnp.asarray(value, jnp.int32)[..., None], axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    def kl_divergence(self, other: "Categorical"):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        logq = jax.nn.log_softmax(other.logits, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs_ = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs_.shape
        return jax.random.bernoulli(_key(key), self.probs_, shape).astype(
            jnp.float32)

    def log_prob(self, value):
        v = _arr(value)
        return v * jnp.log(self.probs_) + (1 - v) * jnp.log1p(-self.probs_)

    def entropy(self):
        p = self.probs_
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Beta(Distribution):
    """Reference distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                    self.beta.shape)
        return jax.random.beta(_key(key), self.alpha, self.beta, shape)

    def log_prob(self, value):
        v = _arr(value)
        return ((self.alpha - 1) * jnp.log(v)
                + (self.beta - 1) * jnp.log1p(-v)
                - betaln(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    """Reference distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _arr(concentration)

    def sample(self, shape=(), key=None):
        return jax.random.dirichlet(_key(key), self.concentration,
                                    tuple(shape))

    def log_prob(self, value):
        c = self.concentration
        v = _arr(value)
        norm = jnp.sum(gammaln(c), -1) - gammaln(jnp.sum(c, -1))
        return jnp.sum((c - 1) * jnp.log(v), -1) - norm

    def entropy(self):
        c = self.concentration
        c0 = jnp.sum(c, -1)
        k = c.shape[-1]
        norm = jnp.sum(gammaln(c), -1) - gammaln(c0)
        return (norm + (c0 - k) * digamma(c0)
                - jnp.sum((c - 1) * digamma(c), -1))


class Multinomial(Distribution):
    """Reference distribution/multinomial.py: counts over k categories
    from ``total_count`` draws."""

    def __init__(self, total_count: int, probs):
        self.total_count = int(total_count)
        p = _arr(probs)
        self.probs = p / jnp.sum(p, axis=-1, keepdims=True)

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=(), key=None):
        logits = jnp.log(jnp.clip(self.probs, 1e-30))
        draws = jax.random.categorical(
            _key(key), logits,
            shape=(self.total_count,) + tuple(shape)
            + self.probs.shape[:-1])
        onehot = jax.nn.one_hot(draws, self.probs.shape[-1])
        return jnp.sum(onehot, axis=0)

    def log_prob(self, value):
        v = _arr(value)
        return (gammaln(self.total_count + 1.0)
                - jnp.sum(gammaln(v + 1.0), -1)
                + jnp.sum(v * jnp.log(jnp.clip(self.probs, 1e-30)), -1))

    def entropy(self):
        # exact series: H = -log n! - n Σ p_i log p_i
        #                   + Σ_i Σ_{x=0}^{n} Binom(n, x, p_i) log x!
        n = self.total_count
        p = self.probs
        x = jnp.arange(n + 1, dtype=jnp.float32)
        log_binom = (gammaln(n + 1.0) - gammaln(x + 1.0)
                     - gammaln(n - x + 1.0))
        logp = jnp.log(jnp.clip(p, 1e-30))
        log1mp = jnp.log(jnp.clip(1.0 - p, 1e-30))
        # (..., k, n+1) pmf of each marginal count
        pmf = jnp.exp(log_binom + x * logp[..., None]
                      + (n - x) * log1mp[..., None])
        e_logfact = jnp.sum(pmf * gammaln(x + 1.0), axis=-1)
        return (-gammaln(n + 1.0) - n * jnp.sum(p * logp, -1)
                + jnp.sum(e_logfact, -1))


class Independent(Distribution):
    """Reinterpret the rightmost batch dims as event dims (reference
    distribution/independent.py): log_prob sums over them."""

    def __init__(self, base: Distribution,
                 reinterpreted_batch_ndims: int = 1):
        self.base = base
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)

    def sample(self, shape=(), key=None):
        return self.base.sample(shape, key)

    def rsample(self, shape=(), key=None):
        return self.base.rsample(shape, key)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        axes = tuple(range(-self.reinterpreted_batch_ndims, 0))
        return jnp.sum(lp, axis=axes)

    def entropy(self):
        e = self.base.entropy()
        axes = tuple(range(-self.reinterpreted_batch_ndims, 0))
        return jnp.sum(e, axis=axes)


# ---------------------------------------------------------------------------
# Transforms (reference distribution/transform.py) — bijectors with
# forward/inverse/log-det used by TransformedDistribution
# ---------------------------------------------------------------------------
class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    """y = loc + scale * x (reference transform.py AffineTransform)."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def forward(self, x):
        return self.loc + self.scale * _arr(x)

    def inverse(self, y):
        return (_arr(y) - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)),
                                jnp.shape(x))


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(_arr(x))

    def inverse(self, y):
        return jnp.log(_arr(y))

    def forward_log_det_jacobian(self, x):
        return _arr(x)


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def forward(self, x):
        return jnp.power(_arr(x), self.power)

    def inverse(self, y):
        return jnp.power(_arr(y), 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        x = _arr(x)
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(_arr(x))

    def inverse(self, y):
        y = _arr(y)
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        x = _arr(x)
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(_arr(x))

    def inverse(self, y):
        return jnp.arctanh(_arr(y))

    def forward_log_det_jacobian(self, x):
        x = _arr(x)
        # log(1 - tanh^2 x) in a numerically stable form
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    def forward(self, x):
        return jnp.abs(_arr(x))

    def inverse(self, y):   # principal branch
        return _arr(y)

    def forward_log_det_jacobian(self, x):
        return jnp.zeros_like(_arr(x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """base distribution pushed through transforms (reference
    distribution/transformed_distribution.py): sample = T(base.sample());
    log_prob(y) = base.log_prob(T^-1(y)) - log|det J_T(T^-1(y))|."""

    def __init__(self, base: Distribution, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transform = ChainTransform(list(transforms))

    def sample(self, shape=(), key=None):
        return self.transform.forward(self.base.sample(shape, key))

    def rsample(self, shape=(), key=None):
        return self.transform.forward(self.base.rsample(shape, key))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        return (self.base.log_prob(x)
                - self.transform.forward_log_det_jacobian(x))


# ---------------------------------------------------------------------------
# kl registry (reference distribution/kl.py: register_kl decorator +
# most-specific dispatch)
# ---------------------------------------------------------------------------
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Decorator registering a pairwise kl rule (reference kl.py:40)."""
    def wrap(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return wrap


def kl_divergence(p: Distribution, q: Distribution):
    """Registry dispatch with most-specific match (reference kl.py:26)."""
    matches = [(pc, qc) for (pc, qc) in _KL_REGISTRY
               if isinstance(p, pc) and isinstance(q, qc)]
    if matches:
        # most-specific: minimal (by subclass ordering) pair
        def depth(pair):
            return (len(type(p).__mro__) - type(p).__mro__.index(pair[0]),
                    len(type(q).__mro__) - type(q).__mro__.index(pair[1]))
        best = max(matches, key=depth)
        return _KL_REGISTRY[best](p, q)
    if hasattr(p, "kl_divergence") and type(p) is type(q):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return p.kl_divergence(q)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a, b = p.probs_, q.probs_
    return (a * (jnp.log(a) - jnp.log(b))
            + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return (betaln(a2, b2) - betaln(a1, b1)
            + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
            + (a2 - a1 + b2 - b1) * digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    c1, c2 = p.concentration, q.concentration
    s1 = jnp.sum(c1, -1)
    return (gammaln(s1) - jnp.sum(gammaln(c1), -1)
            - gammaln(jnp.sum(c2, -1)) + jnp.sum(gammaln(c2), -1)
            + jnp.sum((c1 - c2) * (digamma(c1) - digamma(s1)[..., None]),
                      -1))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    kl = jnp.log(q.high - q.low) - jnp.log(p.high - p.low)
    contained = (q.low <= p.low) & (p.high <= q.high)
    return jnp.where(contained, kl, jnp.inf)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference
    distribution/exponential_family.py): subclasses expose natural
    parameters + log-normalizer; entropy falls out via the Bregman
    identity (autodiff of the log-normalizer against the natural
    parameters — the reference's _mean_carrier_measure pattern)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        """-E[log p] via η·∇A(η) - A(η) (Bregman / Legendre duality),
        elementwise over batched natural parameters — entropy keeps the
        distribution's batch shape like every other Distribution here."""
        nat = [jnp.asarray(p) for p in self._natural_parameters]
        logA = self._log_normalizer(*nat)
        grads = jax.grad(
            lambda *ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=tuple(range(len(nat))))(*nat)
        ent = logA - self._mean_carrier_measure
        for p, g in zip(nat, grads):
            ent = ent - p * g
        return ent


__all__.append("ExponentialFamily")


class IndependentTransform(Transform):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` dims as
    event dims: log-dets sum over them (reference transform.py
    IndependentTransform)."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self._base.forward(x)

    def inverse(self, y):
        return self._base.inverse(y)

    def _sum_rightmost(self, v):
        for _ in range(self._rank):
            v = jnp.sum(v, axis=-1)
        return v

    def forward_log_det_jacobian(self, x):
        return self._sum_rightmost(
            self._base.forward_log_det_jacobian(x))


class ReshapeTransform(Transform):
    """Event reshape (reference transform.py ReshapeTransform); volume
    preserving — log-det 0."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        enforce(int(np.prod(self.in_event_shape))
                == int(np.prod(self.out_event_shape)),
                "reshape must preserve the event volume")

    def forward(self, x):
        x = _arr(x)
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def inverse(self, y):
        y = _arr(y)
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def forward_log_det_jacobian(self, x):
        x = _arr(x)
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, jnp.float32)


class SoftmaxTransform(Transform):
    """x -> softmax over the last dim (reference SoftmaxTransform; not
    bijective on R^n, inverse is log up to an additive constant)."""

    def forward(self, x):
        return jax.nn.softmax(_arr(x), axis=-1)

    def inverse(self, y):
        return jnp.log(_arr(y))


class StackTransform(Transform):
    """Apply a list of transforms along slices of ``axis`` (reference
    StackTransform)."""

    def __init__(self, transforms, axis: int = 0):
        self._transforms = list(transforms)
        self._axis = axis

    def _map(self, fn_name, v):
        v = _arr(v)
        parts = [getattr(t, fn_name)(s.squeeze(self._axis))
                 for t, s in zip(self._transforms,
                                 jnp.split(v, len(self._transforms),
                                           axis=self._axis))]
        return jnp.stack(parts, axis=self._axis)

    def forward(self, x):
        return self._map("forward", x)

    def inverse(self, y):
        return self._map("inverse", y)

    def forward_log_det_jacobian(self, x):
        return self._map("forward_log_det_jacobian", x)


class StickBreakingTransform(Transform):
    """R^k -> interior of the (k+1)-simplex via stick-breaking
    (reference StickBreakingTransform)."""

    def forward(self, x):
        x = _arr(x).astype(jnp.float32)
        k = x.shape[-1]
        offset = jnp.log(jnp.asarray(k, jnp.float32)
                         - jnp.arange(k, dtype=jnp.float32))
        z = jax.nn.sigmoid(x - offset)
        one_minus = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(one_minus[..., :1]), one_minus[..., :-1]],
            axis=-1)
        head = z * lead
        return jnp.concatenate([head, one_minus[..., -1:]], axis=-1)

    def inverse(self, y):
        y = _arr(y).astype(jnp.float32)
        k = y.shape[-1] - 1
        cum = jnp.concatenate(
            [jnp.zeros_like(y[..., :1]), jnp.cumsum(y[..., :-1], -1)],
            axis=-1)[..., :-1]
        z = y[..., :-1] / jnp.maximum(1 - cum, 1e-30)
        offset = jnp.log(jnp.asarray(k, jnp.float32)
                         - jnp.arange(k, dtype=jnp.float32))
        return jnp.log(z / jnp.maximum(1 - z, 1e-30)) + offset

    def forward_log_det_jacobian(self, x):
        x = _arr(x).astype(jnp.float32)
        k = x.shape[-1]
        offset = jnp.log(jnp.asarray(k, jnp.float32)
                         - jnp.arange(k, dtype=jnp.float32))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        one_minus = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(one_minus[..., :1]), one_minus[..., :-1]],
            axis=-1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(lead), axis=-1)


__all__ += ["IndependentTransform", "ReshapeTransform", "SoftmaxTransform",
            "StackTransform", "StickBreakingTransform"]
