"""paddle.distribution parity (reference python/paddle/distribution/ — the
torch.distributions-style API: Normal/Uniform/Categorical/Beta/Dirichlet/
Bernoulli + kl_divergence, SURVEY A14).

TPU-native: sampling draws keys from the framework RNG stream (eager) or an
explicit key (jit); densities are jnp compositions that fuse into the
surrounding program."""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammaln

from ..framework import random as fw_random

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "kl_divergence"]


def _key(key):
    return key if key is not None else fw_random.next_key()


def _arr(x):
    return jnp.asarray(x, jnp.float32) if not isinstance(
        x, jnp.ndarray) else x


class Distribution:
    def sample(self, shape: Sequence[int] = (), key=None):
        raise NotImplementedError

    def rsample(self, shape: Sequence[int] = (), key=None):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return jnp.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError


class Normal(Distribution):
    """Reference distribution/normal.py."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return jnp.square(self.scale)

    def sample(self, shape=(), key=None):
        return self.rsample(shape, key)

    def rsample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(_key(key), shape)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        var = jnp.square(self.scale)
        return (-jnp.square(_arr(value) - self.loc) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
            jnp.broadcast_to(self.scale, jnp.broadcast_shapes(
                self.loc.shape, self.scale.shape)))

    def kl_divergence(self, other: "Normal"):
        var_ratio = jnp.square(self.scale / other.scale)
        t1 = jnp.square((self.loc - other.loc) / other.scale)
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


class Uniform(Distribution):
    """Reference distribution/uniform.py: U[low, high)."""

    def __init__(self, low, high):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=(), key=None):
        return self.rsample(shape, key)

    def rsample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(_key(key), shape)
        return self.low + (self.high - self.low) * u

    def log_prob(self, value):
        value = _arr(value)
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)


class Categorical(Distribution):
    """Reference distribution/categorical.py (logits parameterization)."""

    def __init__(self, logits=None, probs=None):
        if logits is None:
            logits = jnp.log(jnp.clip(_arr(probs), 1e-30))
        self.logits = _arr(logits)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), key=None):
        return jax.random.categorical(_key(key), self.logits,
                                      shape=tuple(shape)
                                      + self.logits.shape[:-1])

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp, jnp.asarray(value, jnp.int32)[..., None], axis=-1)[..., 0]

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    def kl_divergence(self, other: "Categorical"):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        logq = jax.nn.log_softmax(other.logits, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)


class Bernoulli(Distribution):
    def __init__(self, probs):
        self.probs_ = jnp.clip(_arr(probs), 1e-7, 1 - 1e-7)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + self.probs_.shape
        return jax.random.bernoulli(_key(key), self.probs_, shape).astype(
            jnp.float32)

    def log_prob(self, value):
        v = _arr(value)
        return v * jnp.log(self.probs_) + (1 - v) * jnp.log1p(-self.probs_)

    def entropy(self):
        p = self.probs_
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))


class Beta(Distribution):
    """Reference distribution/beta.py."""

    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    def sample(self, shape=(), key=None):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                    self.beta.shape)
        return jax.random.beta(_key(key), self.alpha, self.beta, shape)

    def log_prob(self, value):
        v = _arr(value)
        return ((self.alpha - 1) * jnp.log(v)
                + (self.beta - 1) * jnp.log1p(-v)
                - betaln(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                + (a + b - 2) * digamma(a + b))


class Dirichlet(Distribution):
    """Reference distribution/dirichlet.py."""

    def __init__(self, concentration):
        self.concentration = _arr(concentration)

    def sample(self, shape=(), key=None):
        return jax.random.dirichlet(_key(key), self.concentration,
                                    tuple(shape))

    def log_prob(self, value):
        c = self.concentration
        v = _arr(value)
        norm = jnp.sum(gammaln(c), -1) - gammaln(jnp.sum(c, -1))
        return jnp.sum((c - 1) * jnp.log(v), -1) - norm

    def entropy(self):
        c = self.concentration
        c0 = jnp.sum(c, -1)
        k = c.shape[-1]
        norm = jnp.sum(gammaln(c), -1) - gammaln(c0)
        return (norm + (c0 - k) * digamma(c0)
                - jnp.sum((c - 1) * digamma(c), -1))


def kl_divergence(p: Distribution, q: Distribution):
    """Reference distribution/kl.py dispatch."""
    if hasattr(p, "kl_divergence") and type(p) is type(q):
        return p.kl_divergence(q)
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
