"""paddle.sparse parity (reference paddle/phi sparse kernels + python
paddle.sparse API: SparseCooTensor/SparseCsrTensor, SURVEY C6).

TPU-native substrate: jax.experimental.sparse.BCOO — XLA's batched-COO
format with native lowering of sparse-dense matmul (the phi
sparse_coo kernels' role).  CSR is represented by converting to BCOO at
construction (TPU has no CSR-specific units; the format distinction is an
API-compat concern, kept via ``.layout``)."""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseTensor",
           "is_sparse", "add", "matmul", "masked_matmul", "relu", "to_dense"]


class SparseTensor:
    """Thin wrapper over BCOO carrying the paddle surface
    (indices/values/to_dense/nnz; layout 'coo' or 'csr')."""

    def __init__(self, bcoo: jsparse.BCOO, layout: str = "coo"):
        self._bcoo = bcoo
        self.layout = layout

    # -- paddle surface ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self):
        return self._bcoo.indices.T  # paddle: (ndim, nnz)

    def values(self):
        return self._bcoo.data

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self):
        return self._bcoo.todense()

    def bcoo(self) -> jsparse.BCOO:
        return self._bcoo

    def __repr__(self):
        return (f"SparseTensor(layout={self.layout}, shape={self.shape}, "
                f"nnz={self.nnz()})")


def sparse_coo_tensor(indices, values, shape: Sequence[int],
                      dtype=None) -> SparseTensor:
    """paddle.sparse.sparse_coo_tensor(indices (ndim, nnz), values)."""
    idx = jnp.asarray(indices).T.astype(jnp.int32)   # BCOO: (nnz, ndim)
    vals = jnp.asarray(values, dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)),
                        layout="coo")


def sparse_csr_tensor(crows, cols, values, shape: Sequence[int],
                      dtype=None) -> SparseTensor:
    """paddle.sparse.sparse_csr_tensor — stored as BCOO internally."""
    crows = np.asarray(crows)
    cols = np.asarray(cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    vals = jnp.asarray(values, dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)),
                        layout="csr")


def is_sparse(x) -> bool:
    return isinstance(x, SparseTensor)


def to_dense(x):
    return x.to_dense() if is_sparse(x) else jnp.asarray(x)


def add(a: SparseTensor, b: SparseTensor) -> SparseTensor:
    summed = (a.bcoo() + b.bcoo()).sum_duplicates()
    return SparseTensor(summed, layout=a.layout)


def matmul(a, b):
    """sparse @ dense (or dense @ sparse) → dense; the phi
    sparse_coo matmul kernel's role, lowered by XLA from BCOO dot."""
    if is_sparse(a):
        return a.bcoo() @ jnp.asarray(b)
    if is_sparse(b):
        return jnp.asarray(a) @ b.bcoo()
    return jnp.asarray(a) @ jnp.asarray(b)


def masked_matmul(a, b, mask: SparseTensor) -> SparseTensor:
    """(dense @ dense) sampled at mask's sparsity pattern (SDDMM;
    reference sparse masked_matmul)."""
    m = mask.bcoo()
    rows = m.indices[:, 0]
    cols = m.indices[:, 1]
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseTensor(jsparse.BCOO((vals, m.indices), shape=m.shape),
                        layout=mask.layout)


def relu(x: SparseTensor) -> SparseTensor:
    """Elementwise on the stored values (reference sparse relu kernel)."""
    b = x.bcoo()
    return SparseTensor(jsparse.BCOO((jnp.maximum(b.data, 0), b.indices),
                                     shape=b.shape), layout=x.layout)
