"""paddle.sparse parity (reference python/paddle/sparse — creation +
layer/activation — and the paddle/phi/kernels/sparse corpus:
sparse_utils_kernel.h dense↔coo↔csr conversions, activation_kernel.h
value-wise unaries, matmul/masked-matmul, softmax; SURVEY C6).

TPU-native substrate: jax.experimental.sparse.BCOO — XLA's batched-COO
format with native lowering of sparse-dense matmul (the phi sparse_coo
kernels' role).  CSR is represented by converting to BCOO at
construction (TPU has no CSR-specific units; the format distinction is an
API-compat concern, kept via ``.layout``).  Everything stays jittable:
nse is static, value-wise ops map over ``.data``, and row-wise softmax
uses segment reductions over the static index set.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.errors import enforce

__all__ = [
    "SparseTensor", "sparse_coo_tensor", "sparse_csr_tensor", "is_sparse",
    "to_dense", "to_sparse_coo", "to_sparse_csr", "coalesce",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "mv", "addmm", "transpose", "softmax",
    "relu", "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "expm1", "neg", "pow", "cast",
    "nn",
]


class SparseTensor:
    """Thin wrapper over BCOO carrying the paddle surface
    (indices/values/crows/cols/to_dense/nnz; layout 'coo' or 'csr')."""

    def __init__(self, bcoo: jsparse.BCOO, layout: str = "coo"):
        self._bcoo = bcoo
        self.layout = layout

    # -- paddle surface ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def ndim(self):
        return len(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self):
        return self._bcoo.indices.T  # paddle: (ndim, nnz)

    def values(self):
        return self._bcoo.data

    def crows(self):
        """CSR row-pointer view (row-major sorted internally, so it is
        consistent with cols()/values() regardless of insertion order)."""
        enforce(self.ndim == 2, "crows() needs a 2-d sparse tensor")
        rows = _sorted(self._bcoo).indices[:, 0]
        n = self.shape[0]
        counts = jnp.zeros((n,), jnp.int32).at[rows].add(1)
        return jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(counts)])

    def cols(self):
        enforce(self.ndim == 2, "cols() needs a 2-d sparse tensor")
        return _sorted(self._bcoo).indices[:, 1]

    def csr_values(self):
        """Values in the same row-major order as crows()/cols()."""
        enforce(self.ndim == 2, "csr_values() needs a 2-d sparse tensor")
        return _sorted(self._bcoo).data

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self):
        return self._bcoo.todense()

    def to_sparse_csr(self) -> "SparseTensor":
        return SparseTensor(_sorted(self._bcoo), layout="csr")

    def to_sparse_coo(self, sparse_dim: Optional[int] = None
                      ) -> "SparseTensor":
        return SparseTensor(self._bcoo, layout="coo")

    def bcoo(self) -> jsparse.BCOO:
        return self._bcoo

    def astype(self, dtype):
        return cast(self, dtype)

    def __repr__(self):
        return (f"SparseTensor(layout={self.layout}, shape={self.shape}, "
                f"nnz={self.nnz()})")


def _sorted(b: jsparse.BCOO) -> jsparse.BCOO:
    """Row-major sorted indices (CSR invariant)."""
    key = b.indices[:, 0] * b.shape[1] + b.indices[:, 1] \
        if len(b.shape) == 2 else b.indices[:, 0]
    order = jnp.argsort(key)
    return jsparse.BCOO((b.data[order], b.indices[order]), shape=b.shape)


def sparse_coo_tensor(indices, values, shape: Sequence[int],
                      dtype=None) -> SparseTensor:
    """paddle.sparse.sparse_coo_tensor(indices (ndim, nnz), values)
    (reference creation.py:30)."""
    idx = jnp.asarray(indices).T.astype(jnp.int32)   # BCOO: (nnz, ndim)
    vals = jnp.asarray(values, dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)),
                        layout="coo")


def sparse_csr_tensor(crows, cols, values, shape: Sequence[int],
                      dtype=None) -> SparseTensor:
    """paddle.sparse.sparse_csr_tensor (reference creation.py:103) —
    stored as BCOO internally."""
    crows = np.asarray(crows)
    cols = np.asarray(cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    vals = jnp.asarray(values, dtype)
    return SparseTensor(jsparse.BCOO((vals, idx), shape=tuple(shape)),
                        layout="csr")


def is_sparse(x) -> bool:
    return isinstance(x, SparseTensor)


def to_dense(x):
    return x.to_dense() if is_sparse(x) else jnp.asarray(x)


def to_sparse_coo(x, sparse_dim: Optional[int] = None) -> SparseTensor:
    """Dense → COO (phi sparse_utils DenseToSparseCoo); nse is the exact
    nonzero count, so use outside jit (static shapes)."""
    x = jnp.asarray(x)
    nse = int(jnp.sum(x != 0))
    return SparseTensor(jsparse.BCOO.fromdense(x, nse=nse), layout="coo")


def to_sparse_csr(x) -> SparseTensor:
    """Dense → CSR (phi sparse_utils DenseToSparseCsr)."""
    t = to_sparse_coo(x)
    return SparseTensor(_sorted(t.bcoo()), layout="csr")


def coalesce(x: SparseTensor) -> SparseTensor:
    """Merge duplicate indices (phi CoalesceKernel).  nse stays the input's
    static nse (duplicates merge into padded out-of-range entries), so the
    op is jit-safe."""
    b = x.bcoo()
    return SparseTensor(_sorted(b.sum_duplicates(nse=b.nse)),
                        layout=x.layout)


# ---------------------------------------------------------------------------
# elementwise sparse∘sparse (phi sparse elementwise kernels): operate on the
# union pattern via BCOO addition identities
# ---------------------------------------------------------------------------
def add(a: SparseTensor, b: SparseTensor) -> SparseTensor:
    merged = a.bcoo() + b.bcoo()
    return SparseTensor(merged.sum_duplicates(nse=merged.nse),
                        layout=a.layout)


def subtract(a: SparseTensor, b: SparseTensor) -> SparseTensor:
    bb = b.bcoo()
    negb = jsparse.BCOO((-bb.data, bb.indices), shape=bb.shape)
    merged = a.bcoo() + negb
    return SparseTensor(merged.sum_duplicates(nse=merged.nse),
                        layout=a.layout)


def multiply(a: SparseTensor, b: SparseTensor) -> SparseTensor:
    """Elementwise product — zero wherever either is zero, so evaluate b
    densely at a's pattern (keeps a's static nse)."""
    ab = coalesce(a).bcoo()
    bd = to_dense(b)
    vals = ab.data * bd[tuple(ab.indices.T)]
    return SparseTensor(jsparse.BCOO((vals, ab.indices), shape=ab.shape),
                        layout=a.layout)


def divide(a: SparseTensor, b: SparseTensor) -> SparseTensor:
    ab = coalesce(a).bcoo()
    bd = to_dense(b)
    vals = ab.data / bd[tuple(ab.indices.T)]
    return SparseTensor(jsparse.BCOO((vals, ab.indices), shape=ab.shape),
                        layout=a.layout)


# ---------------------------------------------------------------------------
# linear algebra
# ---------------------------------------------------------------------------
def matmul(a, b):
    """sparse @ dense (or dense @ sparse) → dense; the phi
    sparse_coo matmul kernel's role, lowered by XLA from BCOO dot."""
    if is_sparse(a):
        return a.bcoo() @ jnp.asarray(b)
    if is_sparse(b):
        return jnp.asarray(a) @ b.bcoo()
    return jnp.asarray(a) @ jnp.asarray(b)


def mv(a: SparseTensor, x) -> jax.Array:
    """sparse matrix × dense vector (phi sparse mv kernel)."""
    return a.bcoo() @ jnp.asarray(x)


def addmm(input, x: SparseTensor, y, beta: float = 1.0,
          alpha: float = 1.0) -> jax.Array:
    """beta*input + alpha*(x @ y) — reference sparse addmm."""
    return beta * jnp.asarray(input) + alpha * matmul(x, y)


def masked_matmul(a, b, mask: SparseTensor) -> SparseTensor:
    """(dense @ dense) sampled at mask's sparsity pattern (SDDMM;
    reference sparse masked_matmul)."""
    m = mask.bcoo()
    rows = m.indices[:, 0]
    cols = m.indices[:, 1]
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseTensor(jsparse.BCOO((vals, m.indices), shape=m.shape),
                        layout=mask.layout)


def transpose(x: SparseTensor, perm: Optional[Sequence[int]] = None
              ) -> SparseTensor:
    enforce(x.ndim == 2, "sparse transpose supports 2-d tensors")
    if perm is not None:
        perm = list(perm)
        enforce(sorted(perm) == [0, 1], f"invalid perm {perm} for 2-d")
        if perm == [0, 1]:   # identity permutation
            return x
    b = x.bcoo()
    idx = b.indices[:, ::-1]
    return SparseTensor(
        _sorted(jsparse.BCOO((b.data, idx),
                             shape=(b.shape[1], b.shape[0]))),
        layout=x.layout)


def softmax(x: SparseTensor, axis: int = -1) -> SparseTensor:
    """Row-wise softmax over the stored values only (phi sparse softmax:
    implicit zeros are NOT part of the distribution)."""
    enforce(x.ndim == 2 and axis in (-1, 1),
            "sparse softmax: 2-d, last axis")
    b = coalesce(x).bcoo()
    rows = b.indices[:, 0]
    n = x.shape[0]
    m = jax.ops.segment_max(b.data, rows, num_segments=n)
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    e = jnp.exp(b.data - m[rows])
    z = jax.ops.segment_sum(e, rows, num_segments=n)
    vals = e / jnp.maximum(z[rows], 1e-30)
    return SparseTensor(jsparse.BCOO((vals, b.indices), shape=b.shape),
                        layout=x.layout)


# ---------------------------------------------------------------------------
# value-wise unaries (phi sparse activation_kernel.h family): act on stored
# values, pattern unchanged — valid exactly for f(0)=0 functions, the same
# set the reference registers
# ---------------------------------------------------------------------------
def _valuewise(name: str, fn: Callable) -> Callable:
    def op(x: SparseTensor, *args) -> SparseTensor:
        b = x.bcoo()
        return SparseTensor(
            jsparse.BCOO((fn(b.data, *args), b.indices), shape=b.shape),
            layout=x.layout)
    op.__name__ = name
    op.__doc__ = f"sparse.{name}: value-wise (pattern preserved)."
    return op


relu = _valuewise("relu", lambda v: jnp.maximum(v, 0))
sin = _valuewise("sin", jnp.sin)
tan = _valuewise("tan", jnp.tan)
asin = _valuewise("asin", jnp.arcsin)
atan = _valuewise("atan", jnp.arctan)
sinh = _valuewise("sinh", jnp.sinh)
tanh = _valuewise("tanh", jnp.tanh)
asinh = _valuewise("asinh", jnp.arcsinh)
atanh = _valuewise("atanh", jnp.arctanh)
sqrt = _valuewise("sqrt", jnp.sqrt)
square = _valuewise("square", jnp.square)
log1p = _valuewise("log1p", jnp.log1p)
abs = _valuewise("abs", jnp.abs)
expm1 = _valuewise("expm1", jnp.expm1)
neg = _valuewise("neg", jnp.negative)
pow = _valuewise("pow", lambda v, p: jnp.power(v, p))
cast = _valuewise("cast", lambda v, dt: v.astype(dt))


# ---------------------------------------------------------------------------
# sparse.nn (reference layer/activation.py ReLU + the attention built from
# subsystem ops: SDDMM → sparse softmax → SpMM)
# ---------------------------------------------------------------------------
class _SparseNNFunctional:
    @staticmethod
    def relu(x: SparseTensor) -> SparseTensor:
        return relu(x)

    @staticmethod
    def attention(query, key, value, sparse_mask: SparseTensor,
                  scale: Optional[float] = None) -> jax.Array:
        """Single-head sparse attention from subsystem primitives:
        scores = masked_matmul(q, k^T) at the mask pattern, row softmax
        over stored entries, then sparse @ v.  The batched CSR entry
        point is nn.functional.sparse_attention."""
        q = jnp.asarray(query)
        k = jnp.asarray(key)
        if scale is None:
            scale = q.shape[-1] ** -0.5
        s = masked_matmul(q * scale, k.T, sparse_mask)
        p = softmax(s)
        return matmul(p, jnp.asarray(value))


class _ReLULayer:
    """paddle.sparse.ReLU (reference layer/activation.py:22)."""

    def __call__(self, x: SparseTensor) -> SparseTensor:
        return relu(x)

    def forward(self, x: SparseTensor) -> SparseTensor:
        return relu(x)


class _SparseNN:
    ReLU = _ReLULayer
    functional = _SparseNNFunctional


nn = _SparseNN
ReLU = _ReLULayer
