"""paddle.hub (reference python/paddle/hub.py): load models from remote
repos.  Gated in this environment (no network egress) the same way
onnx export is — local repo dirs still work."""
from __future__ import annotations

import importlib.util
import os
from typing import List

from .framework.errors import enforce

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"
_CACHE: dict = {}


def _load_hubconf(repo_dir: str, force_reload: bool = False):
    enforce(os.path.isdir(repo_dir),
            f"hub: remote sources need network egress (disabled); pass a "
            f"LOCAL repo directory (got {repo_dir!r})")
    path = os.path.join(repo_dir, _HUBCONF)
    enforce(os.path.exists(path), f"hub: no {_HUBCONF} in {repo_dir!r}")
    key = (os.path.abspath(path), os.path.getmtime(path))
    if not force_reload and key in _CACHE:
        return _CACHE[key]
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _CACHE[key] = mod
    return mod


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:
    """Entrypoints exported by a local repo's hubconf.py."""
    mod = _load_hubconf(repo_dir, force_reload)
    return [n for n in dir(mod)
            if not n.startswith("_") and callable(getattr(mod, n))]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:
    mod = _load_hubconf(repo_dir, force_reload)
    fn = getattr(mod, model, None)
    enforce(fn is not None, f"hub: no entrypoint {model!r} in {repo_dir!r}")
    return fn.__doc__ or ""


def load(repo_dir: str, model: str, *args, source: str = "local",
         force_reload: bool = False, **kwargs):
    mod = _load_hubconf(repo_dir, force_reload)
    fn = getattr(mod, model, None)
    enforce(fn is not None, f"hub: no entrypoint {model!r} in {repo_dir!r}")
    return fn(*args, **kwargs)
