"""paddle.onnx analog (reference: python/paddle/onnx/export.py:21
``paddle.onnx.export`` via paddle2onnx).

This environment ships no ``onnx`` package (and installs are not
permitted), so ONNX serialization is gated: ``export`` raises with the
TPU-native alternative spelled out.  The deployment path of this framework
is ``paddle_tpu.jit.save`` — a StableHLO artifact that needs no model code
and feeds XLA-based serving directly (SURVEY L9: XLA is the engine).
"""
from __future__ import annotations

import importlib.util

__all__ = ["export", "onnx_available"]


def onnx_available() -> bool:
    return importlib.util.find_spec("onnx") is not None


def export(layer, path: str, input_spec=None, opset_version: int = 9,
           **configs):
    """Export ``layer`` to ONNX (reference onnx/export.py:21).

    Requires the ``onnx`` package; unavailable in this build — use
    ``paddle_tpu.jit.save(layer, path, input_spec)`` for a
    StableHLO serving artifact instead."""
    if not onnx_available():
        raise RuntimeError(
            "paddle_tpu.onnx.export requires the 'onnx' package, which is "
            "not installed in this environment (and package installs are "
            "disabled). Use paddle_tpu.jit.save(layer, path, input_spec) "
            "to produce a StableHLO serving artifact — the TPU-native "
            "deployment format consumed by paddle_tpu.inference.")
    raise NotImplementedError(
        "onnx graph building is not implemented; jit.save is the "
        "supported export path")
