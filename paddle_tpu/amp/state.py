"""AMP policy state consulted by functional ops.

TPU-native analog of the reference's per-op white/black cast lists
(reference: python/paddle/fluid/dygraph/amp/auto_cast.py:33-79 WHITE_LIST/
BLACK_LIST; tracer-side casting imperative/tracer.cc:223-231, amp_auto_cast.cc).

On TPU the low-precision dtype is bfloat16 by default (fp16 supported for
parity).  Ops call :func:`cast_for_op` on their matmul-class inputs; the
active policy decides whether to cast.  Everything is trace-friendly: the
policy is host-side python state read at trace time, so a jitted train step
bakes the policy in (the reference does the same — the cast ops are recorded
into the program).
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

# Op categories (mirrors the reference's list semantics).
WHITE_OPS = {  # always compute in low precision (MXU-bound)
    "matmul", "linear", "conv2d", "einsum", "attention",
}
BLACK_OPS = {  # keep fp32 (numerically sensitive)
    "softmax", "log_softmax", "layer_norm", "batch_norm", "cross_entropy",
    "mean", "sum", "exp", "log", "norm", "cumsum",
}

_tls = threading.local()


class _AmpState:
    __slots__ = ("enabled", "level", "dtype")

    def __init__(self, enabled=False, level="O1", dtype=jnp.bfloat16):
        self.enabled = enabled
        self.level = level
        self.dtype = dtype


def _get() -> _AmpState:
    st = getattr(_tls, "amp", None)
    if st is None:
        st = _AmpState()
        _tls.amp = st
    return st


def push(enabled: bool, level: str, dtype) -> _AmpState:
    prev = _get()
    _tls.amp = _AmpState(enabled, level, dtype)
    return prev


def pop(prev: _AmpState) -> None:
    _tls.amp = prev


def enabled() -> bool:
    return _get().enabled


def amp_dtype():
    return _get().dtype


def level() -> str:
    return _get().level


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def cast_for_op(op_name: str, *xs):
    """Cast inputs per the active policy; returns inputs (possibly cast)."""
    st = _get()
    if not st.enabled:
        return xs if len(xs) > 1 else xs[0]
    if op_name in BLACK_OPS:
        out = tuple(x.astype(jnp.float32) if _is_float(x) else x for x in xs)
    elif op_name in WHITE_OPS or st.level == "O2":
        # O1: cast white-list ops down.  O2: cast everything not black-listed.
        out = tuple(x.astype(st.dtype) if _is_float(x) else x for x in xs)
    else:
        out = xs
    return out if len(out) > 1 else out[0]
