"""Automatic mixed precision (reference: python/paddle/amp/ — auto_cast.py:21
``auto_cast``, :81 ``decorate``; grad_scaler.py:26 ``GradScaler``; on-device
finite check + scale update ops paddle/fluid/operators/amp/
check_finite_and_unscale_op.cc and update_loss_scaling_op.cc).

TPU defaults to bfloat16, where loss scaling is unnecessary — but the full
dynamic-loss-scaling state machine is implemented (and jit-safe) for fp16
parity.  See SURVEY.md A8.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.dtype import convert_dtype
from ..framework.errors import enforce
from . import state as _state
from .state import BLACK_OPS, WHITE_OPS  # noqa: F401

__all__ = ["auto_cast", "decorate", "GradScaler", "amp_guard",
           "is_bfloat16_supported", "is_float16_supported"]


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1",
              dtype: str = "bfloat16"):
    """Context under which white-listed ops run in low precision."""
    added_w = set(custom_white_list or ()) - WHITE_OPS
    added_b = set(custom_black_list or ()) - BLACK_OPS
    WHITE_OPS.update(added_w)
    BLACK_OPS.update(added_b)
    prev = _state.push(enable, level, convert_dtype(dtype))
    try:
        yield
    finally:
        _state.pop(prev)
        WHITE_OPS.difference_update(added_w)
        BLACK_OPS.difference_update(added_b)


amp_guard = auto_cast  # legacy alias (fluid.dygraph.amp.amp_guard)


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight: Optional[bool] = None):
    """O2 decoration: cast model params to the low dtype; optimizers keep fp32
    master weights (multi_precision, on by default)."""
    if level not in ("O1", "O2"):
        raise ValueError("level must be O1 or O2")
    if level == "O2":
        single = not isinstance(models, (list, tuple))
        for m in ([models] if single else models):
            m.astype(convert_dtype(dtype))
    if optimizers is not None:
        opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        for o in opts:
            if master_weight is not False:
                o.multi_precision = True
        return models, optimizers
    return models


class GradScaler:
    """Dynamic loss scaling (reference amp/grad_scaler.py:26).

    Functional API (jit-safe, the TPU path):
        st = scaler.init_state()
        scaled = scaler.scale_value(loss, st)
        grads, found_inf = scaler.unscale_and_check(grads, st)
        new_st = scaler.update_state(st, found_inf)
        # skip the optimizer update where found_inf via jnp.where / lax.cond

    Stateful API (eager parity): scale(), step(), minimize(), update().
    """

    def __init__(self, enable: bool = True, init_loss_scaling: float = 2.0 ** 15,
                 incr_ratio: float = 2.0, decr_ratio: float = 0.5,
                 incr_every_n_steps: int = 1000,
                 decr_every_n_nan_or_inf: int = 2,
                 use_dynamic_loss_scaling: bool = True):
        self._enable = enable
        self.init_loss_scaling = init_loss_scaling
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self.use_dynamic = use_dynamic_loss_scaling
        self._st = self.init_state()
        self._already_unscaled = False

    def is_enable(self) -> bool:
        return self._enable

    # -- functional -------------------------------------------------------
    def init_state(self):
        return {
            "scale": jnp.asarray(self.init_loss_scaling if self._enable else 1.0,
                                 jnp.float32),
            "good": jnp.zeros((), jnp.int32),
            "bad": jnp.zeros((), jnp.int32),
        }

    def scale_value(self, loss, state):
        if not self._enable:
            return loss
        return loss * state["scale"].astype(loss.dtype)

    def unscale_and_check(self, grads, state):
        """check_finite_and_unscale op semantics: unscale all grads, report a
        single found_inf flag (reference operators/amp/
        check_finite_and_unscale_op.cc)."""
        if not self._enable:
            return grads, jnp.zeros((), jnp.bool_)
        inv = 1.0 / state["scale"]
        unscaled = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        finite = jnp.array(True)
        for g in jax.tree_util.tree_leaves(unscaled):
            finite = finite & jnp.all(jnp.isfinite(g.astype(jnp.float32)))
        return unscaled, ~finite

    def update_state(self, state, found_inf):
        """update_loss_scaling op semantics (reference operators/amp/
        update_loss_scaling_op.cc)."""
        if not self._enable or not self.use_dynamic:
            return state
        scale, good, bad = state["scale"], state["good"], state["bad"]
        bad_n = jnp.where(found_inf, bad + 1, 0)
        good_n = jnp.where(found_inf, 0, good + 1)
        decr = bad_n >= self.decr_every_n_nan_or_inf
        incr = good_n >= self.incr_every_n_steps
        new_scale = jnp.where(decr, jnp.maximum(scale * self.decr_ratio, 1.0),
                              jnp.where(incr, scale * self.incr_ratio, scale))
        return {"scale": new_scale,
                "good": jnp.where(incr, 0, good_n),
                "bad": jnp.where(decr, 0, bad_n)}

    # -- stateful (eager) -------------------------------------------------
    def scale(self, value):
        return self.scale_value(value, self._st)

    def step(self, optimizer, grads=None):
        """Unscale, check, conditionally step, update the scale.  If
        ``unscale_(optimizer)`` already ran this iteration (the
        grad-clipping idiom), grads are NOT unscaled a second time —
        the reference tracks the same per-iteration state."""
        if not self._enable:
            optimizer.step(grads)
            return
        if grads is None:
            # paddle-canonical scaler.step(optimizer): pull the grads the
            # user attached to the bound parameters so they get unscaled too
            grads = [p._grad for p in optimizer._parameters]
        if self._already_unscaled:
            found_inf = jnp.asarray(not all(
                bool(jnp.all(jnp.isfinite(g))) for g in grads
                if g is not None))
            unscaled = grads
        else:
            unscaled, found_inf = self.unscale_and_check(grads, self._st)
        if not bool(found_inf):
            optimizer.step(unscaled)
        else:
            optimizer.clear_grad()
        self._st = self.update_state(self._st, found_inf)
        self._already_unscaled = False

    def unscale_(self, optimizer=None):
        """Eager-path unscale of the bound optimizer's param grads
        (reference GradScaler.unscale_, the grad-clip idiom); the
        following step() will not unscale again.  The jit path uses
        unscale_and_check."""
        params = getattr(optimizer, "_parameters", None) or []
        inv = 1.0 / float(self._st["scale"])
        for p in params:
            if getattr(p, "_grad", None) is not None:
                p._grad = p._grad * inv
        self._already_unscaled = True
        return optimizer

    # -- accessor tail (reference amp/grad_scaler.py) ---------------------
    def is_use_dynamic_loss_scaling(self):
        return self.use_dynamic

    def get_init_loss_scaling(self):
        return float(self.init_loss_scaling)

    def set_init_loss_scaling(self, v):
        self.init_loss_scaling = float(v)
        self._st = self.init_state()

    def get_incr_ratio(self):
        return self.incr_ratio

    def set_incr_ratio(self, v):
        enforce(v > 1.0, "incr_ratio must be > 1")
        self.incr_ratio = float(v)

    def get_decr_ratio(self):
        return self.decr_ratio

    def set_decr_ratio(self, v):
        enforce(0.0 < v < 1.0, "decr_ratio must be in (0, 1)")
        self.decr_ratio = float(v)

    def get_incr_every_n_steps(self):
        return self.incr_every_n_steps

    def set_incr_every_n_steps(self, v):
        self.incr_every_n_steps = int(v)

    def get_decr_every_n_nan_or_inf(self):
        return self.decr_every_n_nan_or_inf

    def set_decr_every_n_nan_or_inf(self, v):
        self.decr_every_n_nan_or_inf = int(v)

    def minimize(self, optimizer, scaled_loss=None, grads=None):
        self.step(optimizer, grads)

    def update(self):
        pass  # folded into step()

    def get_loss_scaling(self):
        return float(self._st["scale"])

    def state_dict(self):
        return dict(self._st)

    def load_state_dict(self, sd):
        self._st = dict(sd)


def is_bfloat16_supported(device=None) -> bool:
    """bf16 is the native TPU compute dtype; CPU XLA supports it too."""
    return True


def is_float16_supported(device=None) -> bool:
    """fp16 lowers on every XLA backend this build targets (incl. the
    tunneled TPU platform, which reports a vendor name); bf16 is still
    preferred on TPU — wider exponent, no loss scaling for most models."""
    return True
