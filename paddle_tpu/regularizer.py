"""paddle.regularizer (reference: python/paddle/regularizer.py — L1Decay /
L2Decay, applied by the optimizer as a gradient addition:
L2 adds coeff*param, L1 adds coeff*sign(param))."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    """Lasso: adds ``coeff * sign(param)`` to the gradient."""


class L2Decay(WeightDecayRegularizer):
    """Ridge: adds ``coeff * param`` to the gradient (for decoupled-decay
    optimizers like AdamW the coefficient feeds the decoupled path)."""
