"""Extended tensor-op surface (reference: python/paddle/tensor/{math,
manipulation,search,random,logic}.py — the long tail of the 578-op corpus
beyond the core set in ``paddle_tpu/__init__``).

Everything here is a thin, paddle-shaped adapter over jnp/lax: XLA owns the
kernels (SURVEY C15 → §7 "operator corpus collapses into jnp").  Ops are
grouped as in the reference's tensor/ modules.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .framework import random as fw_random
from .framework.dtype import convert_dtype
from .framework.errors import enforce

__all__ = [
    # top-level gap fill (reference __init__ __all__ parity)
    "add_n", "lgamma", "asinh", "acosh", "atanh", "floor_mod",
    "bitwise_not", "rank", "empty_like", "is_empty", "unstack", "reverse",
    "increment", "slice", "strided_slice", "crop", "shard_index",
    "scatter_nd", "scatter_nd_add", "reshape_", "squeeze_", "unsqueeze_",
    "tanh_", "scatter_",
    # math
    "amax", "amin", "addmm", "angle", "conj", "real", "imag", "deg2rad",
    "rad2deg", "diff", "digamma", "erfinv", "expm1", "gcd", "lcm", "lerp",
    "logit", "logsumexp", "logcumsumexp", "nanmean", "nansum", "nanmedian",
    "stanh", "scale", "trace", "frac", "ldexp", "hypot", "copysign",
    "log1p", "rsqrt_",
    # complex
    "complex", "as_complex", "as_real", "is_complex", "is_floating_point",
    "is_integer",
    # linalg-adjacent (top-level in paddle)
    "cross", "dist", "histogram", "bincount", "inner", "kron", "mv",
    "tensordot", "matrix_transpose",
    # manipulation
    "broadcast_shape", "broadcast_tensors", "diagflat", "diagonal",
    "expand_as", "index_sample", "meshgrid", "moveaxis", "multiplex",
    "put_along_axis", "repeat_interleave", "renorm", "rot90", "unbind",
    "unique_consecutive", "as_strided", "view", "tolist",
    # search / sort
    "kthvalue", "median", "mode", "quantile", "searchsorted", "bucketize",
    "isclose", "index_sample",
    # random
    "multinomial", "poisson", "standard_normal", "randint_like",
    "exponential",
]


def _arr(x):
    return x if isinstance(x, jax.Array) else jnp.asarray(x)


# ---------------------------------------------------------------------------
# math (reference tensor/math.py)
# ---------------------------------------------------------------------------
def amax(x, axis=None, keepdim=False):
    return jnp.amax(_arr(x), axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.amin(_arr(x), axis=axis, keepdims=keepdim)


def addmm(input, x, y, beta: float = 1.0, alpha: float = 1.0):
    return beta * _arr(input) + alpha * (_arr(x) @ _arr(y))


def angle(x):
    return jnp.angle(_arr(x))


def conj(x):
    return jnp.conj(_arr(x))


def real(x):
    return jnp.real(_arr(x))


def imag(x):
    return jnp.imag(_arr(x))


def deg2rad(x):
    return jnp.deg2rad(_arr(x))


def rad2deg(x):
    return jnp.rad2deg(_arr(x))


def diff(x, n: int = 1, axis: int = -1, prepend=None, append=None):
    return jnp.diff(_arr(x), n=n, axis=axis, prepend=prepend, append=append)


def digamma(x):
    return jax.scipy.special.digamma(_arr(x))


def erfinv(x):
    return jax.scipy.special.erfinv(_arr(x))


def expm1(x):
    return jnp.expm1(_arr(x))


def log1p(x):
    return jnp.log1p(_arr(x))


def gcd(x, y):
    return jnp.gcd(_arr(x), _arr(y))


def lcm(x, y):
    return jnp.lcm(_arr(x), _arr(y))


def lerp(x, y, weight):
    x = _arr(x)
    return x + _arr(weight) * (_arr(y) - x)


def logit(x, eps: Optional[float] = None):
    x = _arr(x)
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x) - jnp.log1p(-x)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(_arr(x), axis=axis, keepdims=keepdim)


def logcumsumexp(x, axis=None):
    x = _arr(x)
    if axis is None:
        x, axis = x.reshape(-1), 0
    return lax.cumlogsumexp(x, axis=axis)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(_arr(x), axis=axis, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(_arr(x), axis=axis, dtype=convert_dtype(dtype),
                      keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(_arr(x), axis=axis, keepdims=keepdim)


def stanh(x, scale_a: float = 0.67, scale_b: float = 1.7159):
    return scale_b * jnp.tanh(scale_a * _arr(x))


def scale(x, scale=1.0, bias=0.0, bias_after_scale: bool = True,
          act=None):
    x = _arr(x)
    y = x * scale + bias if bias_after_scale else (x + bias) * scale
    if act is not None:
        from .nn import functional as F
        fn = getattr(F, act, None)
        enforce(fn is not None, f"scale: unknown activation {act!r}")
        y = fn(y)
    return y


def trace(x, offset: int = 0, axis1: int = 0, axis2: int = 1):
    return jnp.trace(_arr(x), offset=offset, axis1=axis1, axis2=axis2)


def frac(x):
    x = _arr(x)
    return x - jnp.trunc(x)


def ldexp(x, y):
    return jnp.ldexp(_arr(x), _arr(y))


def hypot(x, y):
    return jnp.hypot(_arr(x), _arr(y))


def copysign(x, y):
    return jnp.copysign(_arr(x), _arr(y))


def rsqrt_(x):  # paddle keeps an inplace alias; arrays are immutable here
    return lax.rsqrt(_arr(x))


# ---------------------------------------------------------------------------
# complex (reference tensor/creation.py complex; attribute.py real/imag)
# ---------------------------------------------------------------------------
def complex(real, imag):  # noqa: A001
    return lax.complex(_arr(real), _arr(imag))


def as_complex(x):
    x = _arr(x)
    enforce(x.shape[-1] == 2, "as_complex expects trailing dim 2")
    return lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    x = _arr(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def is_complex(x) -> bool:
    return jnp.issubdtype(_arr(x).dtype, jnp.complexfloating)


def is_floating_point(x) -> bool:
    return jnp.issubdtype(_arr(x).dtype, jnp.floating)


def is_integer(x) -> bool:
    return jnp.issubdtype(_arr(x).dtype, jnp.integer)


# ---------------------------------------------------------------------------
# linalg-adjacent top-level ops (reference tensor/linalg.py)
# ---------------------------------------------------------------------------
def cross(x, y, axis: int = 9):
    x, y = _arr(x), _arr(y)
    if axis == 9:  # paddle default: first axis of size 3
        axis = next((i for i, d in enumerate(x.shape) if d == 3), None)
        enforce(axis is not None,
                "cross: no dimension of size 3 found; pass axis explicitly")
    return jnp.cross(x, y, axis=axis)


def dist(x, y, p: float = 2.0):
    d = (_arr(x) - _arr(y)).reshape(-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(d.dtype)
    return jnp.linalg.norm(d, ord=p)


def histogram(x, bins: int = 100, min: float = 0.0, max: float = 0.0):
    x = _arr(x).reshape(-1)
    if min == 0.0 and max == 0.0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = jnp.asarray(min, x.dtype), jnp.asarray(max, x.dtype)
    counts, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return counts


def bincount(x, weights=None, minlength: int = 0):
    return jnp.bincount(_arr(x), weights=weights, minlength=minlength,
                        length=None)


def inner(x, y):
    return jnp.inner(_arr(x), _arr(y))


def kron(x, y):
    return jnp.kron(_arr(x), _arr(y))


def mv(x, vec):
    return _arr(x) @ _arr(vec)


def tensordot(x, y, axes=2):
    return jnp.tensordot(_arr(x), _arr(y), axes=axes)


def matrix_transpose(x):
    return jnp.swapaxes(_arr(x), -1, -2)


# ---------------------------------------------------------------------------
# manipulation (reference tensor/manipulation.py)
# ---------------------------------------------------------------------------
def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(inputs):
    return list(jnp.broadcast_arrays(*[_arr(i) for i in inputs]))


def diagflat(x, offset: int = 0):
    return jnp.diagflat(_arr(x), k=offset)


def diagonal(x, offset: int = 0, axis1: int = 0, axis2: int = 1):
    return jnp.diagonal(_arr(x), offset=offset, axis1=axis1, axis2=axis2)


def expand_as(x, y):
    return jnp.broadcast_to(_arr(x), _arr(y).shape)


def index_sample(x, index):
    """Per-row gather: out[i, j] = x[i, index[i, j]] (index_sample_op)."""
    return jnp.take_along_axis(_arr(x), _arr(index), axis=1)


def meshgrid(*args):
    xs = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) \
        else args
    return list(jnp.meshgrid(*[_arr(x) for x in xs], indexing="ij"))


def moveaxis(x, source, destination):
    return jnp.moveaxis(_arr(x), source, destination)


def multiplex(inputs, index):
    """out[i] = inputs[index[i]][i] (multiplex_op semantics)."""
    stacked = jnp.stack([_arr(i) for i in inputs], axis=0)   # (K, N, ...)
    idx = _arr(index).reshape(-1).astype(jnp.int32)          # (N,)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def put_along_axis(arr, indices, values, axis: int, reduce: str = "assign"):
    arr, indices = _arr(arr), _arr(indices)
    values = jnp.broadcast_to(_arr(values), indices.shape).astype(arr.dtype)
    dnums = jnp.indices(indices.shape, sparse=True)
    full_idx = tuple(indices if i == axis else d
                     for i, d in enumerate(dnums))
    if reduce == "assign":
        return arr.at[full_idx].set(values)
    if reduce == "add":
        return arr.at[full_idx].add(values)
    if reduce == "multiply" or reduce == "mul":
        return arr.at[full_idx].multiply(values)
    raise ValueError(f"unsupported reduce {reduce!r}")


def repeat_interleave(x, repeats, axis: Optional[int] = None):
    x = _arr(x)
    if axis is None:
        x, axis = x.reshape(-1), 0
    return jnp.repeat(x, repeats, axis=axis)


def renorm(x, p: float, axis: int, max_norm: float):
    """Clamp the p-norm of every slice along ``axis`` to max_norm."""
    x = _arr(x)
    axes = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


def rot90(x, k: int = 1, axes=(0, 1)):
    return jnp.rot90(_arr(x), k=k, axes=tuple(axes))


def unbind(x, axis: int = 0):
    x = _arr(x)
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, x.shape[axis], axis=axis)]


def unique_consecutive(x, return_inverse: bool = False,
                       return_counts: bool = False, axis=None):
    """Deduplicate consecutive runs (host-side sizes: not jittable, same as
    the reference's dynamic-shape op)."""
    import numpy as np
    xn = np.asarray(_arr(x))
    if axis is None:
        xn = xn.reshape(-1)
    keep = np.ones(xn.shape[0], bool)
    keep[1:] = np.any(
        xn[1:].reshape(xn.shape[0] - 1, -1)
        != xn[:-1].reshape(xn.shape[0] - 1, -1), axis=1) \
        if xn.ndim > 1 else xn[1:] != xn[:-1]
    out = jnp.asarray(xn[keep])
    rets = [out]
    if return_inverse:
        rets.append(jnp.asarray(np.cumsum(keep) - 1))
    if return_counts:
        idx = np.flatnonzero(keep)
        rets.append(jnp.asarray(np.diff(np.append(idx, xn.shape[0]))))
    return rets[0] if len(rets) == 1 else tuple(rets)


def as_strided(x, shape, stride, offset: int = 0):
    """View with explicit strides (reference as_strided): gather-based,
    works under jit for static shapes/strides."""
    x = _arr(x).reshape(-1)
    idx = jnp.asarray(offset)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
    for g, st in zip(grids, stride):
        idx = idx + g * st
    return x[idx]


def view(x, shape_or_dtype):
    x = _arr(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return x.reshape(tuple(shape_or_dtype))
    return x.view(convert_dtype(shape_or_dtype))


def tolist(x):
    return _arr(x).tolist()


# ---------------------------------------------------------------------------
# search / sort (reference tensor/search.py, stat.py)
# ---------------------------------------------------------------------------
def kthvalue(x, k: int, axis: int = -1, keepdim: bool = False):
    x = _arr(x)
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    val = jnp.take(vals, k - 1, axis=axis)
    idx = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        idx = jnp.expand_dims(idx, axis)
    return val, idx


def median(x, axis=None, keepdim: bool = False):
    return jnp.median(_arr(x), axis=axis, keepdims=keepdim)


def mode(x, axis: int = -1, keepdim: bool = False):
    """Most frequent value along axis; ties resolve to the largest value
    (sort-based, static shapes — mode_op semantics)."""
    x = _arr(x)
    sx = jnp.sort(x, axis=axis)
    n = x.shape[axis]
    sx_m = jnp.moveaxis(sx, axis, -1)
    eq = sx_m[..., 1:] == sx_m[..., :-1]
    # run length ending at each position
    def scan_fn(carry, e):
        run = jnp.where(e, carry + 1, jnp.ones_like(carry))
        return run, run
    init = jnp.ones(sx_m.shape[:-1], jnp.int32)
    _, runs = lax.scan(scan_fn, init, jnp.moveaxis(eq, -1, 0))
    runs = jnp.concatenate([init[None], runs], axis=0)   # (n, ...)
    runs = jnp.moveaxis(runs, 0, -1)
    # exact integer tie-break: longest run, then last (=largest) value
    best = jnp.argmax(runs * n + jnp.arange(n), axis=-1)
    val = jnp.take_along_axis(sx_m, best[..., None], axis=-1)[..., 0]
    idx_m = jnp.argmax(jnp.moveaxis(x, axis, -1) == val[..., None], axis=-1)
    if keepdim:
        val = jnp.expand_dims(val, axis)
        idx_m = jnp.expand_dims(idx_m, axis)
    return val, idx_m


def quantile(x, q, axis=None, keepdim: bool = False):
    return jnp.quantile(_arr(x), jnp.asarray(q), axis=axis,
                        keepdims=keepdim)


def searchsorted(sorted_sequence, values, out_int32: bool = False,
                 right: bool = False):
    out = jnp.searchsorted(_arr(sorted_sequence), _arr(values),
                           side="right" if right else "left")
    return out.astype(jnp.int32) if out_int32 else out.astype(jnp.int64)


def bucketize(x, sorted_sequence, out_int32: bool = False,
              right: bool = False):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def isclose(x, y, rtol: float = 1e-5, atol: float = 1e-8,
            equal_nan: bool = False):
    return jnp.isclose(_arr(x), _arr(y), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


# ---------------------------------------------------------------------------
# random (reference tensor/random.py) — global-stream keys, eager-mode API
# ---------------------------------------------------------------------------
def multinomial(x, num_samples: int = 1, replacement: bool = False):
    x = _arr(x)
    key = fw_random.next_key()
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        # categorical's shape prepends to the batch dims: draw
        # (num_samples, *batch) then move samples last — (batch, n) out
        batch = x.shape[:-1]
        draws = jax.random.categorical(key, logits, axis=-1,
                                       shape=(num_samples, *batch))
        return jnp.moveaxis(draws, 0, -1).astype(jnp.int64) if batch \
            else draws.astype(jnp.int64)
    enforce(num_samples <= x.shape[-1],
            "cannot draw more samples than categories without replacement")
    # Gumbel top-k trick: without-replacement sampling
    g = jax.random.gumbel(key, x.shape)
    _, idx = lax.top_k(logits + g, num_samples)
    return idx.astype(jnp.int64)


def poisson(x):
    return jax.random.poisson(fw_random.next_key(), _arr(x)).astype(
        _arr(x).dtype)


def standard_normal(shape, dtype="float32"):
    return jax.random.normal(fw_random.next_key(), tuple(shape),
                             convert_dtype(dtype))


def randint_like(x, low, high=None, dtype=None):
    x = _arr(x)
    if high is None:
        low, high = 0, low
    out_dtype = convert_dtype(dtype) if dtype else x.dtype  # paddle: match x
    draw_dtype = out_dtype if jnp.issubdtype(out_dtype, jnp.integer) \
        else jnp.int32
    out = jax.random.randint(fw_random.next_key(), x.shape, low, high,
                             draw_dtype)
    return out.astype(out_dtype)


def exponential(x, lam: float = 1.0):
    """Exponential-distribution samples shaped like x (exponential_ op)."""
    u = jax.random.uniform(fw_random.next_key(), _arr(x).shape,
                           _arr(x).dtype, minval=1e-9, maxval=1.0)
    return -jnp.log(u) / lam


# ---------------------------------------------------------------------------
# top-level gap fill (reference python/paddle/__init__.py __all__ parity):
# manipulation/search ops + the documented-in-place aliases
# ---------------------------------------------------------------------------
def add_n(inputs):
    """Elementwise sum of a tensor list (reference tensor/math.py add_n)."""
    if not isinstance(inputs, (list, tuple)):
        return _arr(inputs)
    out = _arr(inputs[0])
    for x in inputs[1:]:
        out = out + _arr(x)
    return out


def lgamma(x):
    return jax.scipy.special.gammaln(_arr(x))


def asinh(x):
    return jnp.arcsinh(_arr(x))


def acosh(x):
    return jnp.arccosh(_arr(x))


def atanh(x):
    return jnp.arctanh(_arr(x))


def floor_mod(x, y):
    return jnp.mod(_arr(x), _arr(y))


def bitwise_not(x):
    return jnp.bitwise_not(_arr(x))


def rank(x):
    """Number of dimensions as a 0-d int32 tensor (reference rank op)."""
    return jnp.asarray(_arr(x).ndim, jnp.int32)


def empty_like(x, dtype=None):
    x = _arr(x)
    return jnp.empty(x.shape, convert_dtype(dtype) if dtype else x.dtype)


def is_empty(x):
    """Whether the tensor holds zero elements (0-d bool; logic.py:229)."""
    return jnp.asarray(_arr(x).size == 0)


def unstack(x, axis=0, num=None):
    """Split along ``axis`` into that dim's many tensors, squeezing it."""
    x = _arr(x)
    n = x.shape[axis] if num is None else num
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, n, axis=axis)]


def reverse(x, axis):
    """Flip along the given axes (reference fluid reverse op)."""
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(_arr(x), axis=tuple(axis))


def increment(x, value=1.0):
    """x + value for a single-element tensor (control-flow counter idiom,
    reference tensor/math.py:3324; jax arrays are immutable so the
    incremented tensor is returned)."""
    x = _arr(x)
    enforce(x.size == 1, "increment requires a single-element tensor")
    return x + jnp.asarray(value, x.dtype)


def slice(input, axes, starts, ends):  # noqa: A001
    """Static slice over the given axes (reference slice op semantics:
    negative indices wrap, ends clamp to the dim size)."""
    import builtins
    x = _arr(input)
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        d = x.shape[ax]
        st = int(st); en = int(en)
        if st < 0:
            st += d
        if en < 0:
            en += d
        # reference clamps to [0, d]: out-of-range ends never re-wrap
        st = builtins.max(builtins.min(st, d), 0)
        en = builtins.max(builtins.min(en, d), 0)
        idx[ax] = builtins.slice(st, en)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    """slice() with per-axis strides (reference strided_slice op)."""
    import builtins
    x = _arr(x)
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        d = x.shape[ax]
        st = int(st); en = int(en); sd = int(sd)
        if st < 0:
            st += d
        if en < 0:
            en += d
        if sd > 0:
            st = builtins.max(builtins.min(st, d), 0)
            en = builtins.max(builtins.min(en, d), 0)
            idx[ax] = builtins.slice(st, en, sd)
        else:
            # negative stride: a still-negative end after one wrap means
            # "run past index 0" (python slice would re-wrap it) — None
            st = builtins.min(st, d - 1)
            idx[ax] = builtins.slice(st, None if en < 0 else en, sd)
    return x[tuple(idx)]


def crop(x, shape=None, offsets=None):
    """Crop to ``shape`` starting at ``offsets`` (reference crop op;
    -1 in shape keeps the rest of that dim)."""
    import builtins
    x = _arr(x)
    if shape is None:
        shape = x.shape
    if offsets is None:
        offsets = [0] * x.ndim
    idx = []
    for d, off, s in zip(x.shape, offsets, shape):
        off = int(off)
        end = d if int(s) == -1 else off + int(s)
        idx.append(builtins.slice(off, end))
    return x[tuple(idx)]


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Re-base class indices onto one shard of [0, index_num)
    (reference fluid/layers/nn.py:15231; the vocab-parallel label
    transform).  Values outside this shard's range become
    ``ignore_value``."""
    enforce(0 <= shard_id < nshards,
            f"shard_id {shard_id} out of range [0, {nshards})")
    x = _arr(input)
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    inside = (x >= lo) & (x < hi)
    return jnp.where(inside, x - lo, jnp.asarray(ignore_value, x.dtype))


def scatter_nd_add(x, index, updates):
    """x with ``updates`` scatter-added at ``index`` (reference
    scatter_nd_add op; duplicate indices accumulate)."""
    x, index, updates = _arr(x), _arr(index), _arr(updates)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape):
    """Zeros of ``shape`` with updates scatter-added (reference
    scatter_nd: scatter_nd_add onto a zero tensor)."""
    updates = _arr(updates)
    return scatter_nd_add(jnp.zeros(tuple(shape), updates.dtype), index,
                          updates)


# Reference in-place variants (tensor/manipulation.py reshape_ etc.).
# jax arrays are immutable: these return the result like their non-inplace
# counterparts — the paddle convention `y = x.reshape_(...)` still works,
# assignment-free mutation of `x` does not (documented in MIGRATION.md).
def reshape_(x, shape):
    return jnp.reshape(_arr(x), tuple(shape))


def squeeze_(x, axis=None):
    from . import squeeze as _squeeze
    return _squeeze(x, axis)


def unsqueeze_(x, axis):
    from . import unsqueeze as _unsqueeze
    return _unsqueeze(x, axis)


def tanh_(x):
    return jnp.tanh(_arr(x))


def scatter_(x, index, updates, overwrite=True):
    from . import scatter as _scatter
    return _scatter(x, index, updates, overwrite)
