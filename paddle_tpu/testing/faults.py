"""Fault-injection harness for the resilience layer (ISSUE 1).

Every durable checkpoint byte in this codebase flows through ONE seam —
``paddle_tpu.utils.fsio.write_bytes`` (shards, manifests, pickles, the
elastic COMMITTED marker).  :class:`FaultInjector` monkeypatches that
seam inside a ``with`` block and injects faults on selected writes:

>>> with FaultInjector() as fi:
...     fi.fail_writes(first=1, times=3)      # 3 transient OSErrors
...     save_sharded(state, path)             # retry absorbs them
>>> fi.write_count                            # observed attempts
6

Injectable faults: raise a (transient) ``OSError`` on the Nth write,
truncate the Nth write, flip a byte of the Nth write, deliver SIGTERM to
this process right after the Nth write completes (preemption mid-save).
Writes are numbered 1-based across the whole ``with`` block; each retry
attempt counts as a fresh write, which is exactly what lets a test prove
"3 consecutive transient errors then success".

Offline corruption helpers (:func:`flip_byte`, :func:`truncate_file`,
:func:`corrupt_shard`, :func:`corrupt_manifest`) damage an
already-committed checkpoint on disk — the "flipped bit in cold storage"
scenario that the checksum verification + restore fallback chain must
catch.  They bypass the seam on purpose (corruption is not a write).

:func:`fast_retries` swaps every module-level retry policy for a
sleepless one so fault tests measure behavior, not backoff time.

Serving-side injectors (ISSUE 15): :class:`poison_request` plugs into
``ServingEngine(step_fault=...)`` to poison one request's step
(raise / NaN logits / hang) so the quarantine, NaN-guard and watchdog
paths are drillable without real hardware faults; :class:`expire_clock`
is a hand-advanced clock for deadline-eviction tests.

Fleet injectors (ISSUE 16): :class:`kill_replica` SIGKILLs one fleet
worker subprocess — optionally gated on a ``when()`` predicate the
drill polls, so "kill replica 0 once stream X has 3 accepted tokens"
is deterministic; :class:`drop_dispatch` plugs into
``Router.dispatch_fault`` and fails the first N dispatch attempts
with ``ConnectionError``, driving the retry-with-backoff and
exhaustion paths without a real network; :class:`flaky_replica`
(ISSUE 17) makes a *live* replica's transport intermittently fail /
stall — the injector the circuit-breaker and retry-budget drills
need: the replica stays alive and healthy by census, but a seeded
fraction of its calls raise ``ConnectionError``.
"""
from __future__ import annotations

import contextlib
import glob
import os
import random
import signal as _signal
import time
from typing import Callable, List, Optional, Tuple

from ..utils import fsio
from ..utils.retry import RetryPolicy

__all__ = ["FaultInjector", "flip_byte", "truncate_file", "corrupt_shard",
           "corrupt_manifest", "fast_retries", "hang", "slow_call",
           "diverge_after", "sigkill_self", "sigkill_at", "bitflip",
           "flip_tree_bit", "poison_request", "expire_clock",
           "kill_replica", "drop_dispatch", "flaky_replica"]


def _default_transient() -> OSError:
    return OSError("injected transient I/O error")


class FaultInjector:
    """Context manager that intercepts ``fsio.write_bytes`` and injects
    configured faults; all writes it does not target pass through to the
    real (fsync'd) implementation."""

    def __init__(self):
        self.write_count = 0
        self.injected: List[Tuple[int, str, str]] = []  # (n, kind, path)
        self._rules: List[tuple] = []
        self._orig: Optional[Callable] = None

    # -- rule builders (chainable) ----------------------------------------
    def fail_writes(self, first: int, times: int = 1,
                    exc_factory: Callable[[], BaseException] =
                    _default_transient) -> "FaultInjector":
        """Raise ``exc_factory()`` on writes ``first .. first+times-1``."""
        self._rules.append(("fail", first, times, exc_factory))
        return self

    def truncate_write(self, nth: int, keep_bytes: int = 8
                       ) -> "FaultInjector":
        """Write only the first ``keep_bytes`` of the Nth write (torn
        write: the file exists but is short)."""
        self._rules.append(("truncate", nth, keep_bytes))
        return self

    def flip_byte_on_write(self, nth: int, offset: int = -1
                           ) -> "FaultInjector":
        """Flip one byte of the Nth write's payload (silent bit rot at
        write time; size stays right, CRC must catch it)."""
        self._rules.append(("flip", nth, offset))
        return self

    def sigterm_on_write(self, nth: int) -> "FaultInjector":
        """Deliver SIGTERM to this process right after the Nth write
        lands (preemption notice arriving mid-save)."""
        self._rules.append(("sigterm", nth))
        return self

    def hang_on_write(self, nth: int, seconds: float) -> "FaultInjector":
        """Stall the Nth write for ``seconds`` (a wedged NFS server) —
        interruptibly, so a supervisor watchdog's ``StepTimeout`` can cut
        it short (ISSUE 2)."""
        self._rules.append(("hang", nth, seconds))
        return self

    # -- interception ------------------------------------------------------
    def __enter__(self) -> "FaultInjector":
        self._orig = fsio.write_bytes
        fsio.write_bytes = self._intercept
        return self

    def __exit__(self, *exc) -> None:
        fsio.write_bytes = self._orig
        self._orig = None

    def _intercept(self, path: str, payload: bytes) -> None:
        self.write_count += 1
        n = self.write_count
        for rule in self._rules:
            kind = rule[0]
            if kind == "fail" and rule[1] <= n < rule[1] + rule[2]:
                self.injected.append((n, kind, path))
                raise rule[3]()
            if kind == "truncate" and n == rule[1]:
                self.injected.append((n, kind, path))
                return self._orig(path, payload[: rule[2]])
            if kind == "flip" and n == rule[1]:
                self.injected.append((n, kind, path))
                mutated = bytearray(payload)
                mutated[rule[2]] ^= 0xFF
                return self._orig(path, bytes(mutated))
            if kind == "sigterm" and n == rule[1]:
                self.injected.append((n, kind, path))
                self._orig(path, payload)
                os.kill(os.getpid(), _signal.SIGTERM)
                return None
            if kind == "hang" and n == rule[1]:
                self.injected.append((n, kind, path))
                hang(rule[2])
                return self._orig(path, payload)
        return self._orig(path, payload)


# -- offline corruption (damage committed bytes on disk) -------------------
def flip_byte(path: str, offset: Optional[int] = None) -> None:
    """XOR one byte of ``path`` in place (default: the middle byte, which
    for .npy files lands in array data, not the header)."""
    with open(path, "r+b") as f:  # noqa: fsio — deliberate corruption, bypasses the seam on purpose
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            raise ValueError(f"{path} is empty, nothing to flip")
        pos = size // 2 if offset is None else offset % size
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))


def truncate_file(path: str, keep_bytes: int = 8) -> None:
    with open(path, "r+b") as f:  # noqa: fsio — deliberate corruption, bypasses the seam on purpose
        f.truncate(keep_bytes)


def corrupt_shard(ckpt_dir: str, index: int = 0,
                  offset: Optional[int] = None) -> str:
    """Flip a byte in the ``index``-th shard file (sorted order) of a
    saved checkpoint; returns the damaged file's path."""
    shards = sorted(glob.glob(os.path.join(ckpt_dir, "*", "shard-*.npy")))
    if not shards:
        raise FileNotFoundError(f"no shard files under {ckpt_dir}")
    flip_byte(shards[index], offset)
    return shards[index]


def corrupt_manifest(ckpt_dir: str, keep_bytes: int = 16) -> str:
    """Truncate the checkpoint's manifest (torn manifest write on a
    pre-atomic-commit writer); returns the damaged file's path."""
    names = (sorted(glob.glob(os.path.join(ckpt_dir, "manifest-p*.json")))
             or [os.path.join(ckpt_dir, "manifest.json")])
    truncate_file(names[0], keep_bytes)
    return names[0]


# -- run-level fault injectors (ISSUE 2: supervisor drills) ----------------
def hang(seconds: float, interval: float = 0.01) -> None:
    """Block for ``seconds`` in short interruptible slices — a simulated
    hung collective/step.  Unlike one long ``time.sleep`` this yields a
    bytecode boundary every ``interval``, so the watchdog's async
    ``StepTimeout`` lands promptly instead of after the full hang."""
    import time as _time

    deadline = _time.monotonic() + float(seconds)
    while _time.monotonic() < deadline:
        _time.sleep(interval)


def slow_call(fn: Callable, seconds: float) -> Callable:
    """Wrap ``fn`` to stall (interruptibly) for ``seconds`` before every
    call — slow-but-alive, the case a watchdog must NOT fire on when the
    deadline is generous enough."""
    import functools

    @functools.wraps(fn)
    def slowed(*args, **kwargs):
        hang(seconds)
        return fn(*args, **kwargs)
    return slowed


class diverge_after:
    """Loss injector for the divergence-guard path: identity until
    ``step``, then poisons every observed loss — ``mode="spike"`` grows
    it by ``factor`` each step (finite blow-up), ``mode="nan"`` /
    ``mode="inf"`` go non-finite at once.  Plugs into
    ``RunSupervisor.inject_loss`` (called as ``fn(step, loss)``); also
    works standalone against ``DivergenceGuard.observe``.  ``triggered``
    counts poisoned steps; ``count`` bounds them (``None`` = keep
    diverging forever — the genuinely-broken-run drill), so a transient
    spike that a rollback recovers from is ``count=K``."""

    def __init__(self, step: int, mode: str = "spike",
                 factor: float = 100.0, count: Optional[int] = None):
        if mode not in ("spike", "nan", "inf"):
            raise ValueError(f"unknown divergence mode {mode!r}")
        self.step = int(step)
        self.mode = mode
        self.factor = float(factor)
        self.count = count
        self.triggered = 0

    def __call__(self, step: int, loss: float) -> float:
        if step < self.step or (self.count is not None
                                and self.triggered >= self.count):
            return loss
        self.triggered += 1
        if self.mode == "nan":
            return float("nan")
        if self.mode == "inf":
            return float("inf")
        return (abs(loss) + 1.0) * self.factor ** self.triggered


def sigkill_self() -> None:
    """SIGKILL this process — the unmaskable preemption.  Unlike the
    SIGTERM the fault injector delivers, there is no grace window and no
    final checkpoint flush: the elastic fleet drill (ISSUE 9) uses this
    to prove that losing a worker *between* checkpoints costs one
    interval, not the run."""
    os.kill(os.getpid(), _signal.SIGKILL)


class sigkill_at:
    """Step-triggered SIGKILL for elastic fault drills: call per step
    (``fault(step)``); fires :func:`sigkill_self` once when ``step >=
    trigger`` AND ``generation == gen`` (``None`` = any generation) —
    gating on the first generation keeps a respawned worker from killing
    itself again.

    Env-driven form for worker scripts:
    ``sigkill_at.from_env(rank, generation)`` reads
    ``PTPU_TEST_SIGKILL_STEP`` / ``PTPU_TEST_SIGKILL_RANK`` and returns
    a no-op when this worker is not the target."""

    def __init__(self, step: int, generation: Optional[int] = 0):
        self.step = int(step)
        self.generation = generation

    def __call__(self, step: int, generation: Optional[int] = None
                 ) -> None:
        if step < self.step:
            return
        if (self.generation is not None and generation is not None
                and int(generation) != self.generation):
            return
        sigkill_self()

    @staticmethod
    def from_env(rank: int) -> Callable[..., None]:
        target_step = os.environ.get("PTPU_TEST_SIGKILL_STEP")
        target_rank = int(os.environ.get("PTPU_TEST_SIGKILL_RANK", "-1"))
        if target_step is None or int(rank) != target_rank:
            return lambda *_a, **_k: None
        return sigkill_at(int(target_step))


# -- silent data corruption (ISSUE 11: integrity drills) -------------------
def flip_tree_bit(tree, leaf: str, bit: int = 0, index: int = 0):
    """XOR one bit of one element of one named leaf of a live state tree
    — the in-memory SDC that CRCs on disk can never see.  ``leaf`` is
    the "/"-joined path name (checkpoint convention); ``bit`` indexes
    into the leaf's raw bytes (0 = LSB of byte 0), ``index`` offsets by
    whole elements first.  Returns a NEW tree (jax arrays are
    immutable); every other leaf is the same reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..distributed.checkpoint import _flatten

    names = [n for n, _x in _flatten(tree)]
    if leaf not in names:
        raise KeyError(f"no leaf {leaf!r} (have {sorted(names)[:8]}...)")

    def _flip(path, x):
        parts = [str(getattr(k, "key", getattr(k, "idx", k)))
                 for k in path]
        if "/".join(parts) != leaf:
            return x
        arr = np.asarray(x).copy()
        raw = arr.reshape(-1).view(np.uint8)
        pos = index * arr.dtype.itemsize + bit // 8
        raw[pos % raw.size] ^= np.uint8(1 << (bit % 8))
        out = arr if isinstance(x, np.ndarray) else jnp.asarray(arr)
        return out

    return jax.tree_util.tree_map_with_path(_flip, tree)


class bitflip:
    """Step-triggered single-bit corruptor for integrity drills: call
    per step with the live state (``state = fault(step, state)``); at
    ``step >= trigger`` on the targeted ``worker`` it flips ``bit`` of
    ``leaf`` exactly once and stays quiet forever after — one cosmic
    ray, not a radiation storm.  ``fired`` records the step it struck.

    The flip happens OUTSIDE the computed path (between steps), which is
    precisely the signature the replay audit classifies as
    ``sdc_suspect``: replays from the stashed pre-state agree with each
    other but not with the live digest."""

    def __init__(self, leaf: str, bit: int = 0, step: int = 1,
                 worker: Optional[int] = None, index: int = 0):
        self.leaf = leaf
        self.bit = int(bit)
        self.step = int(step)
        self.worker = worker
        self.index = int(index)
        self.fired: Optional[int] = None

    def __call__(self, step: int, tree, worker: Optional[int] = None):
        if self.fired is not None or step < self.step:
            return tree
        if (self.worker is not None and worker is not None
                and int(worker) != self.worker):
            return tree
        self.fired = int(step)
        return flip_tree_bit(tree, self.leaf, self.bit, self.index)

    @staticmethod
    def from_env(rank: int) -> Optional["bitflip"]:
        """Env-driven form for worker scripts: reads
        ``PTPU_TEST_BITFLIP_STEP`` / ``_RANK`` / ``_LEAF`` / ``_BIT``;
        None when this worker is not the target."""
        step = os.environ.get("PTPU_TEST_BITFLIP_STEP")
        target = int(os.environ.get("PTPU_TEST_BITFLIP_RANK", "-1"))
        if step is None or int(rank) != target:
            return None
        return bitflip(os.environ["PTPU_TEST_BITFLIP_LEAF"],
                       bit=int(os.environ.get("PTPU_TEST_BITFLIP_BIT", "0")),
                       step=int(step), worker=target)


# -- serving-resilience injectors (ISSUE 15: quarantine/deadline drills) ---
class poison_request:
    """Step-fault injector for the ServingEngine quarantine drill: plug
    into ``ServingEngine(step_fault=...)``; the engine calls it as
    ``fault(engine, kind, request_ids, logits)`` on every executed step
    — bisection probes included.

    ``target`` is a request id (str) or a submit-order index (int,
    resolved lazily against ``engine._submit_order``).  Modes:

    - ``"raise"`` — raise a RuntimeError whenever the target is in the
      batch (the allocator-error / kernel-crash shape; re-fires on every
      probe subset containing the target, which is what lets the
      engine's bisection converge on it);
    - ``"nan"`` — overwrite the target's logits row with NaN (the
      silent-corruption shape ``PTPU_SERVE_NAN_GUARD`` must catch);
    - ``"hang"`` — stall interruptibly for ``seconds`` (watchdog drill);
      fires at most ``count`` times (default 1) since the target stays
      in the batch after hang recovery.

    ``fired`` counts activations.  The injector goes quiet on its own
    once the target is quarantined — it is simply no longer in the
    batch."""

    def __init__(self, target, mode: str = "raise",
                 seconds: float = 1.0, count: Optional[int] = None,
                 kinds: Tuple[str, ...] = ("prefill", "decode")):
        if mode not in ("raise", "nan", "hang"):
            raise ValueError(f"unknown poison mode {mode!r}")
        self.target = target
        self.mode = mode
        self.seconds = float(seconds)
        self.count = (1 if mode == "hang" else None) \
            if count is None else int(count)
        self.kinds = tuple(kinds)   # restrict to decode to drill bisection
        self.fired = 0

    def _target_id(self, engine) -> Optional[str]:
        if isinstance(self.target, str):
            return self.target
        order = engine._submit_order
        idx = int(self.target)
        return order[idx] if 0 <= idx < len(order) else None

    def __call__(self, engine, kind: str, request_ids, logits):
        if kind not in self.kinds:
            return None
        rid = self._target_id(engine)
        if rid is None or rid not in request_ids:
            return None
        if self.count is not None and self.fired >= self.count:
            return None
        self.fired += 1
        if self.mode == "raise":
            raise RuntimeError(f"injected poisoned step ({rid})")
        if self.mode == "hang":
            hang(self.seconds)
            return None
        import numpy as np
        out = np.array(logits, copy=True)
        out[request_ids.index(rid)] = np.nan
        return out


class expire_clock:
    """Controllable clock for deadline drills: pass as
    ``ServingEngine(clock=...)``, then ``advance(secs)`` to expire
    deadlines without real waiting.  Starts at ``start`` (default 1000.0
    — any fixed epoch; deadline math is all relative)."""

    def __init__(self, start: float = 1000.0):
        self.now = float(start)

    def advance(self, seconds: float) -> float:
        self.now += float(seconds)
        return self.now

    def __call__(self) -> float:
        return self.now


# ---------------------------------------------------------------------------
# fleet injectors (ISSUE 16)
# ---------------------------------------------------------------------------
class kill_replica:
    """SIGKILL one fleet worker subprocess, deterministically.

    ``target`` is anything with a live process: a ``ReplicaManager``
    plus ``index``, an ``HttpReplica`` (its ``.process``), a
    ``subprocess.Popen``, or a bare pid.  With ``when`` (a no-arg
    predicate) the drill polls ``maybe()`` in its pump loop and the
    kill fires exactly once, the first time the predicate holds —
    e.g. ``when=lambda: len(journal.tokens) >= 3`` pins "die
    mid-stream after 3 accepted tokens".  Calling the injector
    directly fires unconditionally.

    >>> k = kill_replica(manager, index=0,
    ...                  when=lambda: len(j.tokens) >= 3)
    >>> while not router.journals_done():
    ...     router.pump(); k.maybe()
    >>> k.fired
    1
    """

    def __init__(self, target, index: Optional[int] = None,
                 sig: int = _signal.SIGKILL,
                 when: Optional[Callable[[], bool]] = None):
        self.target = target
        self.index = index
        self.sig = sig
        self.when = when
        self.fired = 0

    def _pid(self) -> int:
        t = self.target
        if isinstance(t, int):
            return t
        if self.index is not None and hasattr(t, "replicas"):
            t = t.replicas[self.index]          # ReplicaManager slot
        proc = getattr(t, "process", t)          # HttpReplica -> Popen
        return int(proc.pid)

    def __call__(self) -> int:
        """Fire now; returns the killed pid."""
        pid = self._pid()
        os.kill(pid, self.sig)
        t = self.target
        if self.index is not None and hasattr(t, "replicas"):
            t.replicas[self.index].process.wait(timeout=10)
            t.poll_states()
        self.fired += 1
        return pid

    def maybe(self) -> bool:
        """Fire once when ``when()`` first holds; True if it fired."""
        if self.fired or (self.when is not None and not self.when()):
            return False
        self()
        return True


class drop_dispatch:
    """Router-visible network fault: assigned to
    ``Router.dispatch_fault``, it raises ``ConnectionError`` for the
    first ``count`` dispatch attempts (optionally only toward
    ``replica_id``), then passes everything — the deterministic way to
    drill retry-with-backoff and ``DispatchExhausted``.

    >>> router.dispatch_fault = drop_dispatch(count=2)
    >>> router.submit(...)      # two retries burned, third attempt lands
    """

    def __init__(self, count: int, replica_id: Optional[int] = None):
        self.count = int(count)
        self.replica_id = replica_id
        self.fired = 0

    def __call__(self, replica_id: int, record) -> None:
        if self.replica_id is not None and replica_id != self.replica_id:
            return
        if self.fired >= self.count:
            return
        self.fired += 1
        raise ConnectionError(
            f"injected dispatch drop {self.fired}/{self.count} "
            f"(replica {replica_id}, request "
            f"{record.get('request_id')!r})")


class flaky_replica:
    """Intermittent transport faults on a LIVE replica (ISSUE 17).

    Unlike :class:`kill_replica`, the replica keeps running and its
    ``healthz`` stays 200 — only the router-facing transport methods
    (``submit`` / ``poll`` / ``serving_stats``) are wrapped so that a
    seeded fraction of calls raise ``ConnectionError`` (``error_rate``)
    and/or stall (``latency_ms``).  That is exactly the *flapping*
    regime: the binary census says healthy, yet every few calls storm
    the retry path — the scenario the circuit breaker + retry budget
    must absorb.

    ``target`` is a ``ReplicaManager``/``LocalReplicaManager`` plus
    ``index``, or a replica object directly.  ``when`` (no-arg
    predicate, like ``kill_replica``) gates injection per call, so
    "start flaking once stream X has 2 tokens" is deterministic.
    Restores the original methods on ``stop()`` / context exit.

    >>> with flaky_replica(manager, index=1, error_rate=0.3,
    ...                    seed=7) as flake:
    ...     router.run(timeout=30)
    >>> flake.injected_errors > 0
    True
    """

    METHODS = ("submit", "poll", "serving_stats")

    def __init__(self, target, index: Optional[int] = None,
                 error_rate: float = 0.0, latency_ms: float = 0.0,
                 seed: int = 0,
                 when: Optional[Callable[[], bool]] = None,
                 sleep=time.sleep):
        if index is not None and hasattr(target, "replicas"):
            target = target.replicas[index]     # manager slot
        self.replica = target
        self.error_rate = float(error_rate)
        self.latency_ms = float(latency_ms)
        self.when = when
        self.rng = random.Random(seed)
        self._sleep = sleep
        self.calls = 0
        self.injected_errors = 0
        self.injected_delays = 0
        self._saved: dict = {}
        self._install()

    _MISSING = object()   # name was class-level, not an instance attr

    def _install(self) -> None:
        for name in self.METHODS:
            orig = getattr(self.replica, name)
            self._saved[name] = self.replica.__dict__.get(
                name, self._MISSING)

            def wrapper(*a, _orig=orig, _name=name, **kw):
                return self._intercept(_orig, _name, *a, **kw)

            setattr(self.replica, name, wrapper)

    def _intercept(self, orig, name, *a, **kw):
        self.calls += 1
        if self.when is None or self.when():
            if self.latency_ms > 0:
                self.injected_delays += 1
                self._sleep(self.latency_ms / 1e3)
            if self.rng.random() < self.error_rate:
                self.injected_errors += 1
                raise ConnectionError(
                    f"injected flake #{self.injected_errors} "
                    f"({name} on replica "
                    f"{getattr(self.replica, 'replica_id', '?')})")
        return orig(*a, **kw)

    def stop(self) -> None:
        """Restore the wrapped transport (idempotent)."""
        for name, prev in self._saved.items():
            if prev is self._MISSING:
                delattr(self.replica, name)   # class method shows again
            else:
                setattr(self.replica, name, prev)
        self._saved = {}

    def __enter__(self) -> "flaky_replica":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


@contextlib.contextmanager
def fast_retries(max_attempts: int = 4):
    """Swap every module-level IO retry policy for a sleepless one for the
    duration of the block (fault tests shouldn't pay real backoff)."""
    from ..distributed import checkpoint as ckpt_mod
    from ..framework import io as io_mod

    policy = RetryPolicy(max_attempts=max_attempts, base_delay=0.0,
                         jitter=0.0, sleep=lambda _t: None)
    saved = (ckpt_mod.IO_RETRY_POLICY, io_mod.IO_RETRY_POLICY)
    ckpt_mod.IO_RETRY_POLICY = policy
    io_mod.IO_RETRY_POLICY = policy
    try:
        yield policy
    finally:
        ckpt_mod.IO_RETRY_POLICY, io_mod.IO_RETRY_POLICY = saved
