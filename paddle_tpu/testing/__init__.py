"""Testing utilities (resilience layer, ISSUE 1).

``paddle_tpu.testing.faults`` is the fault-injection harness used by
``tests/test_fault_tolerance.py`` to prove the checkpoint/elastic stack
survives torn writes, bit flips, transient I/O errors and preemption
signals.
"""
from . import faults  # noqa: F401

__all__ = ["faults"]
