"""paddle_tpu — a TPU-native deep-learning framework.

Built from scratch on JAX/XLA/Pallas/pjit with the capability set of the
reference framework (PaddlePaddle, surveyed in /root/repo/SURVEY.md).  The
tensor type is jax.Array; `paddle_tpu.*` provides the paddle-shaped tensor
API (reference: python/paddle/tensor/*), with jax.numpy as the kernel
substrate — the analog of the reference's 287 phi kernels, which XLA both
implements and fuses.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np

from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import framework  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import device  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import sysconfig  # noqa: F401
from . import callbacks  # noqa: F401
from . import hub  # noqa: F401
from .reader import batch  # noqa: F401
from . import cost_model  # noqa: F401
from . import hapi  # noqa: F401
from . import incubate  # noqa: F401
from . import onnx  # noqa: F401
from . import inference  # noqa: F401
from . import jit  # noqa: F401
from . import linalg  # noqa: F401
from .hapi import flops, summary  # noqa: F401
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from . import sparse  # noqa: F401
from . import static  # noqa: F401
from . import utils  # noqa: F401
from . import version  # noqa: F401
from .framework.tensor_methods import install_tensor_methods

install_tensor_methods()      # paddle.Tensor method surface on jax arrays

from .framework import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa: F401
                        NPUPlace, TPUPlace, get_device, load, save, seed,
                        set_device)
from .framework.dtype import convert_dtype
from .framework.flags import get_flags, set_flags  # noqa: F401
from .framework.random import key_scope, next_key  # noqa: F401
from .nn.initializer import ParamAttr  # noqa: F401
from .nn.layer import Parameter  # noqa: F401

__version__ = "0.1.0"

# dtype names (paddle.float32 etc.)
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
uint8 = jnp.uint8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
bool = jnp.bool_  # noqa: A001
complex64 = jnp.complex64
complex128 = jnp.complex128
dtype = jnp.dtype            # paddle.dtype: the dtype *type*

Tensor = jax.Array


def _arr(x):
    return x.__jax_array__() if hasattr(x, "__jax_array__") else x


# ---------------------------------------------------------------------------
# creation (reference python/paddle/tensor/creation.py)
# ---------------------------------------------------------------------------
_default_dtype = jnp.float32


def _float_dtype(dtype):
    """Resolve a creation-API dtype: None -> the global default float
    (paddle.set_default_dtype)."""
    return _default_dtype if dtype is None else convert_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    x = jnp.asarray(_arr(data), dtype=convert_dtype(dtype))
    if place is not None:
        x = jax.device_put(x, place.device)
    return x


def zeros(shape, dtype=None):
    return jnp.zeros(shape, _float_dtype(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, _float_dtype(dtype))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, _float_dtype(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(_arr(x), convert_dtype(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(_arr(x), convert_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(_arr(x), fill_value, convert_dtype(dtype))


def arange(start, end=None, step=1, dtype=None):
    return jnp.arange(start, end, step, convert_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=_float_dtype(dtype))


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=_float_dtype(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(shape, _float_dtype(dtype))


def rand(shape, dtype=None):
    return jax.random.uniform(next_key(), shape, _float_dtype(dtype))


def randn(shape, dtype=None):
    return jax.random.normal(next_key(), shape, _float_dtype(dtype))


def randint(low, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return jax.random.randint(next_key(), shape, low, high,
                              convert_dtype(dtype))


def randperm(n, dtype="int64"):
    return jax.random.permutation(next_key(), n).astype(convert_dtype(dtype))


def uniform(shape, dtype=None, min=-1.0, max=1.0):
    return jax.random.uniform(next_key(), shape, _float_dtype(dtype), min, max)


def normal(mean=0.0, std=1.0, shape=(1,)):
    return mean + std * jax.random.normal(next_key(), shape)


def bernoulli(x):
    return jax.random.bernoulli(next_key(), _arr(x)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# math / reduction / comparison (reference python/paddle/tensor/math.py)
# ---------------------------------------------------------------------------
def _wrap1(fn):
    def op(x, *a, **k):
        return fn(_arr(x), *a, **k)
    op.__name__ = fn.__name__
    return op


def _wrap2(fn):
    def op(x, y, *a, **k):
        return fn(_arr(x), _arr(y), *a, **k)
    op.__name__ = fn.__name__
    return op


abs = _wrap1(jnp.abs)  # noqa: A001
exp = _wrap1(jnp.exp)
log = _wrap1(jnp.log)
log2 = _wrap1(jnp.log2)
log10 = _wrap1(jnp.log10)
log1p = _wrap1(jnp.log1p)
sqrt = _wrap1(jnp.sqrt)
rsqrt = _wrap1(jax.lax.rsqrt)
square = _wrap1(jnp.square)
sin = _wrap1(jnp.sin)
cos = _wrap1(jnp.cos)
tan = _wrap1(jnp.tan)
asin = _wrap1(jnp.arcsin)
acos = _wrap1(jnp.arccos)
atan = _wrap1(jnp.arctan)
sinh = _wrap1(jnp.sinh)
cosh = _wrap1(jnp.cosh)
tanh = _wrap1(jnp.tanh)
floor = _wrap1(jnp.floor)
ceil = _wrap1(jnp.ceil)
round = _wrap1(jnp.round)  # noqa: A001
trunc = _wrap1(jnp.trunc)
sign = _wrap1(jnp.sign)
reciprocal = _wrap1(jnp.reciprocal)
neg = _wrap1(jnp.negative)
erf = _wrap1(jax.scipy.special.erf)
sigmoid = _wrap1(jax.nn.sigmoid)
isnan = _wrap1(jnp.isnan)
isinf = _wrap1(jnp.isinf)
isfinite = _wrap1(jnp.isfinite)

add = _wrap2(jnp.add)
subtract = _wrap2(jnp.subtract)
multiply = _wrap2(jnp.multiply)
divide = _wrap2(jnp.divide)
floor_divide = _wrap2(jnp.floor_divide)
mod = _wrap2(jnp.mod)
remainder = _wrap2(jnp.remainder)
pow = _wrap2(jnp.power)  # noqa: A001
maximum = _wrap2(jnp.maximum)
minimum = _wrap2(jnp.minimum)
fmax = _wrap2(jnp.fmax)
fmin = _wrap2(jnp.fmin)
atan2 = _wrap2(jnp.arctan2)
equal = _wrap2(jnp.equal)
not_equal = _wrap2(jnp.not_equal)
greater_than = _wrap2(jnp.greater)
greater_equal = _wrap2(jnp.greater_equal)
less_than = _wrap2(jnp.less)
less_equal = _wrap2(jnp.less_equal)
logical_and = _wrap2(jnp.logical_and)
logical_or = _wrap2(jnp.logical_or)
logical_xor = _wrap2(jnp.logical_xor)
logical_not = _wrap1(jnp.logical_not)
bitwise_and = _wrap2(jnp.bitwise_and)
bitwise_or = _wrap2(jnp.bitwise_or)
bitwise_xor = _wrap2(jnp.bitwise_xor)

mean = _wrap1(jnp.mean)
# `sum`/`max`/`min`/`prod` accept paddle-style axis kw
def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    return jnp.sum(_arr(x), axis=axis, dtype=convert_dtype(dtype),
                   keepdims=keepdim)


def max(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.max(_arr(x), axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.min(_arr(x), axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False):
    return jnp.prod(_arr(x), axis=axis, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(_arr(x), axis=axis, ddof=1 if unbiased else 0,
                   keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(_arr(x), axis=axis, ddof=1 if unbiased else 0,
                   keepdims=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmax(_arr(x), axis=axis, keepdims=keepdim).astype(
        convert_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    return jnp.argmin(_arr(x), axis=axis, keepdims=keepdim).astype(
        convert_dtype(dtype))


def argsort(x, axis=-1, descending=False):
    idx = jnp.argsort(_arr(x), axis=axis)
    return jnp.flip(idx, axis=axis) if descending else idx


def sort(x, axis=-1, descending=False):
    y = jnp.sort(_arr(x), axis=axis)
    return jnp.flip(y, axis=axis) if descending else y


def topk(x, k, axis=-1, largest=True):
    x = _arr(x)
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(x if largest else -x, k)
    if not largest:
        vals = -vals
    if axis not in (-1, _arr(x).ndim - 1):
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx


def cumsum(x, axis=None, dtype=None):
    return jnp.cumsum(_arr(x), axis=axis, dtype=convert_dtype(dtype))


def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(_arr(x), axis=dim, dtype=convert_dtype(dtype))


def clip(x, min=None, max=None):
    return jnp.clip(_arr(x), min, max)


def all(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.all(_arr(x), axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):  # noqa: A001
    return jnp.any(_arr(x), axis=axis, keepdims=keepdim)


# linalg-ish
matmul = nn.functional.matmul
def mm(x, y):
    return jnp.matmul(_arr(x), _arr(y))


def bmm(x, y):
    return jnp.matmul(_arr(x), _arr(y))


def dot(x, y):
    return jnp.sum(_arr(x) * _arr(y), axis=-1)


def t(x):
    """Reference paddle.t: identity for 0/1-D, transpose for 2-D; higher
    ranks are an error (use transpose)."""
    x = _arr(x)
    if x.ndim < 2:
        return x
    if x.ndim == 2:
        return jnp.swapaxes(x, -1, -2)
    raise ValueError(
        f"paddle.t expects a tensor of rank <= 2, got rank {x.ndim}; "
        "use transpose for higher-rank permutations")


def einsum(eq, *xs):
    return jnp.einsum(eq, *[_arr(x) for x in xs])


def norm(x, p="fro", axis=None, keepdim=False):
    """paddle.norm: with axis=None the input is flattened and the vector
    p-norm is taken ('fro' ≡ 2-norm of the flattened tensor — the reference
    docstring's 'NOT REAL MATRIX NORM'); matrix norms only for 2-tuple axis."""
    x = _arr(x)
    if axis is None:
        pv = 2 if p == "fro" else p
        out = jnp.linalg.norm(x.reshape(-1), ord=pv)
        if keepdim:
            out = out.reshape((1,) * x.ndim)
        return out
    return jnp.linalg.norm(x, ord=(2 if p == "fro" and not isinstance(axis, (tuple, list)) else p),
                           axis=(tuple(axis) if isinstance(axis, list) else axis),
                           keepdims=keepdim)


def outer(x, y):
    return jnp.outer(_arr(x), _arr(y))


def diag(x, offset=0):
    return jnp.diag(_arr(x), k=offset)


def tril(x, diagonal=0):
    return jnp.tril(_arr(x), k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(_arr(x), k=diagonal)


# ---------------------------------------------------------------------------
# manipulation (reference python/paddle/tensor/manipulation.py)
# ---------------------------------------------------------------------------
def reshape(x, shape):
    return jnp.reshape(_arr(x), shape)


def transpose(x, perm):
    return jnp.transpose(_arr(x), perm)


def squeeze(x, axis=None):
    return jnp.squeeze(_arr(x), axis=axis)


def unsqueeze(x, axis):
    return jnp.expand_dims(_arr(x), axis)


def concat(xs, axis=0):
    return jnp.concatenate([_arr(x) for x in xs], axis=axis)


def stack(xs, axis=0):
    return jnp.stack([_arr(x) for x in xs], axis=axis)


def split(x, num_or_sections, axis=0):
    x = _arr(x)
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    sizes = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sizes:
        known = _np.sum([s for s in sizes if s != -1])
        sizes[sizes.index(-1)] = total - int(known)
    offsets = _np.cumsum(sizes)[:-1].tolist()
    return jnp.split(x, offsets, axis=axis)


def chunk(x, chunks, axis=0):
    return jnp.split(_arr(x), chunks, axis=axis)


def tile(x, repeat_times):
    return jnp.tile(_arr(x), repeat_times)


def expand(x, shape):
    return jnp.broadcast_to(_arr(x), shape)


def broadcast_to(x, shape):
    return jnp.broadcast_to(_arr(x), shape)


def flip(x, axis):
    return jnp.flip(_arr(x), axis=axis)


def roll(x, shifts, axis=None):
    return jnp.roll(_arr(x), shifts, axis=axis)


def flatten(x, start_axis=0, stop_axis=-1):
    return nn.functional.flatten(x, start_axis, stop_axis)


def gather(x, index, axis=0):
    # jnp.take no longer coerces python lists — asarray the indices
    return jnp.take(_arr(x), jnp.asarray(_arr(index)), axis=axis)


def gather_nd(x, index):
    x, index = _arr(x), _arr(index)
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(_arr(x), _arr(indices), axis=axis)


def scatter(x, index, updates, overwrite=True):
    """Reference phi scatter kernel: with overwrite=False the destination rows
    are zeroed first (ScatterAssignAdd, paddle/phi/kernels/funcs/scatter.h),
    so result rows are the sum of updates only, not dest + updates."""
    x, index, updates = (jnp.asarray(_arr(x)), _arr(index),
                         _arr(updates))
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].set(jnp.zeros((), x.dtype)).at[index].add(updates)


def index_select(x, index, axis=0):
    return jnp.take(_arr(x), _arr(index), axis=axis)


def masked_select(x, mask):
    return _arr(x)[_arr(mask)]


def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.where(_arr(condition))
    return jnp.where(_arr(condition), _arr(x), _arr(y))


def nonzero(x):
    return jnp.stack(jnp.nonzero(_arr(x)), axis=-1)


def unique(x, return_index=False, return_inverse=False, return_counts=False):
    return jnp.unique(_arr(x), return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts)


def cast(x, dtype):
    return _arr(x).astype(convert_dtype(dtype))


def numel(x):
    return _arr(x).size


def shape(x):
    return tuple(_arr(x).shape)


def is_tensor(x):
    return isinstance(x, jax.Array)


def assign(x, output=None):
    return jnp.asarray(_arr(x))


def clone(x):
    return jnp.copy(_arr(x))


def numpy(x):
    return _np.asarray(_arr(x))


def item(x):
    return _arr(x).item()


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(_arr(x), _arr(y), rtol=rtol, atol=atol,
                        equal_nan=equal_nan)


def equal_all(x, y):
    return jnp.array_equal(_arr(x), _arr(y))


# grad/no-grad parity
no_grad = autograd.no_grad
grad = autograd.grad

# execution-mode toggles (recorded state; one codepath — framework/mode.py)
from .framework.mode import (  # noqa: E402
    enable_static, disable_static, in_dynamic_mode, set_grad_enabled,
    is_grad_enabled)

# Keras-style Model at the top level (reference paddle.Model = hapi.Model)
Model = hapi.Model


def is_compiled_with_cuda() -> bool:
    """False by construction — this build targets TPU via XLA (reference
    paddle.is_compiled_with_cuda; the whole WITH_GPU family answers No)."""
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def get_cudnn_version():
    """None — no cuDNN in a TPU build (reference device.py
    get_cudnn_version returns None when not compiled with CUDA)."""
    return None


def stop_gradient(x):
    return jax.lax.stop_gradient(_arr(x))


# device helpers
def device_count():
    return len(jax.devices())


def synchronize():
    """Block until all enqueued device work is done (paddle.device.cuda.
    synchronize analog)."""
    for a in jax.live_arrays():
        a.block_until_ready()


# ---------------------------------------------------------------------------
# top-level parity fill (reference python/paddle/__init__.py __all__)
# ---------------------------------------------------------------------------
def set_default_dtype(d):
    """Global default float dtype for creation APIs called with dtype=None
    (reference paddle.set_default_dtype)."""
    global _default_dtype
    d = convert_dtype(d)
    if not jnp.issubdtype(d, jnp.floating):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return jnp.dtype(_default_dtype).name


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """numpy print options govern jax.Array reprs too."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def get_cuda_rng_state():
    """Accelerator RNG state (maps onto the framework key stream; the
    reference returns per-GPU generator states)."""
    return [framework.random.default_generator().get_state()]


def set_cuda_rng_state(states):
    framework.random.default_generator().set_state(states[0])


def disable_signal_handler():
    """No-op: the reference unhooks its C++ signal handlers; this runtime
    installs none (dataloader workers use multiprocessing defaults)."""


def check_shape(shape):
    """Validate a creation-API shape (reference fluid data_feeder
    check_shape): ints, or a list/tuple of ints with at most one -1."""
    from .framework.errors import enforce
    if isinstance(shape, int):
        shape = (shape,)
    enforce(isinstance(shape, (list, tuple)),
            f"shape must be int or list/tuple of int, got {type(shape)}")
    negs = 0
    for s in shape:
        enforce(isinstance(s, int), f"shape entries must be int, got {s!r}")
        negs += s < 0
    enforce(negs <= 1, f"at most one -1 allowed in shape, got {shape}")
    return tuple(shape)


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone Parameter (reference paddle.create_parameter): the
    free-function twin of Layer.create_parameter — same initializer
    convention (framework Initializer called as init(key, shape, dtype)),
    same attr handling (ParamAttr initializer + trainable honored)."""
    from .nn import initializer as I
    shape = check_shape(shape)
    d = convert_dtype(dtype)
    trainable = True
    init = default_initializer
    if attr is not None:
        if getattr(attr, "initializer", None) is not None and init is None:
            init = attr.initializer
        trainable = getattr(attr, "trainable", True)
    if init is None:
        init = I._global_initializer["bias" if is_bias else "weight"]
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierUniform()
    val = init(framework.random.next_key(), shape, d)
    return Parameter(val, trainable=trainable, is_bias=is_bias)


class DataParallel(nn.Layer):
    """Reference paddle.DataParallel(model) wrapper.  Under GSPMD the
    gradient synchronization the reference does with allreduce hooks
    (python/paddle/fluid/dygraph/parallel.py:413) is emitted by XLA from
    the dp sharding — the wrapper only needs to preserve the reference's
    surface: forward delegation, ``_layers``, state_dict passthrough, and
    the no-op scale_loss/apply_collective_grads pair."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


# extended op corpus (reference tensor/{math,manipulation,search,random}.py
# long tail) — see tensor_ops.py
from .tensor_ops import *  # noqa: F401,F403,E402

def inverse(x):
    """Matrix inverse (reference paddle.inverse == linalg.inv)."""
    return linalg.inv(x)


# second method-install pass: the full reference tensor_method_func
# contract, now that every functional op is importable
from .framework.tensor_methods import install_reference_method_contract

install_reference_method_contract()
