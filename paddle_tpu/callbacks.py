"""paddle.callbacks namespace (reference python/paddle/callbacks.py —
a re-export of the hapi callback classes)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, LRScheduler, ModelCheckpoint,
    ProgBarLogger)

__all__ = ["Callback", "CallbackList", "EarlyStopping", "LRScheduler",
           "ModelCheckpoint", "ProgBarLogger"]
