"""Compile/retrace tracking (ISSUE 4).

``jax.jit`` recompiles whenever an argument's *signature* — pytree
structure, leaf shapes/dtypes, or a static value — differs from every
trace it has cached.  On a TPU pod a retrace costs seconds to minutes of
XLA time, so a data pipeline that leaks one ragged batch shape per step
("retrace storm") silently turns an MFU-45% run into a compile farm.
The PR 3 telemetry spine records *how long* a step took; this module
records *why* it recompiled.

:func:`track_jit` wraps an already-jitted callable with a signature
cache that mirrors jax's own cache key (structure + shape/dtype of array
leaves + repr of static leaves).  Every call classifies as a cache hit
or miss; misses beyond the first are **retraces**, and each retrace is
diffed against the previous trace's signature to name *which argument*
changed and how (``data[1]: f32[2,8] -> f32[2,12]``).  When
``storm_threshold`` retraces land within a ``storm_window``-call window,
a ``compile.retrace_storm`` record is emitted naming the most frequent
culprit argument — the one line a run doctor needs.

Instruments (per wrapped function ``<name>``):

- counter   ``compile.count[fn=<name>]``      — traces (first + retraces)
- counter   ``compile.cache_hit[fn=<name>]``  — calls served from cache
- counter   ``compile.retraces[fn=<name>]``   — misses beyond the first
- counter   ``compile.storms[fn=<name>]``     — storm detections
- histogram ``compile.wall_ms[fn=<name>]``    — miss-call wall time
  (trace + XLA compile dominate it; the honest proxy available on every
  backend without PJRT compile callbacks)

Event records: ``compile`` (one per miss, with ``changed`` naming the
diffed arguments) and ``compile.retrace_storm``.
"""
from __future__ import annotations

import functools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["arg_signature", "diff_signatures", "CompileTracker",
           "track_jit", "get_tracker", "reset_tracker"]


def _describe_leaf(x: Any) -> str:
    """Shape/dtype for array-likes (``f32[4,6]``), bounded repr for
    static leaves — mirrors what jax's cache key sees."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    r = repr(x)
    return r if len(r) <= 64 else r[:61] + "..."


def arg_signature(arg: Any) -> Tuple[str, Tuple[str, ...]]:
    """One argument's trace signature: (pytree structure, leaf descs).

    Two calls with equal signatures land on the same jax trace; a
    differing signature forces a retrace.  Scalars/None/strings are
    pytree leaves (or empty trees) and show up in the repr half.
    """
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(arg)
    return (str(treedef), tuple(_describe_leaf(x) for x in leaves))


def diff_signatures(prev: Sequence[Tuple[str, Tuple[str, ...]]],
                    cur: Sequence[Tuple[str, Tuple[str, ...]]],
                    names: Sequence[str]) -> List[Dict[str, str]]:
    """Name every argument whose signature changed between two traces.

    Returns ``[{"arg": name, "detail": "f32[2,8] -> f32[2,12]"}, ...]``;
    an argument whose pytree *structure* changed reports
    ``"structure changed"`` plus the structural reprs.
    """
    changed: List[Dict[str, str]] = []
    n = max(len(prev), len(cur))
    for i in range(n):
        name = names[i] if i < len(names) else f"arg{i}"
        if i >= len(prev) or i >= len(cur):
            changed.append({"arg": name, "detail": "added/removed"})
            continue
        (ptree, pleaves), (ctree, cleaves) = prev[i], cur[i]
        if ptree != ctree:
            changed.append({"arg": name, "detail": "structure changed"})
            continue
        if pleaves == cleaves:
            continue
        for j, (a, b) in enumerate(zip(pleaves, cleaves)):
            if a != b:
                detail = f"{a} -> {b}"
                if len(pleaves) > 1:
                    detail = f"leaf {j}: {detail}"
                changed.append({"arg": name, "detail": detail})
                break  # one leaf names the argument; don't spam
    return changed


class _FuncState:
    __slots__ = ("names", "seen", "last_sig", "traces", "retraces",
                 "storms", "recent", "calls")

    def __init__(self, names: Sequence[str]):
        self.names = list(names)
        self.seen: set = set()
        self.last_sig: Optional[List[Tuple[str, Tuple[str, ...]]]] = None
        self.traces = 0
        self.retraces = 0
        self.storms = 0
        self.calls = 0
        # (call index, changed-arg names) of recent retraces
        self.recent: deque = deque(maxlen=64)


class CompileTracker:
    """Process-wide compile/retrace accountant.

    ``registry`` defaults to the global metrics registry at call time, so
    records land on the run's JSONL timeline like every other emitter.
    ``storm_threshold`` retraces of one function within the last
    ``storm_window`` calls flag a storm (and re-arm: the next storm needs
    a fresh ``storm_threshold`` retraces).
    """

    def __init__(self, registry=None, storm_threshold: int = 3,
                 storm_window: int = 16, max_signatures: int = 4096):
        self._registry = registry
        self.storm_threshold = int(storm_threshold)
        self.storm_window = int(storm_window)
        self.max_signatures = int(max_signatures)
        self._lock = threading.Lock()
        self._funcs: Dict[str, _FuncState] = {}

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from .registry import get_registry
        return get_registry()

    # -- introspection -----------------------------------------------------
    def stats(self, name: str) -> Dict[str, int]:
        with self._lock:
            st = self._funcs.get(name)
            if st is None:
                return {"calls": 0, "traces": 0, "retraces": 0, "storms": 0}
            return {"calls": st.calls, "traces": st.traces,
                    "retraces": st.retraces, "storms": st.storms}

    def functions(self) -> List[str]:
        with self._lock:
            return sorted(self._funcs)

    def reset(self) -> None:
        with self._lock:
            self._funcs.clear()

    # -- the observation path ----------------------------------------------
    def observe(self, name: str, args: Sequence[Any],
                arg_names: Optional[Sequence[str]] = None,
                wall_ms: Optional[float] = None) -> Optional[dict]:
        """Classify one call; returns the emitted ``compile`` record on a
        miss, None on a hit.  Called by the :func:`track_jit` wrapper —
        or directly by code that times its own compiles (bench.py)."""
        return self.observe_signatures([arg_signature(a) for a in args],
                                       name=name, arg_names=arg_names,
                                       wall_ms=wall_ms)

    def observe_signatures(self, sigs: List[Tuple[str, Tuple[str, ...]]],
                           name: str,
                           arg_names: Optional[Sequence[str]] = None,
                           wall_ms: Optional[float] = None
                           ) -> Optional[dict]:
        """Like :meth:`observe` but with pre-computed signatures — the
        wrapper computes them *before* the call so donated buffers
        (``donate_argnums``) are described while still alive."""
        key = hash(tuple(sigs))
        names = list(arg_names or [])
        while len(names) < len(sigs):
            names.append(f"arg{len(names)}")
        reg = self._reg()
        with self._lock:
            st = self._funcs.get(name)
            if st is None:
                st = self._funcs[name] = _FuncState(names)
            st.calls += 1
            if key in st.seen:
                hit = True
            else:
                hit = False
                if len(st.seen) < self.max_signatures:
                    st.seen.add(key)
                st.traces += 1
                if st.last_sig is not None:
                    st.retraces += 1
            prev, call_idx = st.last_sig, st.calls
            st.last_sig = sigs
        if hit:
            reg.counter(f"compile.cache_hit[fn={name}]").inc()
            return None
        reg.counter(f"compile.count[fn={name}]").inc()
        if wall_ms is not None:
            reg.histogram(f"compile.wall_ms[fn={name}]").observe(wall_ms)
        changed: List[Dict[str, str]] = []
        retrace = prev is not None
        if retrace:
            changed = diff_signatures(prev, sigs, names)
            reg.counter(f"compile.retraces[fn={name}]").inc()
        record = {"function": name, "trace": True, "retrace": retrace,
                  "changed": changed, "wall_ms": wall_ms,
                  "nargs": len(sigs)}
        reg.emit("compile", **record)
        if retrace:
            self._maybe_storm(name, call_idx, changed, reg)
        return record

    def _maybe_storm(self, name: str, call_idx: int,
                     changed: List[Dict[str, str]], reg) -> None:
        with self._lock:
            st = self._funcs[name]
            st.recent.append(
                (call_idx, tuple(c["arg"] for c in changed)))
            window = [(i, args) for i, args in st.recent
                      if call_idx - i < self.storm_window]
            if len(window) < self.storm_threshold:
                return
            # culprit: the argument changing most often across the storm
            freq: Dict[str, int] = {}
            for _i, args in window:
                for a in args:
                    freq[a] = freq.get(a, 0) + 1
            st.storms += 1
            st.recent.clear()  # re-arm
            retraces = len(window)
        culprits = sorted(freq, key=lambda a: (-freq[a], a))
        reg.counter(f"compile.storms[fn={name}]").inc()
        reg.emit("compile.retrace_storm", function=name,
                 retraces=retraces, window=self.storm_window,
                 culprits=culprits,
                 culprit=(culprits[0] if culprits else None),
                 last_changed=changed)
        from ..framework.log import vlog
        vlog(0, "observability: retrace storm on %s — %d retraces in "
             "%d calls, culprit argument %r", name, retraces,
             self.storm_window, culprits[0] if culprits else "?")


_tracker_lock = threading.Lock()
_tracker: Optional[CompileTracker] = None


def get_tracker() -> CompileTracker:
    """The process-global compile tracker (mirrors ``get_registry``)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = CompileTracker()
        return _tracker


def reset_tracker() -> None:
    """Drop all per-function compile state (tests)."""
    get_tracker().reset()


def track_jit(fn: Callable, name: Optional[str] = None,
              arg_names: Optional[Sequence[str]] = None,
              tracker: Optional[CompileTracker] = None) -> Callable:
    """Wrap a jitted callable with compile/retrace accounting.

    The wrapper is transparent (same args/result) and cheap on hits —
    one signature walk over the arguments (linear in pytree leaves, no
    device sync).  Misses additionally time the call: on a fresh
    signature the call wall time is trace + XLA compile + first run,
    the honest per-backend compile-cost proxy.

    >>> step = track_jit(jax.jit(step), name="train_step",
    ...                  arg_names=("params", "batch"))
    """
    if name is None:
        name = getattr(fn, "__name__", None) or repr(fn)

    @functools.wraps(fn)
    def tracked(*args, **kwargs):
        tr = tracker or get_tracker()
        sigs = names = None
        abstract = None
        try:
            # signatures BEFORE the call: donated buffers are gone after
            all_args = list(args) + [kwargs[k] for k in sorted(kwargs)]
            sigs = [arg_signature(a) for a in all_args]
            names = list(arg_names) if arg_names else None
            if names is not None and kwargs:
                names = names[:len(args)] + sorted(kwargs)
        except Exception:
            sigs = None  # tracking must never break the call
        if sigs is not None:
            try:
                # abstract shapes too, and for the same reason: the
                # roofline observatory re-lowers this signature later,
                # after any donated buffers are dead (ISSUE 19)
                from . import roofline
                if roofline.capture_active():
                    abstract = roofline.abstractify(args, kwargs)
            except Exception:
                abstract = None
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        if sigs is not None:
            try:
                wall_ms = (time.perf_counter() - t0) * 1e3
                rec = tr.observe_signatures(sigs, name=name,
                                            arg_names=names,
                                            wall_ms=wall_ms)
                if abstract is not None:
                    roofline.get_observatory().record(
                        name, fn, abstract[0], abstract[1],
                        sig_key=hash(tuple(sigs)),
                        miss=rec is not None)
            except Exception as e:
                from ..framework.log import vlog
                vlog(1, "observability: compile tracking failed for %s: "
                     "%r", name, e)
        return result

    tracked.__tracked_name__ = name
    tracked.__wrapped_fn__ = fn
    return tracked
