"""Per-step HBM accounting (ISSUE 4).

PJRT's allocator telemetry (``device.memory_stats()``: ``bytes_in_use``,
``peak_bytes_in_use``, ``largest_alloc_size``, ``bytes_limit``) is the
only ground truth for the second silent MFU killer — HBM pressure.  A
run that creeps toward the limit starts fragmenting, then rematerializing,
then OOMs; by the time the OOM surfaces, the interesting state is gone.
This module samples the watermark table on a step cadence and keeps the
last table around so an OOM leaves a postmortem.

- :class:`MemorySampler` — samples every ``PTPU_MEM_SAMPLE_EVERY`` steps
  (default 16; PJRT stats are a host RPC on some backends, so not every
  step).  Each sample emits one ``memory`` record with the per-device
  table plus deltas vs the previous sample, and refreshes gauges
  ``memory.bytes_in_use[device=..]`` / ``memory.peak_bytes[device=..]``
  / ``memory.utilization[device=..]``.
- :func:`oom_postmortem` — called when a step dies with an allocator
  error (:func:`is_oom_error`): emits a ``memory.oom`` record carrying
  the last-known watermark table per device — the state *before* the
  allocation that killed the run.

CPU backends report no allocator stats ({}); the sampler then emits
nothing and costs one dict probe per cadence.  Tests inject
``stats_fn``.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

__all__ = ["MEM_SAMPLE_ENV", "MemorySampler", "default_sample_every",
           "device_stats_table", "is_oom_error", "oom_postmortem",
           "get_sampler", "reset_sampler"]

MEM_SAMPLE_ENV = "PTPU_MEM_SAMPLE_EVERY"

# the PJRT stat keys a watermark table carries (when the backend has them)
_KEYS = ("bytes_in_use", "peak_bytes_in_use", "largest_alloc_size",
         "bytes_limit", "bytes_reserved", "num_allocs")


def default_sample_every() -> int:
    return max(1, int(os.environ.get(MEM_SAMPLE_ENV, "16")))


def device_stats_table() -> Dict[str, Dict[str, int]]:
    """{``platform:id``: PJRT stats} for every *addressable* device —
    the per-device accounting the cross-replica weight-update analysis
    assumes.  Devices without allocator telemetry are omitted."""
    from .. import device as device_mod
    return device_mod.local_memory_stats()


class MemorySampler:
    """Step-cadenced HBM watermark sampler.

    ``stats_fn`` returns the per-device table (defaults to
    :func:`device_stats_table`); ``every`` defaults to the
    ``PTPU_MEM_SAMPLE_EVERY`` env knob.  ``sample(step)`` is a no-op off
    cadence, so it can sit unconditionally in the per-step telemetry
    path.
    """

    def __init__(self, every: Optional[int] = None,
                 stats_fn: Optional[Callable[[], Dict[str, Dict[str, int]]]]
                 = None, registry=None):
        self.every = default_sample_every() if every is None else max(
            1, int(every))
        self._stats_fn = stats_fn or device_stats_table
        self._registry = registry
        self._lock = threading.Lock()
        self._prev: Dict[str, Dict[str, int]] = {}
        self.last_table: Dict[str, Dict[str, int]] = {}
        self.last_step: Optional[int] = None
        self.samples = 0

    def _reg(self):
        if self._registry is not None:
            return self._registry
        from .registry import get_registry
        return get_registry()

    def sample(self, step: Optional[int] = None,
               force: bool = False) -> Optional[Dict[str, Any]]:
        """Take one sample (off-cadence calls return None).  The emitted
        ``memory`` record carries, per device, the raw watermark keys
        plus ``in_use_delta`` / ``largest_alloc_delta`` vs the previous
        sample — the creep signal a doctor trends on."""
        if not force and step is not None and step % self.every != 0:
            return None
        try:
            table = {dev: {k: int(v) for k, v in stats.items()
                           if k in _KEYS}
                     for dev, stats in self._stats_fn().items()}
        except Exception as e:  # sampling must never hurt the run
            from ..framework.log import vlog
            vlog(1, "observability: memory sample failed: %r", e)
            return None
        if not table:
            return None
        reg = self._reg()
        devices: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            prev = self._prev
            for dev, stats in table.items():
                row: Dict[str, Any] = dict(stats)
                p = prev.get(dev, {})
                if "bytes_in_use" in stats:
                    row["in_use_delta"] = (
                        stats["bytes_in_use"] - p.get("bytes_in_use",
                                                      stats["bytes_in_use"]))
                if "largest_alloc_size" in stats:
                    row["largest_alloc_delta"] = (
                        stats["largest_alloc_size"]
                        - p.get("largest_alloc_size",
                                stats["largest_alloc_size"]))
                limit = stats.get("bytes_limit")
                if limit:
                    row["utilization"] = stats.get("bytes_in_use", 0) / limit
                devices[dev] = row
            self._prev = table
            self.last_table = devices
            self.last_step = step
            self.samples += 1
        for dev, row in devices.items():
            if "bytes_in_use" in row:
                reg.gauge(f"memory.bytes_in_use[device={dev}]").set(
                    row["bytes_in_use"])
            if "peak_bytes_in_use" in row:
                reg.gauge(f"memory.peak_bytes[device={dev}]").set(
                    row["peak_bytes_in_use"])
            if "utilization" in row:
                reg.gauge(f"memory.utilization[device={dev}]").set(
                    row["utilization"])
        record = {"step": step, "devices": devices}
        reg.emit("memory", **record)
        return record


def is_oom_error(exc: BaseException) -> bool:
    """Does this exception look like a device allocator OOM?  XLA
    surfaces them as RESOURCE_EXHAUSTED ``XlaRuntimeError``s; match on
    the message so the check needs no backend-private exception types."""
    msg = str(exc).lower()
    return ("resource_exhausted" in msg or "out of memory" in msg
            or ("allocating" in msg and "exceeds" in msg))


def oom_postmortem(sampler: Optional[MemorySampler] = None,
                   error: Optional[BaseException] = None,
                   step: Optional[int] = None) -> Dict[str, Any]:
    """Dump the last-known watermark table per device as a
    ``memory.oom`` record (and return it).  Tries one fresh sample first
    — often the allocator survives the failed allocation and the
    *current* table shows exactly how full each device is."""
    sampler = sampler or get_sampler()
    try:
        sampler.sample(step=step, force=True)
    except Exception:  # noqa: swallow
        pass  # post-OOM stats RPC may itself die; the stale table below
        # is still the best evidence we have
    table = sampler.last_table
    reg = sampler._reg()
    reg.counter("memory.oom_count").inc()
    record = {"step": step if step is not None else sampler.last_step,
              "error": (f"{type(error).__name__}: {error}"[:512]
                        if error is not None else None),
              "devices": table}
    reg.emit("memory.oom", **record)
    from ..framework.log import vlog
    vlog(0, "observability: OOM postmortem — %d device watermark rows "
         "recorded", len(table))
    return record


_sampler_lock = threading.Lock()
_sampler: Optional[MemorySampler] = None


def get_sampler() -> MemorySampler:
    """The process-global sampler (honors ``PTPU_MEM_SAMPLE_EVERY``)."""
    global _sampler
    with _sampler_lock:
        if _sampler is None:
            _sampler = MemorySampler()
        return _sampler


def reset_sampler() -> None:
    """Drop the global sampler (tests re-read the env knob)."""
    global _sampler
    with _sampler_lock:
        _sampler = None
