"""MFU microscope (ISSUE 19) — roofline attribution of the gap between
achieved and peak FLOP throughput.

The bench matrix has always reported *achieved* MFU; nothing could say
where the missing fraction went.  This module is the instrument: for
every jitted step the PR 4 compile tracker already sees, it captures the
compiled artifact (``lowered.compile().cost_analysis()`` plus the
optimized-HLO text), classifies each op, fits a per-``device_kind``
roofline (Williams et al.: per-op time = max(flops/peak_flops,
bytes/peak_bw)) and decomposes the measured step time into an **MFU-gap
budget** of named sinks:

==================  ====================================================
sink                meaning
==================  ====================================================
``mxu``             modeled matrix-unit time — the useful part
``memory_bound``    per-op excess of ``bytes/bw`` over ``flops/peak``
``comm``            exposed collectives (the measured collective phase)
``host``            input pipeline + readback (measured data+readback)
``padding``         wasted flops: pow2 prefill buckets and batch pad
                    rows (``padding_frac`` × compute phase)
``unknown_device``  device kind absent from the roofline table — the
                    whole compute phase lands here *explicitly* rather
                    than being silently skipped (CPU dev boxes included)
``residual``        unattributed remainder — the honesty gauge,
                    mirroring request-trace ``coverage``
==================  ====================================================

Buckets (with residual) sum to the measured step p50 by construction;
``coverage`` = 1 − |residual|/measured.

Capture path: :func:`~paddle_tpu.observability.compilation.track_jit`
records each wrapped function's *abstract* argument shapes (taken
before the call — donated buffers are gone after) into the process
:class:`RooflineObservatory` whenever a :class:`capture_window` is open.
The bench runner opens one around each scenario and asks the window for
the row's ``roofline`` block at the end; capture is lazy (one
``lower().compile()`` per distinct function, at window close, never in
the timed region).

Portability: ``cost_analysis()`` on this jax returns aggregate totals
(a list of one dict on CPU) and may be sparse or missing entirely on
some backends — the per-op model therefore comes from parsing the
compiled HLO text, with the cost totals kept as a cross-check, and any
op whose shapes/flops can't be recovered is counted ``unmodeled``
instead of silently dropped.

Knobs: ``PTPU_HLO_DUMP_DIR`` (dump lowered + compiled text per jit
entry, filenames keyed by the PR 4 signature-cache key, newest
``PTPU_HLO_DUMP_KEEP`` entries kept), ``PTPU_ROOFLINE_TEST_INFLATE``
(``<sink>:<frac>`` synthetic drill — claims that fraction of the
measured step for the named sink and marks the block ``injected``; CI
uses it to prove the doctor names the right dominant sink).
"""
from __future__ import annotations

import os
import re
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

# `from . import mfu` would resolve to the package's re-exported
# mfu() *function* (it shadows the submodule attr); import by
# module path instead
from .mfu import device_spec as _device_spec

__all__ = ["SINKS", "RooflineObservatory", "get_observatory",
           "reset_observatory", "capture_window", "abstractify",
           "parse_hlo_ops", "fit_roofline", "analyze_program",
           "gap_budget", "degraded_block", "hlo_dump_dir",
           "hlo_dump_keep", "dump_hlo",
           "HLO_DUMP_ENV", "HLO_DUMP_KEEP_ENV", "INFLATE_ENV"]

# the gap-bucket taxonomy; bench.schema mirrors this literally (a test
# pins the two tuples equal) so the row schema never imports this module
# at module scope
SINKS = ("mxu", "memory_bound", "comm", "host", "padding",
         "unknown_device", "residual")

HLO_DUMP_ENV = "PTPU_HLO_DUMP_DIR"
HLO_DUMP_KEEP_ENV = "PTPU_HLO_DUMP_KEEP"
INFLATE_ENV = "PTPU_ROOFLINE_TEST_INFLATE"
DEFAULT_HLO_DUMP_KEEP = 16


# --------------------------------------------------------------------------
# HLO text parsing
# --------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}
_INT_DTYPES = frozenset(d for d in _DTYPE_BYTES
                        if d[0] in "su" and d != "u4" and d != "s4")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
# `%dot.4 = f32[64,32]{1,0} dot(f32[64,128]{1,0} %Arg_0.1, ...)` — the
# optimized-HLO def line shape this jax's compiled.as_text() emits;
# tuple-shaped results (fusions, ROOT) match the paren alternative
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\([^=]*?\)|[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"([\w\-]+)\(")

_COMM_OPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast", "ragged-all-to-all"})
_HOST_OPS = frozenset({"infeed", "outfeed", "send", "recv"})
# ops that move no bytes of their own (views, metadata)
_FREE_OPS = frozenset({
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "opt-barrier"})
_MXU_CUSTOM_RE = re.compile(r"gemm|matmul|dot|conv|einsum", re.IGNORECASE)
# `replica_groups={{0,1,2,3},{4,5,6,7}}` — the first group's size is the
# collective's participant count (groups are uniform by construction)
_REPLICA_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9, ]+)\}")


def _strip_async(opcode: str) -> str:
    """Base collective opcode: ``all-reduce-start`` → ``all-reduce``."""
    for suf in ("-start", "-done", "-update"):
        if opcode.endswith(suf):
            return opcode[:-len(suf)]
    return opcode


def _shape_stats(shape_str: str) -> Tuple[Optional[int], int, Optional[str]]:
    """(total bytes, total elements, first dtype) of a shape string —
    handles tuples by summing components; bytes is None when any dtype
    is outside the table (token, opaque)."""
    total_b: Optional[int] = 0
    elems = 0
    first_dtype = None
    saw = False
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        saw = True
        if first_dtype is None:
            first_dtype = dtype
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        elems += n
        sz = _DTYPE_BYTES.get(dtype)
        if sz is None or total_b is None:
            total_b = None
        else:
            total_b += n * sz
    if not saw:
        return None, 0, None
    return total_b, elems, first_dtype


def _dims_of(shape_str: str) -> Optional[List[int]]:
    """Dims of a single (non-tuple) shape string, else None."""
    m = _SHAPE_RE.search(shape_str)
    if not m:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


def _operand_region(rest: str) -> str:
    """The text inside the op's call parens (``rest`` starts right after
    the opening paren); trailing attributes are excluded by depth scan."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


def _classify(opcode: str, rest: str) -> Optional[str]:
    base = opcode
    for suf in ("-start", "-done", "-update"):
        if base.endswith(suf):
            base = base[:-len(suf)]
    if base in _FREE_OPS:
        return None
    if base in _COMM_OPS:
        return "comm"
    if base in _HOST_OPS:
        return "host"
    if base in ("dot", "convolution"):
        return "mxu"
    if base == "custom-call":
        m = re.search(r'custom_call_target="([^"]*)"', rest)
        if m and _MXU_CUSTOM_RE.search(m.group(1)):
            return "mxu"
        return "hbm"
    return "hbm"


def _dot_flops(rest: str, operands: str, out_elems: int,
               symtab: Dict[str, str]) -> Optional[float]:
    """Exact dot flops = 2 · out_elems · K, K from the lhs contracting
    dims (``lhs_contracting_dims={1}`` + the lhs shape)."""
    m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", rest)
    if not m:
        return None
    contracting = [int(d) for d in m.group(1).split(",") if d]
    lhs_dims = None
    sm = _SHAPE_RE.search(operands)
    if sm:
        lhs_dims = [int(d) for d in sm.group(2).split(",") if d]
    else:
        rm = re.search(r"%([\w.\-]+)", operands)
        if rm and rm.group(1) in symtab:
            lhs_dims = _dims_of(symtab[rm.group(1)])
    if lhs_dims is None:
        return None
    k = 1.0
    for i in contracting:
        if i >= len(lhs_dims):
            return None
        k *= lhs_dims[i]
    return 2.0 * out_elems * k


def _conv_flops(rest: str, operands: str, out_elems: int,
                symtab: Dict[str, str]) -> Optional[float]:
    """Conv flops = 2 · out_elems · (kernel spatial × in-features) —
    the rhs element count divided by its output-feature dim, located via
    ``dim_labels=b01f_01io->b01f``."""
    m = re.search(r"dim_labels=[0-9a-z]+_([0-9a-z]+)->", rest)
    if not m or "o" not in m.group(1):
        return None
    o_pos = m.group(1).index("o")
    shapes = _SHAPE_RE.findall(operands)
    rhs_dims = None
    if len(shapes) >= 2:
        rhs_dims = [int(d) for d in shapes[1][1].split(",") if d]
    else:
        refs = re.findall(r"%([\w.\-]+)", operands)
        if len(refs) >= 2 and refs[1] in symtab:
            rhs_dims = _dims_of(symtab[refs[1]])
    if rhs_dims is None or o_pos >= len(rhs_dims):
        return None
    k = 1.0
    for i, d in enumerate(rhs_dims):
        if i != o_pos:
            k *= d
    return 2.0 * out_elems * k


def _entry_span(lines: List[str]) -> Tuple[int, int]:
    """(start, end) line indices of the ENTRY computation body; the
    whole text when no ENTRY header is found (already a single block)."""
    start = None
    for i, ln in enumerate(lines):
        if ln.lstrip().startswith("ENTRY ") and "{" in ln:
            start = i
            break
    if start is None:
        return 0, len(lines)
    depth = 0
    for i in range(start, len(lines)):
        depth += lines[i].count("{") - lines[i].count("}")
        if depth <= 0 and i > start:
            return start, i + 1
    return start, len(lines)


def parse_hlo_ops(text: str) -> List[Dict[str, Any]]:
    """Parse optimized-HLO text into per-op records:
    ``{"name", "opcode", "klass", "bytes", "flops", "integer"}``.

    Only the ENTRY computation is walked (fused computations would
    double-count against their fusion op) — except dot/convolution defs,
    which are collected wherever they live so matmuls folded into
    fusions still contribute MXU flops.  ``bytes``/``flops`` are None
    when the line can't be modeled; the fit counts those as
    ``unmodeled`` rather than dropping them silently.
    """
    if not text:
        return []
    lines = text.splitlines()
    matches: List[Tuple[int, Any]] = []
    symtab: Dict[str, str] = {}
    for i, ln in enumerate(lines):
        m = _DEF_RE.match(ln)
        if not m:
            continue
        matches.append((i, m))
        symtab.setdefault(m.group(1), m.group(2))
    lo, hi = _entry_span(lines)
    ops: List[Dict[str, Any]] = []
    seen = set()
    for i, m in matches:
        name, shape_str, opcode = m.group(1), m.group(2), m.group(3)
        in_entry = lo <= i < hi
        if not in_entry and opcode not in ("dot", "convolution"):
            continue
        rest = lines[i][m.end():]
        klass = _classify(opcode, rest)
        if klass is None or name in seen:
            continue
        seen.add(name)
        out_bytes, out_elems, dtype = _shape_stats(shape_str)
        operands = _operand_region(rest)
        op_bytes: Optional[float] = None
        opn_b, _opn_e, _ = _shape_stats(operands)
        if opn_b is None:
            # untyped operands — resolve %refs through the symbol table
            opn_b = 0
            for ref in re.findall(r"%([\w.\-]+)", operands):
                rb, _re_, _rd = _shape_stats(symtab.get(ref, ""))
                if rb is None:
                    opn_b = None
                    break
                opn_b += rb
        if out_bytes is not None and opn_b is not None:
            op_bytes = float(out_bytes + opn_b)
        flops: Optional[float] = None
        if opcode == "dot":
            flops = _dot_flops(rest, operands, out_elems, symtab)
        elif opcode == "convolution":
            flops = _conv_flops(rest, operands, out_elems, symtab)
        participants = None
        if klass == "comm":
            gm = _REPLICA_GROUPS_RE.search(rest)
            if gm:
                ids = [t for t in gm.group(1).replace(" ", "").split(",")
                       if t]
                participants = len(ids) or None
        ops.append({"name": name, "opcode": opcode, "klass": klass,
                    "bytes": op_bytes, "flops": flops,
                    "integer": dtype in _INT_DTYPES,
                    "participants": participants})
    return ops


# --------------------------------------------------------------------------
# roofline fit
# --------------------------------------------------------------------------

def _zero_fit() -> Dict[str, Any]:
    return {"mxu_s": 0.0, "memory_s": 0.0, "flops": 0.0, "bytes": 0.0,
            "comm_bytes": 0.0, "comm_ops": {}, "ops_modeled": 0,
            "ops_unmodeled": 0, "ops_total": 0}


def fit_roofline(ops: List[Dict[str, Any]],
                 spec: Dict[str, Any]) -> Dict[str, Any]:
    """Per-op roofline over a parsed op list: MXU ops contribute
    ``flops/peak`` (int8 peak for integer dots) with any ``bytes/bw``
    excess booked as memory-bound; HBM ops contribute ``bytes/bw``.
    Comm/host op *time* belongs to the measured phase split — only
    their bytes are tallied.  Ops missing shapes/flops are counted
    ``unmodeled``; they never silently vanish."""
    peak_bf16 = float(spec["bf16_tflops"]) * 1e12
    peak_int8 = float(spec["int8_tops"]) * 1e12
    bw = float(spec["hbm_gbps"]) * 1e9
    fit = _zero_fit()
    fit["ops_total"] = len(ops)
    for op in ops:
        klass = op["klass"]
        if klass == "comm":
            fit["comm_bytes"] += op["bytes"] or 0.0
            # per-opcode comm table (ISSUE 20): the interconnect
            # microscope models each collective opcode separately
            base = _strip_async(op["opcode"])
            rec = fit["comm_ops"].setdefault(
                base, {"count": 0, "bytes": 0.0, "participants": None})
            rec["count"] += 1
            rec["bytes"] += op["bytes"] or 0.0
            if op.get("participants"):
                rec["participants"] = max(rec["participants"] or 0,
                                          int(op["participants"]))
            fit["ops_modeled"] += 1
            continue
        if klass == "host":
            fit["ops_modeled"] += 1
            continue
        b, f = op["bytes"], op["flops"]
        if klass == "mxu":
            if f is None or b is None:
                fit["ops_unmodeled"] += 1
                continue
            peak = peak_int8 if op.get("integer") else peak_bf16
            t_flops = f / peak
            t_bytes = b / bw
            fit["mxu_s"] += t_flops
            if t_bytes > t_flops:
                fit["memory_s"] += t_bytes - t_flops
            fit["flops"] += f
            fit["bytes"] += b
            fit["ops_modeled"] += 1
        else:  # hbm
            if b is None:
                fit["ops_unmodeled"] += 1
                continue
            fit["memory_s"] += b / bw
            fit["bytes"] += b
            fit["ops_modeled"] += 1
    return fit


def _normalize_cost_analysis(raw: Any) -> Dict[str, Optional[float]]:
    """Flatten the backend's ``cost_analysis()`` return — a dict, a
    list of one dict (CPU on this jax), or None/garbage — into the three
    totals the roofline cross-checks, with None for missing keys (the
    sparse-key portability contract the tests pin)."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else {}
    if not isinstance(raw, dict):
        raw = {}

    def _num(key: str) -> Optional[float]:
        v = raw.get(key)
        return float(v) if isinstance(v, (int, float)) else None

    return {"flops": _num("flops"),
            "bytes_accessed": _num("bytes accessed"),
            "transcendentals": _num("transcendentals")}


def analyze_program(fn: Any, abstract_args: tuple,
                    abstract_kwargs: Dict[str, Any], *,
                    name: str, spec: Dict[str, Any]) -> Dict[str, Any]:
    """Lower + compile one jitted function at its recorded abstract
    signature and fit the roofline; never raises — failures come back as
    ``error`` with a zero fit (degrade, don't crash the bench)."""
    res: Dict[str, Any] = {"name": name, "error": None, "cost": {},
                           "fit": _zero_fit()}
    inner = getattr(fn, "__wrapped_fn__", fn)
    if not hasattr(inner, "lower"):
        res["error"] = "not lowerable (no .lower)"
        return res
    try:
        compiled = inner.lower(*abstract_args, **abstract_kwargs).compile()
    except Exception as e:  # noqa: BLE001 — degrade per-program
        res["error"] = repr(e)
        return res
    try:
        res["cost"] = _normalize_cost_analysis(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — cost_analysis is optional
        res["cost"] = _normalize_cost_analysis(None)
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — text is optional too
        text = ""
    res["fit"] = fit_roofline(parse_hlo_ops(text), spec)
    return res


# --------------------------------------------------------------------------
# gap budget
# --------------------------------------------------------------------------

def _apply_inflation(buckets: Dict[str, float],
                     measured: float) -> Optional[Dict[str, Any]]:
    """The synthetic drill (``PTPU_ROOFLINE_TEST_INFLATE=<sink>:<frac>``):
    claim ``frac`` of the measured step for the named sink and rescale
    the others so the budget still sums to measured.  Returns the
    ``injected`` marker (honesty: a drilled block is labeled, never
    passed off as a real attribution)."""
    raw = os.environ.get(INFLATE_ENV, "").strip()
    if not raw or measured <= 0:
        return None
    try:
        sink, frac_s = raw.split(":", 1)
        frac = float(frac_s)
    except ValueError:
        return None
    if sink not in buckets:
        return None
    frac = min(max(frac, 0.0), 1.0)
    target = frac * measured
    others = sum(v for k, v in buckets.items() if k != sink)
    scale = max(0.0, (measured - target) / others) if others > 1e-12 else 0.0
    for k in list(buckets):
        if k != sink:
            buckets[k] *= scale
    buckets[sink] = target
    return {"sink": sink, "frac": frac}


def gap_budget(step_p50_ms: float, phases_ms: Dict[str, float], *,
               analyses: Optional[Dict[str, Dict[str, Any]]] = None,
               calls: Optional[Dict[str, int]] = None,
               padding_frac: float = 0.0,
               spec: Optional[Dict[str, Any]] = None,
               degraded: Optional[str] = None) -> Dict[str, Any]:
    """Assemble the MFU-gap budget block for one scenario.

    ``analyses`` maps function name → :func:`analyze_program` result;
    ``calls`` weights multi-program scenarios (serve's prefill buckets +
    decode) by tracker call share, assuming one tracked call per bench
    step.  On an unknown ``device_kind`` the fit is not trusted: the
    compute phase lands in the explicit ``unknown_device`` sink and the
    raw model is still reported under ``programs`` for reference.
    """
    spec = spec or _device_spec()
    measured = float(step_p50_ms or 0.0)
    ph = {p: float((phases_ms or {}).get(p, 0.0) or 0.0)
          for p in ("data", "compute", "readback", "collective")}
    comm_ms = ph["collective"]
    host_ms = ph["data"] + ph["readback"]
    compute_ms = ph["compute"]
    padding_frac = min(max(float(padding_frac or 0.0), 0.0), 1.0)
    padding_ms = padding_frac * compute_ms

    programs: Dict[str, Any] = {}
    comm_ops: Dict[str, Dict[str, Any]] = {}
    model_mxu_s = model_mem_s = 0.0
    ops_modeled = ops_unmodeled = 0
    analyses = analyses or {}
    total_calls = sum(max(0, int((calls or {}).get(n, 0)))
                      for n in analyses)
    for name in sorted(analyses):
        a = analyses[name]
        c = max(0, int((calls or {}).get(name, 0)))
        share = (c / total_calls) if total_calls else 1.0 / len(analyses)
        fit = a.get("fit") or _zero_fit()
        model_mxu_s += share * fit["mxu_s"]
        model_mem_s += share * fit["memory_s"]
        ops_modeled += fit["ops_modeled"]
        ops_unmodeled += fit["ops_unmodeled"]
        # call-share-weighted per-opcode comm table (ISSUE 20): bytes a
        # step ships per HLO collective opcode, for the interconnect
        # microscope's exposed-vs-overlapped estimate
        for opcode, rec in (fit.get("comm_ops") or {}).items():
            agg = comm_ops.setdefault(
                opcode, {"count": 0, "bytes": 0.0, "participants": None})
            agg["count"] += int(rec.get("count") or 0)
            agg["bytes"] += share * float(rec.get("bytes") or 0.0)
            if rec.get("participants"):
                agg["participants"] = max(agg["participants"] or 0,
                                          int(rec["participants"]))
        cost = a.get("cost") or {}
        programs[name] = {
            "calls": c, "share": round(share, 4),
            "flops": fit["flops"], "bytes": fit["bytes"],
            "mxu_ms": round(fit["mxu_s"] * 1e3, 6),
            "memory_ms": round(fit["memory_s"] * 1e3, 6),
            "ops_modeled": fit["ops_modeled"],
            "ops_unmodeled": fit["ops_unmodeled"],
            "cost_flops": cost.get("flops"),
            "cost_bytes": cost.get("bytes_accessed"),
            "error": a.get("error"),
        }

    model_mxu_ms = model_mxu_s * 1e3
    model_mem_ms = model_mem_s * 1e3
    if spec.get("known"):
        buckets = {"mxu": model_mxu_ms, "memory_bound": model_mem_ms,
                   "comm": comm_ms, "host": host_ms,
                   "padding": padding_ms, "unknown_device": 0.0}
    else:
        buckets = {"mxu": 0.0, "memory_bound": 0.0,
                   "comm": comm_ms, "host": host_ms,
                   "padding": padding_ms,
                   "unknown_device": max(0.0, compute_ms - padding_ms)}
    injected = _apply_inflation(buckets, measured)
    residual = measured - sum(buckets.values())
    buckets["residual"] = residual
    coverage = (1.0 - min(1.0, abs(residual) / measured)
                if measured > 0 else 0.0)
    candidates = {k: v for k, v in buckets.items() if k != "mxu"}
    dominant = (max(candidates, key=lambda k: candidates[k])
                if candidates and max(candidates.values()) > 0
                else "residual")
    block = {
        "device": {k: spec.get(k) for k in
                   ("device_kind", "gen", "known", "bf16_tflops",
                    "int8_tops", "hbm_gbps")},
        "measured_step_ms": round(measured, 6),
        # the roofline prediction: modeled compute + the measured
        # comm/host phases (nominal-peak extrapolation when known=False)
        "modeled_step_ms": round(
            model_mxu_ms + model_mem_ms + comm_ms + host_ms, 6),
        "buckets_ms": {k: round(v, 6) for k, v in buckets.items()},
        "coverage": round(coverage, 6),
        "dominant_sink": dominant,
        "padding_frac": round(padding_frac, 6),
        "ops": {"modeled": ops_modeled, "unmodeled": ops_unmodeled},
        "comm_ops": comm_ops,
        "programs": programs,
        "injected": injected,
        "degraded": degraded,
    }
    return block


def degraded_block(step_p50_ms: float, phases_ms: Dict[str, float], *,
                   padding_frac: float = 0.0,
                   reason: str = "no compiled-program capture",
                   spec: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """A schema-valid gap budget with no compiled-program model — the
    phase split carries all attribution.  ``schema.new_row`` synthesizes
    this when a caller passes no roofline block, so every v2 row sums to
    measured even from producers that never opened a capture window."""
    return gap_budget(step_p50_ms, phases_ms, analyses=None, calls=None,
                      padding_frac=padding_frac, spec=spec,
                      degraded=reason)


# --------------------------------------------------------------------------
# the observatory (track_jit hook target)
# --------------------------------------------------------------------------

def abstractify(args: tuple, kwargs: Dict[str, Any]) -> Tuple[tuple, dict]:
    """Shape-and-dtype skeleton of a call's arguments — taken *before*
    the call (donated buffers are unreadable after), cheap (no device
    sync), and sufficient for a later ``fn.lower()``."""
    import jax

    def to_abstract(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            try:
                return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
            except Exception:  # noqa: BLE001 — keep the odd leaf as-is
                return x
        return x

    return (jax.tree_util.tree_map(to_abstract, tuple(args)),
            jax.tree_util.tree_map(to_abstract, dict(kwargs)))


class RooflineObservatory:
    """Bounded registry of (function, abstract signature) pairs seen by
    ``track_jit`` while a capture window is open.  Nothing is lowered or
    compiled at record time — :meth:`analyses` does that lazily, outside
    any timed region."""

    def __init__(self, limit: int = 32):
        self._lock = threading.Lock()
        self._limit = int(limit)
        self._entries: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()

    def record(self, name: str, fn: Any, abstract_args: tuple,
               abstract_kwargs: Dict[str, Any], *,
               sig_key: int = 0, miss: bool = False) -> None:
        """One tracked call: remember the newest abstract signature per
        function name; on a compile miss, honor ``PTPU_HLO_DUMP_DIR``."""
        with self._lock:
            self._entries[name] = {
                "fn": fn, "args": abstract_args, "kwargs": abstract_kwargs,
                "sig_key": int(sig_key), "ts": time.time()}
            self._entries.move_to_end(name)
            while len(self._entries) > self._limit:
                self._entries.popitem(last=False)
        if miss:
            d = hlo_dump_dir()
            if d:
                try:
                    dump_hlo(d, name, fn, abstract_args, abstract_kwargs,
                             sig_key)
                except Exception as e:  # noqa: BLE001 — dump is best-effort
                    from ..framework.log import vlog
                    vlog(1, "observability: hlo dump failed for %s: %r",
                         name, e)

    def entries(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def analyses(self, spec: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Dict[str, Any]]:
        """Lower + compile every recorded program and fit the roofline;
        one entry per function name, errors included (never raises)."""
        spec = spec or _device_spec()
        out: Dict[str, Dict[str, Any]] = {}
        for name, e in self.entries().items():
            out[name] = analyze_program(e["fn"], e["args"], e["kwargs"],
                                        name=name, spec=spec)
        return out


_obs_lock = threading.Lock()
_observatory: Optional[RooflineObservatory] = None


def get_observatory() -> RooflineObservatory:
    """The process-global observatory (mirrors ``get_tracker``)."""
    global _observatory
    with _obs_lock:
        if _observatory is None:
            _observatory = RooflineObservatory()
        return _observatory


def reset_observatory() -> None:
    """Disable and clear all captured state (tests)."""
    obs = get_observatory()
    obs.disable()
    obs.reset()


def capture_active() -> bool:
    """Cheap per-call gate for the ``track_jit`` hook: abstract shapes
    are only captured while a window is open or HLO dumping is on."""
    return bool((_observatory is not None and _observatory.enabled)
                or hlo_dump_dir())


class capture_window:
    """Scoped observatory enablement — the bench runner brackets each
    scenario with one and asks it for the row's ``roofline`` block:

    >>> with capture_window() as rw:
    ...     payload = scenario(mode)
    >>> block = rw.build_block(p50_ms, phases_ms, padding_frac=0.0)
    """

    def __init__(self, spec: Optional[Dict[str, Any]] = None):
        self._spec = spec

    def __enter__(self) -> "capture_window":
        obs = get_observatory()
        obs.reset()
        obs.enable()
        return self

    def __exit__(self, *exc) -> None:
        get_observatory().disable()

    def build_block(self, step_p50_ms: float,
                    phases_ms: Dict[str, float], *,
                    padding_frac: float = 0.0,
                    calls: Optional[Dict[str, int]] = None
                    ) -> Dict[str, Any]:
        spec = self._spec or _device_spec()
        obs = get_observatory()
        analyses = obs.analyses(spec)
        if not analyses:
            return degraded_block(step_p50_ms, phases_ms,
                                  padding_frac=padding_frac,
                                  reason="no jitted step captured",
                                  spec=spec)
        if calls is None:
            from .compilation import get_tracker
            tr = get_tracker()
            calls = {n: tr.stats(n)["calls"] for n in analyses}
        return gap_budget(step_p50_ms, phases_ms, analyses=analyses,
                          calls=calls, padding_frac=padding_frac,
                          spec=spec)


# --------------------------------------------------------------------------
# HLO dumping (satellite: PTPU_HLO_DUMP_DIR)
# --------------------------------------------------------------------------

def hlo_dump_dir() -> Optional[str]:
    d = os.environ.get(HLO_DUMP_ENV, "").strip()
    return d or None


def hlo_dump_keep() -> int:
    """Newest-N bound on dumped jit entries (pairs of files), mirroring
    the fleet journal's ``PTPU_FLEET_JOURNAL_KEEP`` doctrine."""
    try:
        return max(1, int(os.environ.get(HLO_DUMP_KEEP_ENV,
                                         str(DEFAULT_HLO_DUMP_KEEP))))
    except ValueError:
        return DEFAULT_HLO_DUMP_KEEP


def dump_hlo(dump_dir: str, name: str, fn: Any, abstract_args: tuple,
             abstract_kwargs: Dict[str, Any],
             sig_key: int) -> Optional[str]:
    """Write ``<name>-<sigkey>.lowered.txt`` + ``.compiled.txt`` for one
    jit entry — the filename key is the PR 4 signature-cache key
    (``hash(tuple(sigs))``), so one file pair per distinct trace.
    Returns the stem, or None when ``fn`` isn't lowerable."""
    inner = getattr(fn, "__wrapped_fn__", fn)
    if not hasattr(inner, "lower"):
        return None
    os.makedirs(dump_dir, exist_ok=True)
    safe = re.sub(r"[^\w.\-]+", "_", str(name)) or "fn"
    stem = "%s-%016x" % (safe, sig_key & 0xFFFFFFFFFFFFFFFF)
    from ..utils import fsio
    lowered = inner.lower(*abstract_args, **abstract_kwargs)
    fsio.atomic_write_bytes(os.path.join(dump_dir, stem + ".lowered.txt"),
                            lowered.as_text().encode("utf-8"))
    fsio.atomic_write_bytes(os.path.join(dump_dir, stem + ".compiled.txt"),
                            lowered.compile().as_text().encode("utf-8"))
    _gc_dumps(dump_dir, hlo_dump_keep())
    return stem


def _gc_dumps(dump_dir: str, keep: int) -> None:
    """Drop all but the newest ``keep`` dumped entries (by mtime of the
    newest file in each pair)."""
    try:
        names = os.listdir(dump_dir)
    except OSError:
        return
    stems: Dict[str, List[Any]] = {}
    for n in names:
        for suf in (".lowered.txt", ".compiled.txt"):
            if n.endswith(suf):
                stem = n[:-len(suf)]
                p = os.path.join(dump_dir, n)
                try:
                    mt = os.path.getmtime(p)
                except OSError:
                    continue
                cur = stems.setdefault(stem, [0.0, []])
                cur[0] = max(cur[0], mt)
                cur[1].append(p)
    if len(stems) <= keep:
        return
    ordered = sorted(stems.items(), key=lambda kv: kv[1][0], reverse=True)
    for _stem, (_mt, paths) in ordered[keep:]:
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass


# --------------------------------------------------------------------------
# CLI: ledger reconciliation check (the CI perf-tier gate)
# --------------------------------------------------------------------------

def _format_gap_table(by_scenario: Dict[str, Dict[str, Any]]) -> str:
    lines = ["MFU-gap budgets (newest row per scenario, ms/step):"]
    cols = [s for s in SINKS]
    header = "  %-14s %9s " % ("scenario", "measured")
    header += " ".join("%12s" % c for c in cols)
    header += "  %8s %s" % ("coverage", "dominant")
    lines.append(header)
    for name in sorted(by_scenario):
        rl = by_scenario[name]
        b = rl.get("buckets_ms") or {}
        line = "  %-14s %9.2f " % (name, rl.get("measured_step_ms") or 0.0)
        line += " ".join("%12.3f" % float(b.get(c) or 0.0) for c in cols)
        line += "  %8.3f %s" % (float(rl.get("coverage") or 0.0),
                                rl.get("dominant_sink"))
        if rl.get("injected"):
            line += "  [injected drill]"
        lines.append(line)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m paddle_tpu.observability.roofline`` — print the gap
    table for the newest ledger row per scenario and fail when any
    row's reconciliation residual exceeds the bound (or lacks a
    roofline block entirely)."""
    import argparse

    from ..bench import ledger as bench_ledger

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.roofline",
        description="modeled-vs-measured reconciliation over the ledger")
    p.add_argument("--ledger", default=None, help="ledger path "
                   "(default benchmarks/ledger.jsonl)")
    p.add_argument("--mode", default="smoke", choices=("smoke", "full"))
    p.add_argument("--max-residual-frac", type=float, default=None,
                   help="|residual| bound as a fraction of measured "
                        "step time (default from golden thresholds)")
    args = p.parse_args(argv)
    drops: Dict[str, int] = {}
    rows = bench_ledger.read_ledger(args.ledger, drops=drops)
    if any(drops.values()):
        print("ledger drops: %s" % drops)  # noqa: print — CLI report
    frac = args.max_residual_frac
    if frac is None:
        frac = bench_ledger.threshold(bench_ledger.load_golden(),
                                      "roofline_max_residual_frac")
    newest: Dict[str, Dict[str, Any]] = {}
    for row in rows:
        if row.get("mode") != args.mode:
            continue
        if not isinstance(row.get("scenario"), str):
            continue
        newest[row["scenario"]] = row  # ledger order: newest last wins
    if not newest:
        print("no %s rows in ledger" % args.mode)  # noqa: print — CLI report
        return 1
    failures: List[str] = []
    table: Dict[str, Dict[str, Any]] = {}
    for name, row in newest.items():
        rl = row.get("roofline")
        if not isinstance(rl, dict):
            failures.append("%s: no roofline block (schema v%s row)"
                            % (name, row.get("schema_version")))
            continue
        table[name] = rl
        measured = float(rl.get("measured_step_ms") or 0.0)
        residual = float((rl.get("buckets_ms") or {}).get("residual")
                         or 0.0)
        if measured > 0 and abs(residual) > frac * measured:
            failures.append(
                "%s: |residual| %.3fms exceeds %.0f%% of measured "
                "%.3fms" % (name, abs(residual), 100 * frac, measured))
    print(_format_gap_table(table))  # noqa: print — CLI report
    if failures:
        print("RECONCILIATION FAILURES (bound %.0f%%):"  # noqa: print — CLI report
              % (100 * frac))
        for f in failures:
            print("  " + f)  # noqa: print — CLI report
        return 1
    print("reconciliation OK: %d scenario(s) within %.0f%% residual"  # noqa: print — CLI report
          % (len(table), 100 * frac))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
