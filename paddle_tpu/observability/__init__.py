"""Unified telemetry layer (ISSUE 3).

Before this package, run health lived in four unrelated channels — VLOG
lines (``framework/log.py``), the XPlane profiler wrapper, the
supervisor's JSON report, heartbeat files — and the only MFU number came
from ``bench.py``'s one-shot harness.  This package is the shared spine
they all report through:

- :mod:`registry` — process-wide counters / gauges / bounded-reservoir
  histograms, thread-safe, near-zero cost when no sink is attached;
- :mod:`tracing` — nesting ``span()`` context managers that feed the
  profiler's host annotations, an aggregated span tree, and a
  chrome-trace exporter;
- :mod:`sinks` — the run-scoped JSONL ``MetricsWriter`` (fsync'd via
  ``utils/fsio``), a periodic stderr summary line, and a Prometheus
  textfile exporter;
- :mod:`mfu` — the peak-TFLOPs table and FLOPs-per-token math shared by
  ``bench.py`` and the live per-step MFU in ``hapi.Model.fit``;
- :mod:`aggregate` — merges ``<run_dir>/metrics/worker-*.jsonl`` into
  ``summary.json`` (driven by ``launch --run_dir``), including the
  cross-worker straggler skew stats;
- :mod:`compilation` — compile/retrace tracking (ISSUE 4):
  :func:`track_jit` signature cache, per-argument retrace diffs and
  storm detection naming the shape-churning argument;
- :mod:`memory` — per-step HBM watermark sampling from PJRT
  ``memory_stats()`` (``PTPU_MEM_SAMPLE_EVERY``) + the OOM postmortem;
- :mod:`doctor` — ``python -m paddle_tpu.observability.doctor
  <run_dir>``: ranked ``diagnosis.json`` (retrace storm / HBM creep /
  straggler / data-starved) with evidence, mirrored into the
  supervisor report;
- :mod:`monitor` — the live layer (ISSUE 5): per-worker
  :class:`~paddle_tpu.observability.monitor.StatusServer`
  (``/metrics`` ``/statusz`` ``/healthz``, started by the supervisor
  when ``PTPU_MONITOR_PORT`` is set) and the
  :class:`~paddle_tpu.observability.monitor.LiveAggregator` that
  tail-reads still-growing worker streams, re-runs the doctor's rules
  on a sliding window, and raises ``monitor.alert`` records mid-run;
- :mod:`flight` — the crash flight recorder: a bounded ring of the
  newest records (``PTPU_FLIGHT_BUFFER``), dumped to
  ``<run_dir>/flight/worker-<i>.json`` on signals/atexit/fault paths
  and ingested by the doctor when the JSONL tail was lost;
- :mod:`roofline` — the MFU microscope (ISSUE 19): per-program
  ``cost_analysis()`` + parsed HLO captured for every jitted step the
  compile tracker sees (:class:`~paddle_tpu.observability.roofline
  .RooflineObservatory`), fitted against the per-``device_kind``
  roofline (:func:`~paddle_tpu.observability.mfu.device_spec`) into a
  modeled step time and an **MFU-gap budget** with named sinks
  (memory-bound, exposed comm, host gaps, padding waste, unknown
  device, residual); lands in every bench row (schema v2), feeds the
  doctor's ``mfu_gap`` verdict and the ``/statusz`` roofline section
  (knobs ``PTPU_HLO_DUMP_DIR``, ``PTPU_HLO_DUMP_KEEP``,
  ``PTPU_ROOFLINE_TEST_INFLATE``);
- :mod:`requesttrace` — fleet request tracing (ISSUE 18): per-request
  ``trace.span`` waterfalls stitched across router + replicas + WAL
  by :class:`~paddle_tpu.observability.requesttrace.TraceAssembler`
  (``python -m paddle_tpu.observability.requesttrace <run_dir>``),
  with tail-latency attribution feeding the doctor's ``tail_latency``
  verdict (knobs ``PTPU_TRACE_REQUESTS``, ``PTPU_TRACE_SAMPLE``).

Emitters across the stack (hapi step breakdown, collective latencies,
supervisor events) talk to :func:`get_registry` unconditionally; records
flow only when a sink is attached — by the run supervisor under its
``run_dir``, by ``PTPU_METRICS_DIR``, or explicitly via ``add_sink``.

Env knobs: ``PTPU_METRICS_DIR`` (auto-attach a JSONL writer),
``PTPU_METRICS_INTERVAL`` (sink flush/summary period, default 30s),
``PTPU_TRACE_BUFFER`` (span buffer bound, default 65536),
``PTPU_MEM_SAMPLE_EVERY`` (HBM watermark cadence, default 16 steps),
``PTPU_COMPILE_CACHE_DIR`` (persistent compile cache, :mod:`compilecache`).
See docs/ARCHITECTURE.md "Telemetry" and "Run doctor".
"""
from __future__ import annotations

from .aggregate import (StreamTail, aggregate_run, read_worker_stream,
                        straggler_stats)
from .compilation import (CompileTracker, arg_signature, diff_signatures,
                          get_tracker, track_jit)
from .compilecache import maybe_enable_persistent_cache, persistent_cache_dir
from .doctor import diagnose, render_report
from .flight import FlightRecorder, flight_dir, read_flight_bundles
from .memory import (MemorySampler, get_sampler, is_oom_error,
                     oom_postmortem)
from .mfu import (PEAK_TFLOPS, flops_per_token, mfu, param_count,
                  peak_flops_per_sec, readback_sync)
from .monitor import (LiveAggregator, StatusServer,
                      default_monitor_interval, live_status_path,
                      maybe_start_server)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       get_registry, split_labels)
from .mfu import DEVICE_SPECS, device_spec
from .requesttrace import (TraceAssembler, assemble_run, component_bucket,
                           mint_trace_id, tail_latency_attribution)
from .roofline import (RooflineObservatory, capture_window, degraded_block,
                       gap_budget, get_observatory, parse_hlo_ops)
from .sinks import (MetricsWriter, PrometheusTextfile, StderrSummary,
                    default_interval, metrics_dir, render_prometheus)
from .tracing import (export_chrome_trace, reset_tracing, span,
                      span_tree_totals, trace_events)

__all__ = [
    # registry
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "split_labels",
    # tracing
    "span", "span_tree_totals", "export_chrome_trace", "trace_events",
    "reset_tracing",
    # sinks
    "MetricsWriter", "StderrSummary", "PrometheusTextfile", "metrics_dir",
    "default_interval", "render_prometheus",
    # mfu
    "PEAK_TFLOPS", "peak_flops_per_sec", "param_count", "flops_per_token",
    "mfu", "readback_sync",
    # aggregation
    "aggregate_run", "read_worker_stream", "straggler_stats", "StreamTail",
    # live monitor (ISSUE 5)
    "StatusServer", "LiveAggregator", "maybe_start_server",
    "default_monitor_interval", "live_status_path",
    # flight recorder (ISSUE 5)
    "FlightRecorder", "flight_dir", "read_flight_bundles",
    # compile/retrace tracking (ISSUE 4)
    "CompileTracker", "arg_signature", "diff_signatures", "get_tracker",
    "track_jit",
    # persistent compile cache (ISSUE 13 / ROADMAP 5a)
    "maybe_enable_persistent_cache", "persistent_cache_dir",
    # memory watermarks (ISSUE 4)
    "MemorySampler", "get_sampler", "is_oom_error", "oom_postmortem",
    # run doctor (ISSUE 4)
    "diagnose", "render_report",
    # request tracing (ISSUE 18) — the chrome exporter stays module-
    # scoped (requesttrace.export_chrome_trace) to avoid shadowing the
    # in-process tracing exporter above
    "TraceAssembler", "assemble_run", "tail_latency_attribution",
    "mint_trace_id", "component_bucket",
    # MFU microscope (ISSUE 19) — note `mfu` above is the *function*;
    # the device table lives in the mfu module, re-exported here
    "DEVICE_SPECS", "device_spec",
    "RooflineObservatory", "get_observatory", "capture_window",
    "gap_budget", "degraded_block", "parse_hlo_ops",
]
