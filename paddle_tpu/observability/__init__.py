"""Unified telemetry layer (ISSUE 3).

Before this package, run health lived in four unrelated channels — VLOG
lines (``framework/log.py``), the XPlane profiler wrapper, the
supervisor's JSON report, heartbeat files — and the only MFU number came
from ``bench.py``'s one-shot harness.  This package is the shared spine
they all report through:

- :mod:`registry` — process-wide counters / gauges / bounded-reservoir
  histograms, thread-safe, near-zero cost when no sink is attached;
- :mod:`tracing` — nesting ``span()`` context managers that feed the
  profiler's host annotations, an aggregated span tree, and a
  chrome-trace exporter;
- :mod:`sinks` — the run-scoped JSONL ``MetricsWriter`` (fsync'd via
  ``utils/fsio``), a periodic stderr summary line, and a Prometheus
  textfile exporter;
- :mod:`mfu` — the peak-TFLOPs table and FLOPs-per-token math shared by
  ``bench.py`` and the live per-step MFU in ``hapi.Model.fit``;
- :mod:`aggregate` — merges ``<run_dir>/metrics/worker-*.jsonl`` into
  ``summary.json`` (driven by ``launch --run_dir``).

Emitters across the stack (hapi step breakdown, collective latencies,
supervisor events) talk to :func:`get_registry` unconditionally; records
flow only when a sink is attached — by the run supervisor under its
``run_dir``, by ``PTPU_METRICS_DIR``, or explicitly via ``add_sink``.

Env knobs: ``PTPU_METRICS_DIR`` (auto-attach a JSONL writer),
``PTPU_METRICS_INTERVAL`` (sink flush/summary period, default 30s),
``PTPU_TRACE_BUFFER`` (span buffer bound, default 65536).
See docs/ARCHITECTURE.md "Telemetry".
"""
from __future__ import annotations

from .aggregate import aggregate_run, read_worker_stream
from .mfu import (PEAK_TFLOPS, flops_per_token, mfu, param_count,
                  peak_flops_per_sec, readback_sync)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       get_registry)
from .sinks import (MetricsWriter, PrometheusTextfile, StderrSummary,
                    default_interval, metrics_dir)
from .tracing import (export_chrome_trace, reset_tracing, span,
                      span_tree_totals, trace_events)

__all__ = [
    # registry
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    # tracing
    "span", "span_tree_totals", "export_chrome_trace", "trace_events",
    "reset_tracing",
    # sinks
    "MetricsWriter", "StderrSummary", "PrometheusTextfile", "metrics_dir",
    "default_interval",
    # mfu
    "PEAK_TFLOPS", "peak_flops_per_sec", "param_count", "flops_per_token",
    "mfu", "readback_sync",
    # aggregation
    "aggregate_run", "read_worker_stream",
]
