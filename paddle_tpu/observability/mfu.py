"""MFU accounting helpers (ISSUE 3) — extracted from ``bench.py`` so the
one-shot benchmark and the live per-step telemetry share one definition
of "model FLOPs utilization".

Two halves:

- the **denominator**: :func:`peak_flops_per_sec` — bf16 peak matmul
  TFLOPs per chip by TPU generation (public specs), with the
  ``PALLAS_AXON_TPU_GEN`` env override and a nominal v5e figure for CPU
  dev environments so the math always produces a number;
- the **numerator**: :func:`flops_per_token` — the standard 6N
  fwd+bwd matmul estimate plus the attention term
  ``12·L·h·S`` per token (halved when causal), exactly the formula the
  benchmark has always used.

Timing methodology note (shared with ``bench.py``): on tunneled TPU
platforms ``block_until_ready`` returns at *dispatch*, not completion —
a host readback is the only true synchronization.  :func:`readback_sync`
is that readback; hapi's step breakdown times it as the "readback"
component, which on TPU absorbs the device compute the dispatch call
didn't wait for.
"""
from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["PEAK_TFLOPS", "DEVICE_SPECS", "device_spec",
           "peak_flops_per_sec", "param_count",
           "flops_per_token", "mfu", "readback_sync"]

# Per-chip roofline specs by TPU generation (public datasheet figures):
# bf16 peak matmul TFLOPs, int8 peak TOPs, and peak HBM bandwidth in
# GB/s.  The bandwidth column is what turns the MFU table into a
# roofline — machine balance (flops/byte at the ridge point) falls
# straight out of bf16_tflops / hbm_gbps.
DEVICE_SPECS = {
    "v2":  {"bf16_tflops": 46.0,   "int8_tops": 46.0,   "hbm_gbps": 700.0},
    "v3":  {"bf16_tflops": 123.0,  "int8_tops": 123.0,  "hbm_gbps": 900.0},
    "v4":  {"bf16_tflops": 275.0,  "int8_tops": 275.0,  "hbm_gbps": 1228.0},
    "v5e": {"bf16_tflops": 197.0,  "int8_tops": 394.0,  "hbm_gbps": 819.0},
    "v5p": {"bf16_tflops": 459.0,  "int8_tops": 918.0,  "hbm_gbps": 2765.0},
    "v6e": {"bf16_tflops": 918.0,  "int8_tops": 1836.0, "hbm_gbps": 1640.0},
}

# bf16 peak matmul TFLOPs per chip — kept as a derived view so every
# pre-roofline caller (bench.py, hapi live MFU) keeps working unchanged.
PEAK_TFLOPS = {gen: spec["bf16_tflops"] for gen, spec in DEVICE_SPECS.items()}

# Nominal spec used when the device kind is not in the table (CPU dev
# boxes, future TPU generations): MFU math still produces a number, but
# roofline attribution routes the whole compute phase into the explicit
# "unknown_device" sink instead of pretending the fit is meaningful.
_NOMINAL_GEN = "v5e"


def device_spec(device_kind: Optional[str] = None) -> dict:
    """Resolve a device kind to its roofline spec.

    Returns a dict with ``device_kind``, ``gen``, ``known`` plus the
    ``bf16_tflops`` / ``int8_tops`` / ``hbm_gbps`` columns.  Unknown
    kinds come back with ``known=False``, ``gen=None`` and nominal
    figures — callers that attribute time (the roofline) must surface
    that as an explicit ``"unknown_device"`` sink rather than silently
    skipping attribution.  ``PALLAS_AXON_TPU_GEN`` overrides the lookup
    the same way it always has for :func:`peak_flops_per_sec`.
    """
    if device_kind is None:
        import jax
        device_kind = getattr(jax.devices()[0], "device_kind", "")
    kind = (device_kind or "").lower()
    for gen, spec in DEVICE_SPECS.items():
        if gen in kind:
            return {"device_kind": device_kind, "gen": gen, "known": True,
                    **spec}
    env_gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if env_gen in DEVICE_SPECS:
        return {"device_kind": device_kind, "gen": env_gen, "known": True,
                **DEVICE_SPECS[env_gen]}
    return {"device_kind": device_kind, "gen": None, "known": False,
            **DEVICE_SPECS[_NOMINAL_GEN]}


def peak_flops_per_sec() -> float:
    """Peak bf16 FLOP/s of the first visible device (nominal v5e figure
    on CPU so dev-box MFU numbers exist — they are labelled by the
    device field every step record carries)."""
    return device_spec()["bf16_tflops"] * 1e12


def param_count(params: Any) -> int:
    """Total element count of a parameter pytree."""
    import jax
    import numpy as np
    return sum(int(np.prod(v.shape))
               for v in jax.tree_util.tree_leaves(params))


def flops_per_token(n_params: int, num_layers: Optional[int] = None,
                    hidden_size: Optional[int] = None,
                    seq_len: Optional[int] = None,
                    causal: bool = True, fwd_only: bool = False) -> float:
    """Train-step (fwd+bwd) FLOPs per token: 6N for the matmuls, plus the
    attention term ``12·L·h·S`` when the transformer shape is known
    (halved for causal masking).  With no shape info this degrades to
    the plain 6N estimate — still the right order for MLPs/CNNs.

    ``fwd_only=True`` divides by 3 (2N + fwd attention) — the serving /
    decode estimate ``bench_serve`` and the engine MFU line share."""
    total = 6.0 * float(n_params)
    if num_layers and hidden_size and seq_len:
        attn = 12.0 * num_layers * hidden_size * seq_len
        total += attn / 2.0 if causal else attn
    return total / 3.0 if fwd_only else total


def mfu(tokens_per_sec: float, flops_token: float,
        peak: Optional[float] = None) -> float:
    """Achieved / peak FLOP throughput."""
    return tokens_per_sec * flops_token / (peak or peak_flops_per_sec())


def readback_sync(x) -> float:
    """Host readback of a scalar — the only true device synchronization
    on platforms where ``block_until_ready`` returns at dispatch."""
    return float(x)
