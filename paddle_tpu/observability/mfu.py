"""MFU accounting helpers (ISSUE 3) — extracted from ``bench.py`` so the
one-shot benchmark and the live per-step telemetry share one definition
of "model FLOPs utilization".

Two halves:

- the **denominator**: :func:`peak_flops_per_sec` — bf16 peak matmul
  TFLOPs per chip by TPU generation (public specs), with the
  ``PALLAS_AXON_TPU_GEN`` env override and a nominal v5e figure for CPU
  dev environments so the math always produces a number;
- the **numerator**: :func:`flops_per_token` — the standard 6N
  fwd+bwd matmul estimate plus the attention term
  ``12·L·h·S`` per token (halved when causal), exactly the formula the
  benchmark has always used.

Timing methodology note (shared with ``bench.py``): on tunneled TPU
platforms ``block_until_ready`` returns at *dispatch*, not completion —
a host readback is the only true synchronization.  :func:`readback_sync`
is that readback; hapi's step breakdown times it as the "readback"
component, which on TPU absorbs the device compute the dispatch call
didn't wait for.
"""
from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["PEAK_TFLOPS", "peak_flops_per_sec", "param_count",
           "flops_per_token", "mfu", "readback_sync"]

# bf16 peak matmul TFLOPs per chip by TPU generation (public specs);
# CPU fallback uses a nominal figure so the math still runs in dev envs.
PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}


def peak_flops_per_sec() -> float:
    """Peak bf16 FLOP/s of the first visible device (nominal v5e figure
    on CPU so dev-box MFU numbers exist — they are labelled by the
    device field every step record carries)."""
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    for gen, tf in PEAK_TFLOPS.items():
        if gen in kind:
            return tf * 1e12
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if gen in PEAK_TFLOPS:
        return PEAK_TFLOPS[gen] * 1e12
    return PEAK_TFLOPS["v5e"] * 1e12


def param_count(params: Any) -> int:
    """Total element count of a parameter pytree."""
    import jax
    import numpy as np
    return sum(int(np.prod(v.shape))
               for v in jax.tree_util.tree_leaves(params))


def flops_per_token(n_params: int, num_layers: Optional[int] = None,
                    hidden_size: Optional[int] = None,
                    seq_len: Optional[int] = None,
                    causal: bool = True, fwd_only: bool = False) -> float:
    """Train-step (fwd+bwd) FLOPs per token: 6N for the matmuls, plus the
    attention term ``12·L·h·S`` when the transformer shape is known
    (halved for causal masking).  With no shape info this degrades to
    the plain 6N estimate — still the right order for MLPs/CNNs.

    ``fwd_only=True`` divides by 3 (2N + fwd attention) — the serving /
    decode estimate ``bench_serve`` and the engine MFU line share."""
    total = 6.0 * float(n_params)
    if num_layers and hidden_size and seq_len:
        attn = 12.0 * num_layers * hidden_size * seq_len
        total += attn / 2.0 if causal else attn
    return total / 3.0 if fwd_only else total


def mfu(tokens_per_sec: float, flops_token: float,
        peak: Optional[float] = None) -> float:
    """Achieved / peak FLOP throughput."""
    return tokens_per_sec * flops_token / (peak or peak_flops_per_sec())


def readback_sync(x) -> float:
    """Host readback of a scalar — the only true device synchronization
    on platforms where ``block_until_ready`` returns at dispatch."""
    return float(x)
