"""Persistent compilation cache (ISSUE 13 satellite; ROADMAP 5a).

jax can persist compiled executables to disk so a *second process* with
the same program shapes skips XLA entirely — on real pods that turns a
multi-minute cold start into seconds.  This module is the one switch:

- ``PTPU_COMPILE_CACHE_DIR=/path`` enables the cache; unset leaves jax
  untouched (the cache is opt-in, never a surprise write to disk);
- the min-compile-time floor is zeroed so even tiny functions persist —
  without this the smoke-sized tests/benches would never populate the
  cache and the warm-start guarantee would be untestable;
- disk hit/miss traffic is surfaced as registry counters
  ``compile.persistent_cache_hits`` / ``compile.persistent_cache_requests``
  via jax's monitoring events, so the PR 4 compile tracker's in-process
  view (calls − traces) composes with the cross-process view: a warm
  start shows ``persistent_hits == persistent_requests > 0`` while the
  tracker still counts one trace per function.

Call sites: ``jit.to_static``, ``hapi.Model.prepare`` and the bench
runner — i.e. every place the framework is about to hand jax a program
worth caching.  The call is idempotent and cheap when the knob is unset.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

__all__ = ["maybe_enable_persistent_cache", "persistent_cache_dir",
           "reset_for_tests"]

_lock = threading.Lock()
_state = {"configured": False, "dir": None, "listener": False}

# jax monitoring event names (stable across the 0.4.x line; the listener
# ignores anything else so a rename degrades to zero counters, not a crash)
_EV_HIT = "/jax/compilation_cache/cache_hits"
_EV_REQ = "/jax/compilation_cache/compile_requests_use_cache"


def persistent_cache_dir() -> Optional[str]:
    """The directory the cache was enabled with (None = disabled)."""
    return _state["dir"]


def _listener(event: str, **kwargs) -> None:
    if event not in (_EV_HIT, _EV_REQ):
        return
    from .registry import get_registry
    reg = get_registry()
    if event == _EV_HIT:
        reg.counter("compile.persistent_cache_hits").inc()
    else:
        reg.counter("compile.persistent_cache_requests").inc()


def maybe_enable_persistent_cache(registry=None) -> Optional[str]:
    """Enable jax's persistent compilation cache if
    ``PTPU_COMPILE_CACHE_DIR`` is set.  Idempotent; returns the cache
    dir in effect (None = knob unset, cache untouched).

    ``registry`` is accepted for call-site symmetry; the event listener
    always resolves the process-global registry at event time (events
    fire long after this call, possibly under a different registry in
    tests).
    """
    cache_dir = os.environ.get("PTPU_COMPILE_CACHE_DIR", "").strip()
    if not cache_dir:
        return None
    with _lock:
        if _state["configured"]:
            return _state["dir"]
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # persist everything: the default floors (compile time / entry
        # size) silently skip small programs, which breaks the
        # warm-start contract for smoke-sized workloads
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:  # noqa: swallow
            pass  # knob absent on older jax: compile-time floor suffices
        # jax latches a cache-used decision on the process's FIRST
        # compile (is_cache_used sets _cache_checked); any eager op
        # before this call — model construction, pt.seed — freezes the
        # cache OFF for the process even though the config above lands.
        # reset_cache() clears the latch; the cache re-initializes
        # lazily from the config on the next compile.
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:  # noqa: swallow
            pass  # latch absent on this jax: config alone suffices
        if not _state["listener"]:
            try:
                from jax._src import monitoring
                monitoring.register_event_listener(_listener)
                _state["listener"] = True
            except Exception:  # noqa: swallow
                pass  # cache still works; only the hit counters go dark
        _state["configured"] = True
        _state["dir"] = cache_dir
        return cache_dir


def reset_for_tests() -> None:
    """Forget the configured state so a test can re-enable with a fresh
    dir.  Does not unregister the jax listener (jax keeps listeners for
    the process lifetime); re-enabling is still idempotent.

    Also undoes the jax-side config when we had enabled it: leaving
    ``jax_compilation_cache_dir`` latched bleeds disk-cache warm starts
    into every later compile in the process — concretely, a test that
    enabled the cache made the doctor-e2e straggler drill misattribute
    the slow worker (worker 0 paid cold compiles, worker 1 got warm
    hits and outran its injected delay)."""
    with _lock:
        was_enabled = _state["configured"] and _state["dir"]
        _state["configured"] = False
        _state["dir"] = None
        if not was_enabled:
            return
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:  # noqa: swallow
            pass  # knob absent on older jax
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:  # noqa: swallow
            pass  # no latch to clear on this jax
